"""GridSearchCV / RandomizedSearchCV — the flagship feature.

Drop-in replacements for the reference's `spark_sklearn.GridSearchCV(sc,
estimator, param_grid)` (reference: python/spark_sklearn/grid_search.py) and
for sklearn's own search estimators.  API compatibility notes:

  - ``GridSearchCV(estimator, param_grid, ...)`` — sklearn-style; ALSO
    accepts the reference's legacy ``GridSearchCV(sc, estimator, param_grid)``
    calling convention: if the first positional argument has no
    ``get_params``, it is treated as a legacy Spark context and ignored (the
    mesh plays its role).
  - ``cv_results_`` schema matches sklearn's `_format_results`
    (sklearn/model_selection/_search.py:1208-1290): `params`, masked
    `param_*` arrays, `split{i}_test_*`, `mean/std/rank_test_*`,
    `mean/std_fit_time`, `mean/std_score_time`, optional train scores.
  - `best_index_/best_params_/best_score_/best_estimator_/refit_time_`,
    `multimetric_`, `n_splits_`, `scorer_` follow sklearn
    (_search.py:1148-1202).

Execution: two tiers (SURVEY §7.0).
  Tier A (compiled): estimator family recognised by the registry -> the
    (candidates x folds) grid becomes nested `vmap` axes of one jitted
    program per compile group, sharded over the mesh "task" axis; the dataset
    is device_put replicated (the TPU-native `sc.broadcast`).
  Tier B (host): any other estimator -> real `clone(est).set_params(**p)
    .fit(...)` via sklearn `_fit_and_score` fanned out with joblib — full
    sklearn generality, exactly the reference's per-task semantics.
"""

from __future__ import annotations

import numbers
import threading
import time
import warnings
from collections import OrderedDict, defaultdict
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from sklearn.base import BaseEstimator, MetaEstimatorMixin, clone, is_classifier
from sklearn.model_selection import ParameterGrid, ParameterSampler, check_cv
from sklearn.utils import Bunch
from sklearn.utils.metadata_routing import (
    MetadataRouter,
    MethodMapping,
    _raise_for_params,
    _routing_enabled,
    process_routing,
)
from sklearn.utils.metaestimators import available_if
from sklearn.utils.validation import _check_method_params, check_is_fitted

from spark_sklearn_tpu.models.base import resolve_family
from spark_sklearn_tpu.parallel import mesh as mesh_lib
from spark_sklearn_tpu.parallel import ownership as _ownership
from spark_sklearn_tpu.parallel.mesh import TpuConfig, build_mesh
from spark_sklearn_tpu.parallel.taskgrid import build_compile_groups
from spark_sklearn_tpu.search.scorers import (
    BINARY_ONLY_SCORERS,
    CLASSIFICATION_SCORERS,
    build_view,
    resolve_scoring,
)
from spark_sklearn_tpu.utils import keycheck as _keycheck
from spark_sklearn_tpu.utils.locks import named_lock, named_rlock
from spark_sklearn_tpu.utils.native import fold_masks
from spark_sklearn_tpu.obs import telemetry as _telemetry
from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.metrics import search_registry
from spark_sklearn_tpu.obs.trace import get_tracer, search_tracing
from spark_sklearn_tpu.parallel import faults as _faults


import contextlib as _contextlib

logger = get_logger("spark_sklearn_tpu.search")
_nullcontext = _contextlib.nullcontext


def _freeze(obj):
    """Strict hashable view for program-cache keys (shared helper in
    parallel/taskgrid.py); raises TypeError for unkeyable values."""
    from spark_sklearn_tpu.parallel.taskgrid import freeze
    return freeze(obj, strict=True)


#: cross-search cache of jitted callables, LRU-ordered (oldest first).
#: Values are (callable, family_tag); jitted callables pin XLA executables
#: and device constants, so the bound is per-family as well as global — a
#: long-lived process cycling many shapes of ONE family can at worst evict
#: its own older programs, never another family's entire working set.
#: CONCURRENT searches (serve/executor.py) hit this cache from several
#: worker threads, so every read-modify-write runs under the rlock;
#: program construction itself stays outside it (builds may take the
#: programstore's own locks, and two racing builders just keep the
#: first-inserted program).
_PROGRAM_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
_PROGRAM_CACHE_MAX = 128
_PROGRAM_CACHE_MAX_PER_FAMILY = 32
_PROGRAM_CACHE_FAMILY_COUNTS: Dict[Any, int] = defaultdict(int)
_PROGRAM_CACHE_LOCK = named_rlock("grid._PROGRAM_CACHE_LOCK")


def _cache_evict(fam=None):
    """Drop the least-recently-used entry (of `fam` if given, else global)."""
    with _PROGRAM_CACHE_LOCK:
        victim = None
        if fam is not None:
            victim = next((k for k, (_, f) in _PROGRAM_CACHE.items()
                           if f == fam), None)
        if victim is None:
            victim = next(iter(_PROGRAM_CACHE))
        _, vfam = _PROGRAM_CACHE.pop(victim)
        _PROGRAM_CACHE_FAMILY_COUNTS[vfam] -= 1
        if _PROGRAM_CACHE_FAMILY_COUNTS[vfam] <= 0:
            del _PROGRAM_CACHE_FAMILY_COUNTS[vfam]
#: launches per compile group under convergence-sorted chunking — enough
#: grading that easy launches early-exit well below max_iter, few enough
#: that each launch stays matmul-wide
_SORTED_LAUNCHES = 8


def _cached_program(key, build, store_parts=None, store=None,
                    check_fields=None):
    """Cross-search cache of jitted callables.

    The fit/score programs are built from per-search closures, so without
    this every search re-traces and re-lowers programs jax has already
    compiled (~0.7 s per search at bench scale even with a warm persistent
    compile cache — the XLA binary is cached, the python->jaxpr->HLO walk
    is not).  Keyed by everything the closures capture; jax.jit's own
    cache below handles shapes/dtypes.  Unkeyable captures (e.g. a fresh
    user lambda) just skip the cache.

    Eviction is LRU with per-family program accounting (keys are
    ("fit"|"score"|..., family, ...) tuples): a family at its cap evicts
    its own LRU entry, the global cap evicts the overall LRU entry.

    ``check_fields`` names the call site's EFFECTIVE trace inputs (the
    config-derived values that alter what ``build`` traces) for the
    ``SST_KEYCHECK=1`` runtime recorder (utils/keycheck.py): each must
    flow into ``key``, so two calls agreeing on the key but disagreeing
    on a field are two distinct traced artifacts aliasing one cache
    slot — reported as a key collision by the conftest hook.

    ``store_parts`` (a deterministic ``(kind, family_name, *structure)``
    tuple) additionally routes the program through ``store`` — THIS
    SEARCH's persistent AOT store (parallel/programstore.py), resolved
    by the caller from its own config so a store-less search never
    consults a store some earlier search activated: the cached value
    becomes a :class:`~spark_sklearn_tpu.parallel.programstore.
    StoredProgram` that resolves serialized artifacts instead of
    re-tracing, and ``n_compiles`` then counts signatures that actually
    traced (store misses) rather than cache builds.
    """
    if store_parts is None:
        store = None
    try:
        k = _freeze(key)
    except TypeError:
        _count_build()
        return build()
    if store is not None:
        # store-backed and plain programs are distinct cache residents:
        # a later store-less search must not consult the store through
        # a stale proxy (nor the reverse)
        k = (k, "__programstore__", store.directory)
    _keycheck.note(
        "program_cache", k, fields=check_fields,
        detail=str(key[0]) if isinstance(key, tuple) and key else "")
    with _PROGRAM_CACHE_LOCK:
        hit = _PROGRAM_CACHE.get(k)
        if hit is not None:
            _PROGRAM_CACHE.move_to_end(k)
    if hit is not None:
        if store is not None:
            # a deactivate/re-activate cycle minted a fresh store
            # object for the same directory: repoint the cached proxy
            # so traffic lands on the store whose counters/manifest
            # this search reports (outside the cache lock: rebind
            # takes the store's own)
            rebind = getattr(hit[0], "rebind", None)
            if rebind is not None:
                rebind(store)
        return hit[0]
    fam = key[1] if isinstance(key, tuple) and len(key) > 1 else None
    # build OUTSIDE the lock: tracing/wrapping may take programstore
    # locks, and a slow build must not stall every concurrent search's
    # cache lookups.  Two racing builders of the same key are benign —
    # the first insert wins below and the loser's identical program is
    # dropped (its _count_build still ran: both really traced).
    fn = build()
    if store is not None:
        from spark_sklearn_tpu.parallel import programstore as _ps
        wrapped = _ps.maybe_wrap(fn, store, store_parts,
                                 on_trace=_count_build)
        if wrapped is fn:     # store-unkeyable: legacy accounting
            _count_build()
        fn = wrapped
    else:
        _count_build()
    with _PROGRAM_CACHE_LOCK:
        raced = _PROGRAM_CACHE.get(k)
        if raced is not None:
            _PROGRAM_CACHE.move_to_end(k)
            return raced[0]
        if _PROGRAM_CACHE_FAMILY_COUNTS.get(fam, 0) >= \
                _PROGRAM_CACHE_MAX_PER_FAMILY:
            _cache_evict(fam)
        elif len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_MAX:
            _cache_evict()
        _PROGRAM_CACHE[k] = (fn, fam)
        _PROGRAM_CACHE_FAMILY_COUNTS[fam] += 1
    return fn


#: count of traced-program constructions (program-cache misses; with a
#: program store active, store-resolution misses) — the search_report's
#: n_compiles.  Store resolution may run on the compile thread while
#: the dispatch thread builds, hence the lock.
_PROGRAM_BUILDS = 0
_BUILDS_LOCK = named_lock("grid._BUILDS_LOCK")


def _count_build() -> None:
    global _PROGRAM_BUILDS
    with _BUILDS_LOCK:
        _PROGRAM_BUILDS += 1


def _program_build_count() -> int:
    with _BUILDS_LOCK:
        return _PROGRAM_BUILDS


@jax.jit
def _models_health(models):
    """(nc_batch, n_folds) True where any inexact model leaf went NaN —
    the compiled-tier analog of est.fit raising.  inf is NOT flagged:
    families use inf sentinels legitimately (e.g. tree split
    thresholds)."""
    bad = None
    for leaf in jax.tree_util.tree_leaves(models):
        if not jnp.issubdtype(leaf.dtype, jnp.inexact):
            continue
        b = jnp.isnan(leaf).any(axis=tuple(range(2, leaf.ndim)))
        bad = b if bad is None else (bad | b)
    return bad


def chunkloop_block(state, *, mode="per_chunk", enabled=False,
                    score_attribution="calibrated"):
    """Normalize the ``search_report["chunkloop"]`` block in place
    (schema pinned in ``obs.metrics.CHUNKLOOP_BLOCK_SCHEMA``).

    The state dict is the registry's own ``metrics.struct("chunkloop")``
    object, so the scan-path finalizers (and halving's elimination
    accounting) mutate the same dict this function returns — a halving
    search's rungs accumulate into one whole-search block.  Emitted for
    BOTH loop modes: a per-chunk search reports the zeroed
    ``enabled=False`` shape, so the report schema never changes.
    """
    defaults = {
        "mode": mode,
        "enabled": bool(enabled),
        "n_segments": 0,
        "n_chunks_scanned": 0,
        "n_launches_saved": 0,
        "segment_lengths": [],
        "fallbacks": [],
        "rung_topk_device": 0,
        "rung_topk_host": 0,
        "score_attribution": score_attribution,
    }
    for k, v in defaults.items():
        state.setdefault(k, v)
    state["mode"] = mode
    state["enabled"] = bool(enabled)
    state["score_attribution"] = score_attribution
    return state


def _looks_like_estimator(obj) -> bool:
    return hasattr(obj, "get_params") and (
        hasattr(obj, "fit") or hasattr(obj, "predict"))


def _is_multimetric(scorer_names) -> bool:
    return not (len(scorer_names) == 1 and scorer_names[0] == "score")



def _check_refit(search_cv, attr):
    if not search_cv.refit:
        raise AttributeError(
            f"This {type(search_cv).__name__} instance was initialized with "
            f"`refit=False`. {attr} is available only after refitting on "
            "the best parameters. You can refit an estimator manually "
            "using the `best_params_` attribute")


def _search_estimator_has(attr):
    """sklearn's delegation check (_search.py:368): method availability
    mirrors the (best_)estimator and the refit flag."""

    def check(self):
        _check_refit(self, attr)
        if hasattr(self, "best_estimator_"):
            getattr(self.best_estimator_, attr)
            return True
        getattr(self.estimator, attr)
        return True

    return check


try:
    from sklearn.callback import CallbackSupportMixin
    from sklearn.callback._callback_support import (
        callback_management_context)
except ImportError:
    # installed sklearn predates (or dropped) the callback module — run
    # with inert stand-ins so the search works identically minus hooks
    class _NullCallbackContext:
        def subcontext(self, *args, **kwargs):
            return self

        def call_on_fit_task_begin(self, **kwargs):
            return self

        def call_on_fit_task_end(self, **kwargs):
            return None

        def propagate_callback_context(self, estimator):
            return _nullcontext()

    class CallbackSupportMixin:  # type: ignore[no-redef]
        def _init_callback_context(self, max_subtasks=None):
            return _NullCallbackContext()

    def callback_management_context(estimator):
        return _nullcontext()


class BaseSearchTPU(CallbackSupportMixin, MetaEstimatorMixin, BaseEstimator):
    """Shared engine: candidate generation is the only subclass hook
    (`_get_candidates`), mirroring sklearn's `_run_search` split
    (_search.py:1708/2109).  Callback support follows sklearn's task tree:
    root -> search -> candidate-split-evaluation leaves, plus a
    refit-with-best-params task (sklearn callback module contract)."""

    def __init__(self, estimator, *, scoring=None, n_jobs=None, refit=True,
                 cv=None, verbose=0, error_score=np.nan,
                 return_train_score=False, backend=None,
                 config: Optional[TpuConfig] = None):
        self.estimator = estimator
        self.scoring = scoring
        self.n_jobs = n_jobs
        self.refit = refit
        self.cv = cv
        self.verbose = verbose
        self.error_score = error_score
        self.return_train_score = return_train_score
        self.backend = backend          # None=auto, "tpu"=compiled, "host"
        self.config = config


    @property
    def search_report(self):
        """Per-search execution report (backend, compile groups, launches,
        fit/score wall).  Stored privately so fit() only adds underscore-
        prefixed/suffixed attributes, per sklearn's estimator checks.

        The report is the rendered view of a typed metrics registry —
        its full schema (every key, kind and meaning) is pinned in
        ``spark_sklearn_tpu.obs.metrics.SEARCH_REPORT_SCHEMA`` and
        rendered into ``docs/API.md``.

        Compiled searches additionally carry ``report["pipeline"]`` — the
        chunk scheduler's timeline (parallel/pipeline.py):

          - ``depth``: the pipeline depth the search ran at (0 = the
            synchronous escape hatch);
          - ``launches``: one record per device launch with its
            ``kind`` (fit/score/calibrate/fused) and per-phase walls
            (``stage_s``/``dispatch_s``/``compute_s``/``gather_s``/
            ``finalize_s``);
          - ``stage_wall_s``/``dispatch_wall_s``/``compute_wall_s``/
            ``gather_wall_s``/``finalize_wall_s``: the per-phase sums,
            and ``wall_s`` the run's actual wall — their gap is the
            ``overlap_frac`` (host work hidden behind device compute);
          - ``n_compiles``/``n_precompiled``: how many programs were
            traced this search, and how many of those the compile-ahead
            thread AOT-compiled;
          - ``persistent_cache_hits``/``persistent_cache_misses``: the
            persistent compilation cache's traffic during this search
            (nonzero hits = a previous process already paid the
            compile; see TpuConfig.compilation_cache_dir).
        """
        if not hasattr(self, "_search_report"):
            from sklearn.exceptions import NotFittedError

            # NotFittedError subclasses AttributeError, so hasattr()
            # and legacy `except AttributeError` callers keep working
            raise NotFittedError(
                f"This {type(self).__name__} instance is not fitted yet; "
                "search_report is set by fit(). Call 'fit' with "
                "appropriate arguments first.")
        return self._search_report

    # -- candidate generation -------------------------------------------
    def _get_candidates(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def _run_search(self, evaluate_candidates, *, callback_ctx=None):
        """sklearn's extension point (_search.py:1040-1134): subclasses may
        call `evaluate_candidates` any number of times with any candidate
        batches (e.g. successive-halving-style searches); each call returns
        `cv_results_`-shaped results for everything evaluated so far."""
        candidates = self._get_candidates()
        if callback_ctx is None:
            evaluate_candidates(candidates)
            return
        search_ctx = callback_ctx.subcontext(
            task_name="search",
            max_subtasks=len(candidates) * self.n_splits_,
            sequential_subtasks=False,
        ).call_on_fit_task_begin(estimator=self)
        evaluate_candidates(candidates, callback_ctx=search_ctx)
        search_ctx.call_on_fit_task_end(estimator=self)

    # -- sklearn plumbing -----------------------------------------------
    def _check_refit_for_multimetric(self, scorer_names):
        if self.refit is not False and (
            not isinstance(self.refit, str) or self.refit not in scorer_names
        ) and not callable(self.refit):
            # sklearn's exact phrasing (_search.py _check_refit_for_...)
            raise ValueError(
                "For multi-metric scoring, the parameter refit must be set "
                "to a scorer key or a callable to refit an estimator with "
                f"the best parameter setting on the whole data and make the "
                f"best_* attributes available for that metric. If this is "
                f"not needed, refit should be set to False explicitly. "
                f"{self.refit!r} was passed.")

    # -- metadata routing (sklearn 1.4+ contract; installed
    # _search.py get_metadata_routing/_get_routed_params_for_fit) --------
    def _get_scorers(self):
        """sklearn-facing scorer objects, used for routing decisions and
        as `scorer_` (the compiled tier resolves its own device scorers
        separately)."""
        from sklearn.metrics import check_scoring
        from sklearn.metrics._scorer import (
            _check_multimetric_scoring, _MultimetricScorer)

        if callable(self.scoring):
            return self.scoring
        if self.scoring is None or isinstance(self.scoring, str):
            return check_scoring(self.estimator, self.scoring)
        scorers = _check_multimetric_scoring(self.estimator, self.scoring)
        return _MultimetricScorer(
            scorers=scorers, raise_exc=(self.error_score == "raise"))

    def _check_scorers_accept_sample_weight(self):
        """Warn per scorer that cannot consume sample_weight (sklearn's
        pre-routing forwarding rule) and return whether any can."""
        from inspect import signature

        from sklearn.metrics._scorer import _MultimetricScorer

        scorers = self._get_scorers()
        if isinstance(scorers, _MultimetricScorer):
            for name, scorer in scorers._scorers.items():
                if not scorer._accept_sample_weight():
                    warnings.warn(
                        f"The scoring {name}={scorer} does not support "
                        "sample_weight, which may lead to statistically "
                        f"incorrect results when fitting {self} with "
                        "sample_weight. ")
            return scorers._accept_sample_weight()
        if hasattr(scorers, "_accept_sample_weight"):
            accept = scorers._accept_sample_weight()
        else:
            accept = "sample_weight" in signature(scorers).parameters
        if not accept:
            warnings.warn(
                f"The scoring {scorers} does not support sample_weight, "
                "which may lead to statistically incorrect results when "
                f"fitting {self} with sample_weight. ")
        return accept

    def _get_routed_params_for_fit(self, params):
        if _routing_enabled():
            return process_routing(self, "fit", **params)
        params = params.copy()
        groups = params.pop("groups", None)
        routed_params = Bunch(
            estimator=Bunch(fit=params),
            splitter=Bunch(split={"groups": groups}),
            scorer=Bunch(score={}),
        )
        # pre-routing rule: sample_weight forwards to the scorer(s) when
        # present and accepted (any scorer, for multimetric)
        if (params.get("sample_weight") is not None
                and self._check_scorers_accept_sample_weight()):
            routed_params.scorer.score["sample_weight"] = \
                params["sample_weight"]
        return routed_params

    def get_metadata_routing(self):
        router = MetadataRouter(owner=self)
        router.add(
            estimator=self.estimator,
            method_mapping=MethodMapping().add(caller="fit", callee="fit"),
        )
        router.add(
            scorer=self._get_scorers(),
            method_mapping=MethodMapping()
            .add(caller="score", callee="score")
            .add(caller="fit", callee="score"),
        )
        router.add(
            splitter=self.cv,
            method_mapping=MethodMapping().add(caller="fit", callee="split"),
        )
        return router

    def fit(self, X, y=None, **params):
        # a session-attached search (TpuSession.attach) is sugar for
        # submit + wait: fit routes through the session's fair-share
        # executor, sharing the device with any concurrently-submitted
        # searches.  Inside an executor worker (current_binding set)
        # this IS the submitted fit, so it runs the real path below —
        # unattached searches are untouched.
        session = getattr(self, "_sst_session", None)
        if session is not None:
            from spark_sklearn_tpu import serve as _serve
            if _serve.current_binding() is None:
                return session.submit(self, X, y, **params).result()
        # teardown of attached callbacks is guaranteed even when fit
        # raises (sklearn wraps fit the same way via _fit_context)
        with callback_management_context(self):
            # span tracing scoped to this search: recording only when
            # TpuConfig(trace=...)/SST_TRACE asks; exact no-op otherwise
            with search_tracing(self.config) as tracer:
                with tracer.span(
                        "search.fit", search=type(self).__name__,
                        estimator=type(self.estimator).__name__):
                    return self._fit_impl(X, y, params)

    def _fit_impl(self, X, y, params):
        estimator = self.estimator
        if self.scoring is None and not hasattr(estimator, "score"):
            # sklearn validates this before any work (BaseSearchCV.fit)
            raise TypeError(
                "If no scoring is specified, the estimator passed should "
                f"have a 'score' method. The estimator {estimator!r} "
                "does not.")
        # multimetric refit misconfiguration must fail BEFORE any other
        # work — even cv validation (sklearn's ordering)
        if isinstance(self.scoring, (list, tuple, set, dict)):
            self._check_refit_for_multimetric(
                list(self.scoring.keys())
                if isinstance(self.scoring, dict) else list(self.scoring))

        cv = check_cv(self.cv, y, classifier=is_classifier(estimator))
        from spark_sklearn_tpu.sparse.csr import CSRMatrix
        if isinstance(X, CSRMatrix):
            X = X.to_scipy()  # splitters/refit understand scipy CSR
        else:
            import scipy.sparse as _sp
            if _sp.issparse(X) and X.format not in ("csr", "csc"):
                X = X.tocsr()  # COO/DOK are not sliceable by fold indices
        X_arr = X if hasattr(X, "shape") else np.asarray(X)

        params = _check_method_params(X, params=params)
        routed_params = self._get_routed_params_for_fit(params)

        sw_meta = params.get("sample_weight")
        metadata_callbacks = ({"sample_weight": sw_meta}
                              if sw_meta is not None else None)
        root_callback_ctx = self._init_callback_context(
            max_subtasks=1 + (self.refit is not False)
        ).call_on_fit_task_begin(
            estimator=self, X=X, y=y, metadata=metadata_callbacks)

        splits = list(cv.split(X_arr, y, **routed_params.splitter.split))
        self.n_splits_ = len(splits)
        if hasattr(cv, "get_n_splits"):
            expected_n_splits = cv.get_n_splits(
                X_arr, y, **routed_params.splitter.split)
            if expected_n_splits != self.n_splits_:
                raise ValueError(
                    "cv.split and cv.get_n_splits return "
                    f"inconsistent results. Expected {expected_n_splits} "
                    f"splits, got {self.n_splits_}")

        family = None if self.backend == "host" else resolve_family(estimator)
        use_compiled = family is not None
        # groups is fine on the compiled path: the splits above already
        # encode it and only fold masks reach the device.  sample_weight is
        # too: it is one multiply into the fold masks.  Any OTHER fit/score
        # param is an arbitrary kwarg that cannot enter a traced fit.
        est_fit_params = dict(routed_params.estimator.fit)
        score_params = dict(routed_params.scorer.score)
        fit_weight = est_fit_params.get("sample_weight")
        score_weight = score_params.get("sample_weight")
        unsupported_compiled = (
            {k for k, v in est_fit_params.items()
             if k != "sample_weight" and v is not None}
            | {k for k, v in score_params.items()
               if k != "sample_weight" and v is not None})
        if use_compiled and fit_weight is not None and \
                getattr(estimator, "class_weight", None) == "balanced" \
                and np.any(np.asarray(fit_weight) == 0):
            # sklearn's balanced counts are unweighted bincounts over ALL
            # train-fold rows; the compiled tier derives them from the
            # weighted mask's support, which drops zero-weight rows ->
            # reproduce sklearn on the host instead
            unsupported_compiled = unsupported_compiled | {"sample_weight"}
        if use_compiled and fit_weight is not None and not getattr(
                family, "accepts_sample_weight", True):
            # e.g. Pipelines: sklearn raises on a bare sample_weight (step
            # routing wants "step__sample_weight") — the host path
            # reproduces that contract
            unsupported_compiled = unsupported_compiled | {"sample_weight"}
        if use_compiled and unsupported_compiled:
            if self.backend == "tpu":
                raise ValueError(
                    f"fit/score params {sorted(unsupported_compiled)} are "
                    "not supported on the compiled path; use backend='host'")
            use_compiled = False
        if use_compiled:
            try:
                resolve_scoring(self.scoring, family)
            except (KeyError, TypeError):
                if self.backend == "tpu":
                    raise
                use_compiled = False

        # sklearn's extension point (_search.py evaluate_candidates):
        # _run_search may call evaluate_candidates several times; batches
        # accumulate and each call returns the results-so-far
        acc: Dict[str, Any] = {
            "params": [], "test": None, "train": None,
            "fit_t": [], "score_t": [], "names": None, "results": None,
            "more": {}}

        state = {"use_compiled": use_compiled}

        def _compact_for_rung(splits_used):
            """Row-compact the dataset to the union of a halving rung's
            subsampled fold indices (compiled tier only).

            The fold-mask machinery makes a subsampled rung CORRECT by
            zero-weighting the unused rows, but zero-weight rows still
            multiply — rung 0 of a 1797-row search at n_resources=40
            would pay full-dataset matmuls for every lane.  Slicing
            X/y (and the weights) to the rows any fold actually uses,
            with the split indices remapped, makes the rung's compute
            proportional to its resource; every used row keeps its
            exact value, so the per-cell scores are the same
            computation on the same rows.  Returns None when
            compaction cannot apply (exotic X containers, nothing to
            drop, or a subsample that lost an entire class — the
            compiled class structure must match the full dataset's)."""
            import scipy.sparse as _sp
            if not (isinstance(X_arr, np.ndarray) or _sp.issparse(X_arr)):
                return None
            used = np.unique(np.concatenate(
                [np.concatenate([np.asarray(tr), np.asarray(te)])
                 for tr, te in splits_used]))
            if used.size == 0 or used.size >= X_arr.shape[0]:
                return None
            y_arr = None if y is None else np.asarray(y)
            y_sub = None if y_arr is None else y_arr[used]
            if y_arr is not None and is_classifier(self.estimator) \
                    and np.unique(y_sub).size != np.unique(y_arr).size:
                return None
            splits_c = [(np.searchsorted(used, np.asarray(tr)),
                         np.searchsorted(used, np.asarray(te)))
                        for tr, te in splits_used]
            fw = None if fit_weight is None \
                else np.asarray(fit_weight)[used]
            sw = None if score_weight is None \
                else np.asarray(score_weight)[used]
            return X_arr[used], y_sub, splits_c, fw, sw

        def _dispatch(cands, eval_ctxs, splits_used, rung_compact=False):
            if self.n_splits_ == 0:
                raise ValueError(
                    "No fits were performed. "
                    "Was the CV iterator empty? "
                    "Were there no candidates?")
            if state["use_compiled"]:
                try:
                    X_c, y_c, splits_c = X_arr, y, splits_used
                    fw_c, sw_c = fit_weight, score_weight
                    if rung_compact:
                        sub = _compact_for_rung(splits_used)
                        if sub is not None:
                            X_c, y_c, splits_c, fw_c, sw_c = sub
                    return self._fit_compiled(
                        family, X_c, y_c, cands, splits_c,
                        fit_weight=fw_c, score_weight=sw_c,
                        eval_ctxs=eval_ctxs)
                except (KeyboardInterrupt, SystemExit):
                    # an interactive abort / interpreter shutdown must
                    # never be traded for a silent host re-run of the
                    # whole grid (narrowed guard; Exception below never
                    # caught these, but the contract is now explicit and
                    # pinned by test)
                    raise
                except Exception as exc:  # unsupported static combo etc.
                    if self.backend == "tpu" or \
                            getattr(exc, "_sst_no_fallback", False):
                        # _sst_no_fallback: error_score='raise' with
                        # invalid candidate params (or a watchdog
                        # LaunchTimeoutError — a hung device would only
                        # wedge the host re-run's next compiled search)
                        # — sklearn raises this exact exception; a host
                        # re-run would only repeat the failure after
                        # redundant work
                        raise
                    state["use_compiled"] = False  # fall back ONCE
                    # recorded into the host report's faults block so
                    # the fallback cause stays observable after the
                    # compiled registry is replaced
                    state["fallback_exc"] = exc
                    warnings.warn(
                        f"compiled search path failed ({exc!r}); falling "
                        "back to the host backend", UserWarning)
            # the host path receives the CALLER's X (list, sparse, frame —
            # sklearn estimators may validate its exact type); only the
            # compiled path needs the dense array form
            return self._fit_host(X, y, cands, splits_used, est_fit_params,
                                  score_params, eval_ctxs,
                                  fallback_exc=state.pop(
                                      "fallback_exc", None))

        def evaluate_candidates(candidate_params, cv=None,
                                more_results=None, callback_ctx=None):
            # sklearn's full evaluate_candidates contract
            # (_search.py:829): a subclass `_run_search` (successive
            # halving) may pass a per-call cv — the rung's subsample
            # splitter — and extra result columns (`iter`,
            # `n_resources`) that accumulate into cv_results_.  The
            # parameter deliberately shadows the outer validated cv.
            cands = list(candidate_params)
            if cv is None:
                splits_used = splits
            else:
                splits_used = list(cv.split(
                    X_arr, y, **routed_params.splitter.split))
                if len(splits_used) != self.n_splits_:
                    raise ValueError(
                        f"the per-call cv yielded {len(splits_used)} "
                        f"splits, expected {self.n_splits_}")
            if self.verbose > 0:
                # structured logger, stdout-parity channel: the line is
                # byte-for-byte sklearn's (BaseSearchCV.fit)
                logger.print(
                    f"Fitting {self.n_splits_} folds for each of "
                    f"{len(cands)} candidates, totalling "
                    f"{self.n_splits_ * len(cands)} fits",
                    n_splits=self.n_splits_, n_candidates=len(cands))
            if not cands:
                if not acc["params"]:
                    raise ValueError(
                        "No fits were performed. "
                        "Was the CV iterator empty? "
                        "Were there no candidates?")
                return acc["results"]
            # one leaf context per (candidate, split) pair, candidate-major
            # like the task list (sklearn: "candidate-split-evaluation").
            # Only allocated when callbacks are attached: a 10k-candidate
            # grid must not build 50k context objects for nobody.
            if callback_ctx is not None and \
                    getattr(self, "_skl_callbacks", None):
                eval_ctxs = [
                    callback_ctx.subcontext(
                        task_name="candidate-split-evaluation",
                        task_id=tid)
                    for tid in range(len(cands) * self.n_splits_)]
            else:
                eval_ctxs = None
            (test_scores, train_scores, fit_times, score_times,
             scorer_names, scorer_attr) = _dispatch(
                cands, eval_ctxs, splits_used,
                # a per-call cv is a halving rung's subsample: compact
                # the compiled tier's rows to what the rung uses (the
                # host tier always receives the caller's full X)
                rung_compact=cv is not None)
            if acc["names"] is None:
                acc["names"] = scorer_names
                acc["test"] = {s: [] for s in scorer_names}
                acc["train"] = ({s: [] for s in scorer_names}
                                if self.return_train_score else None)
                self.scorer_ = scorer_attr
            elif scorer_names != acc["names"]:
                raise ValueError(
                    f"inconsistent scorer names across evaluate_candidates "
                    f"calls: {scorer_names} vs {acc['names']}")
            acc["params"].extend(cands)
            for s in scorer_names:
                acc["test"][s].append(test_scores[s])
                if self.return_train_score:
                    acc["train"][s].append(train_scores[s])
            acc["fit_t"].append(fit_times)
            acc["score_t"].append(score_times)
            if more_results:
                for k, v in more_results.items():
                    acc["more"].setdefault(k, []).extend(v)
            acc["results"] = self._format_results(
                acc["params"],
                {s: np.concatenate(v) for s, v in acc["test"].items()},
                ({s: np.concatenate(v) for s, v in acc["train"].items()}
                 if self.return_train_score else None),
                np.concatenate(acc["fit_t"]),
                np.concatenate(acc["score_t"]), acc["names"],
                more_results=acc["more"])
            return acc["results"]

        from inspect import signature as _signature
        # the search doctor's wall: timed around the WHOLE candidate
        # loop (every rung for halving), so host orchestration the
        # pipeline never sees is attributable too
        _doctor_t0 = time.perf_counter()
        if "callback_ctx" in _signature(self._run_search).parameters:
            self._run_search(evaluate_candidates,
                             callback_ctx=root_callback_ctx)
        else:
            # custom subclasses predating the callback API
            self._run_search(evaluate_candidates)
        _doctor_wall = time.perf_counter() - _doctor_t0
        # critical-path attribution + run-log sentinel (exact no-op
        # when attribution=False or on the host tier)
        self._doctor_finalize(
            _doctor_wall, _doctor_t0,
            family_name=(family.name if family is not None
                         else type(estimator).__name__),
            structure_parts=(
                type(estimator).__name__, len(acc["params"]),
                self.n_splits_, tuple(getattr(X_arr, "shape", ())),
                str(getattr(self.config, "dtype", ""))))

        if not acc["params"]:
            raise ValueError(
                "No fits were performed. "
                "Was the CV iterator empty? "
                "Were there no candidates?")
        scorer_names = acc["names"]
        self.multimetric_ = _is_multimetric(scorer_names)
        if self.multimetric_:
            self._check_refit_for_multimetric(scorer_names)
        # a string refit only names a metric when scoring is multimetric;
        # single-metric results are keyed "score" regardless (sklearn)

        results = acc["results"]
        self.cv_results_ = results

        refit_metric = (self.refit if self.multimetric_
                        and isinstance(self.refit, str) else "score")
        if self.refit or not self.multimetric_:
            self.best_index_ = self._select_best_index(
                self.refit, refit_metric, results)
            if not callable(self.refit):
                self.best_score_ = results[
                    f"mean_test_{refit_metric}"][self.best_index_]
            self.best_params_ = results["params"][self.best_index_]

        if self.refit:
            # Refit on the "driver", exactly like the reference
            # (grid_search.py: best_estimator_ = clone(base).set_params(
            #  **best_params).fit(X, y)); our native estimators run their own
            # compiled fit here.
            # param VALUES are cloned too, so estimator-valued grid
            # entries (e.g. {"regressor": [LinearRegression()]}) are never
            # fitted in place (sklearn _search.py:1166)
            self.best_estimator_ = clone(estimator).set_params(
                **clone(self.best_params_, safe=False))
            refit_subctx = root_callback_ctx.subcontext(
                task_name="refit-with-best-params")
            t0 = time.perf_counter()
            with refit_subctx.propagate_callback_context(
                    self.best_estimator_), \
                    get_tracer().span("refit",
                                      estimator=type(
                                          self.best_estimator_).__name__):
                refit_subctx.call_on_fit_task_begin(
                    estimator=self, X=X, y=y, metadata=metadata_callbacks)
                if y is not None:
                    self.best_estimator_.fit(
                        X, y, **routed_params.estimator.fit)
                else:
                    self.best_estimator_.fit(
                        X, **routed_params.estimator.fit)
            self.refit_time_ = time.perf_counter() - t0
            refit_subctx.call_on_fit_task_end(
                estimator=self, X=X, y=y, metadata=metadata_callbacks)
            if hasattr(self.best_estimator_, "classes_"):
                self.classes_ = self.best_estimator_.classes_
        if hasattr(X_arr, "shape") and len(getattr(X_arr, "shape", ())) == 2:
            self.n_features_in_ = X_arr.shape[1]
        root_callback_ctx.call_on_fit_task_end(
            estimator=self, X=X, y=y, metadata=metadata_callbacks)
        return self

    def _doctor_finalize(self, wall_s, t0_s, family_name,
                         structure_parts):
        """Search doctor: render ``search_report["attribution"]`` from
        the blocks the search just recorded, then let the run log
        persist the record and judge it against the stored baseline
        (``obs/attribution.py`` + ``obs/runlog.py``).

        Runs AFTER ``_run_search`` returns, so every block the
        analyzer consumes (pipeline, scheduler, faults, memory,
        geometry, halving) is already rendered.  Exact no-op when
        ``TpuConfig.attribution`` is off or the fit never reached the
        compiled tier (no pipeline timeline to decompose) — the
        report stays byte-identical to the pre-doctor shape."""
        if not getattr(self.config, "attribution", True):
            return
        metrics = getattr(self, "_search_metrics", None)
        if metrics is None or "pipeline" not in metrics.data:
            return
        from spark_sklearn_tpu.obs import attribution as _attribution
        from spark_sklearn_tpu.obs import runlog as _runlog
        tracer = get_tracer()
        # the tracer ring is process-global: clip to THIS search's
        # wall window so a previous search's compile/recovery spans
        # cannot leak into these lanes
        t1_s = t0_s + wall_s
        spans = [(name, max(a, t0_s), min(b, t1_s))
                 for name, a, b in _attribution.spans_from_tracer(
                     tracer.events())
                 if a < t1_s and b > t0_s] if len(tracer) else []
        with tracer.span("doctor.analyze", family=family_name):
            block = _attribution.attribution_block(
                metrics.data, wall_s, spans)
            metrics.put("attribution", block)
        digest = _runlog.structure_digest(family_name, *structure_parts)
        with tracer.span("doctor.sentinel", family=family_name):
            _runlog.note_run(metrics.data, family_name, digest,
                             config=self.config)
        logger.info(
            "search doctor: %s", block["verdict"],
            family=family_name, dominant=block["dominant"],
            wall_s=block["wall_s"],
            regression=block["regression"].get("status", "off"))

    @staticmethod
    def _hashable_labels(y):
        """Deterministic bytes for the checkpoint fingerprint: object-dtype
        labels would hash pointer addresses."""
        if y is None:
            return "none"
        y_arr = np.asarray(y)
        if y_arr.dtype == object:
            y_arr = y_arr.astype(str)
        return y_arr

    @staticmethod
    def _densify(X, dtype):
        """Sparse inputs reach the compiled path as dense device arrays
        (XLA has no first-class CSR; the native runtime does the threaded
        decompression — the CSRVectorUDT analog's job).  The host path
        receives sparse X unchanged, like sklearn."""
        import scipy.sparse as sp

        from spark_sklearn_tpu.utils.native import csr_to_dense

        # CSRMatrix was already converted to scipy CSR at the top of fit()
        if sp.issparse(X):
            m = X.tocsr()
            return csr_to_dense(
                m.data, m.indices, m.indptr, m.shape).astype(
                dtype, copy=False)
        return np.asarray(X)

    @staticmethod
    def _select_best_index(refit, refit_metric, results):
        if callable(refit):
            best_index = refit(results)
            if not isinstance(best_index, numbers.Integral):
                raise TypeError("best_index_ returned is not an integer")
            if best_index < 0 or best_index >= len(results["params"]):
                raise IndexError("best_index_ index out of range")
            return best_index
        return results[f"rank_test_{refit_metric}"].argmin()

    # ------------------------------------------------------------------
    # Tier A: compiled path
    # ------------------------------------------------------------------
    def _fit_compiled(self, family, X, y, candidates, splits,
                      fit_weight=None, score_weight=None, eval_ctxs=None):
        config = self.config or TpuConfig()
        if fit_weight is not None and \
                np.any(np.asarray(fit_weight) == 0):
            # 'balanced' may also arrive via the grid itself, not just the
            # estimator (the _fit_impl guard covers only the latter); the
            # compiled balanced counts come from the weighted mask's
            # support, which drops zero-weight rows sklearn would count
            if any(v == "balanced" for c in candidates for k, v in c.items()
                   if k == "class_weight" or k.endswith("__class_weight")):
                raise ValueError(
                    "class_weight='balanced' with zero-valued sample "
                    "weights is not compiled; use backend='host'")
        out = self._fit_compiled_dispatch(
            family, X, y, candidates, splits, config,
            fit_weight=fit_weight, score_weight=score_weight)
        # compiled tasks execute fused inside XLA programs, so per-task
        # hooks fire host-side AFTER the sweep succeeds (begin/end per
        # task, completion-report style — live per-task progress does not
        # exist under fusion).  Firing post-hoc also means a compiled
        # failure that falls back to the host path has fired nothing, so
        # the host tier's _fit_and_score hooks are the only ones seen.
        # X/y passed to hooks are the full replicated arrays — fold
        # slicing exists only as masks on the device.
        if eval_ctxs is not None and getattr(self, "_skl_callbacks", None):
            n_folds = len(splits)
            for t, ctx in enumerate(eval_ctxs):
                train_idx = splits[t % n_folds][0]
                md = ({"sample_weight": np.asarray(fit_weight)[train_idx]}
                      if fit_weight is not None else None)
                ctx.call_on_fit_task_begin(
                    estimator=self, X=X, y=y, metadata=md)
                ctx.call_on_fit_task_end(
                    estimator=self, X=X, y=y, metadata=md)
        return out

    def _fit_compiled_dispatch(self, family, X, y, candidates, splits,
                               config, fit_weight=None, score_weight=None):
        # closed-form linear-algebra families (ridge-type normal equations)
        # amplify f32 rounding through the Gram conditioning to ~1e-4 —
        # far from sklearn's f64 answers.  They advertise wants_float64 and
        # run under a temporarily-enabled x64 mode so sklearn parity and
        # weighted-vs-repeated equivalence hold at sklearn's own 1e-7.
        use_f64 = bool(getattr(family, "wants_float64", False)) and \
            config.dtype is None
        if not use_f64:
            return self._fit_compiled_impl(
                family, X, y, candidates, splits, config,
                fit_weight=fit_weight, score_weight=score_weight)
        prev_x64 = jax.config.jax_enable_x64
        jax.config.update("jax_enable_x64", True)
        try:
            return self._fit_compiled_impl(
                family, X, y, candidates, splits, config,
                fit_weight=fit_weight, score_weight=score_weight,
                dtype_override=np.float64)
        finally:
            jax.config.update("jax_enable_x64", prev_x64)

    def _prevalidate_candidates(self, candidates):
        """Host-side per-candidate hyperparameter validation (sklearn
        raises InvalidParameterError inside fit(); the compiled solvers
        accept any finite value, so the failure is reproduced here).

        Fast path: sklearn's ``_validate_params`` checks each declared
        param independently against the class's declarative
        ``_parameter_constraints``, so a candidate only needs its CHANGED
        keys re-checked against the owning (sub-)estimator's constraints —
        the unchanged rest was validated once on the base clone.  A
        clone-per-candidate loop (the previous implementation) costs ~1 ms
        per candidate, which at bench scale (1000 candidates) was ~25% of
        the whole warm search.  Candidates that rewire sub-estimators
        (estimator-valued values) fall back to the full clone+validate.
        """
        n_cand = len(candidates)
        failed = np.zeros(n_cand, bool)
        first_exc = None

        def validate_all(cand):
            if hasattr(cand, "_validate_params"):
                cand._validate_params()
            for sub in cand.get_params(deep=True).values():
                if hasattr(sub, "_validate_params") and \
                        hasattr(sub, "get_params"):
                    sub._validate_params()

        try:
            from sklearn.utils._param_validation import (
                validate_parameter_constraints)
        except ImportError:            # future sklearn moved it: slow path
            validate_parameter_constraints = None

        base = clone(self.estimator)
        base_exc = None
        try:
            validate_all(base)
        except Exception as exc:
            base_exc = exc
        deep = base.get_params(deep=True)

        def rewires(params):
            return any(
                hasattr(v, "get_params") or (
                    isinstance(v, (list, tuple))
                    and any(hasattr(e, "get_params") for e in v))
                for v in params.values())

        def validate_fast(params):
            """Check only the candidate's changed values against their
            owners' declarative constraints (what _validate_params does
            per key); keys are already known to exist in `deep`."""
            for k, v in params.items():
                if "__" in k:
                    prefix, bare = k.rsplit("__", 1)
                    owner = deep.get(prefix)
                else:
                    owner, bare = base, k
                constraints = getattr(owner, "_parameter_constraints", None)
                if constraints and bare in constraints:
                    validate_parameter_constraints(
                        {bare: constraints[bare]}, {bare: v},
                        caller_name=type(owner).__name__)

        for ci, params in enumerate(candidates):
            # base_exc disables the fast path entirely: a candidate may
            # OVERRIDE the base's invalid value with a valid one, which
            # only the real clone+set_params+validate can decide
            fast = validate_parameter_constraints is not None \
                and base_exc is None and not rewires(params)
            if fast and any(k not in deep for k in params):
                fast = False           # key may be unknown: let set_params
                                       # produce its own (aborting) error
            cand = None
            if not fast:
                # unknown param KEYS abort the whole search (set_params
                # raises OUTSIDE the try), exactly as before
                cand = clone(self.estimator).set_params(**params)
            exc = None
            try:
                if fast:
                    validate_fast(params)
                else:
                    validate_all(cand)
            except Exception as e:
                exc = e
            if exc is not None:
                failed[ci] = True
                if first_exc is None:
                    first_exc = exc
        return failed, first_exc

    def _fit_compiled_impl(self, family, X, y, candidates, splits, config,
                           fit_weight=None, score_weight=None,
                           dtype_override=None):
        from sklearn.metrics import check_scoring

        from spark_sklearn_tpu.parallel.pipeline import (
            enable_persistent_cache)
        enable_persistent_cache(config.resolved_cache_dir(),
                                config.persistent_cache_min_compile_s)
        # persistent AOT program store: sessionless fits activate it
        # here (a TpuSession already did at construction) — programs
        # resolve from serialized artifacts instead of re-tracing, and
        # the search publishes what it compiles for the next process
        from spark_sklearn_tpu.parallel import programstore as _programstore
        pstore = _programstore.activate_store(config)
        ps_before = _programstore.snapshot_counters(pstore)
        # successive-halving rung owner (search/halving.py, attached
        # through the launch-ownership protocol): when set, this
        # evaluate_candidates call is ONE RUNG of a multi-rung search —
        # the report registry, pipeline and counter baselines are
        # shared across rungs so the final search_report covers the
        # whole search, not the last rung
        rung = _ownership.current_owner(self, kind="rung")
        if rung is not None:
            if rung.ps_before is None:
                rung.ps_before = ps_before
            ps_before = rung.ps_before
        dtype = dtype_override or config.dtype or np.float32
        scorers, _ = resolve_scoring(self.scoring, family)
        scorer_names = list(scorers)

        # sklearn's log_loss clips probas at THEIR dtype's machine eps
        # (_classification.py log_loss), and the sklearn twin's proba
        # dtype is a per-family fact: on this sklearn nearly every
        # classifier (libsvm, forests, KNN, LogReg, the NB family)
        # produces f64 probas regardless of X dtype; only MLP and LDA
        # preserve the user's X dtype (proba_dtype_rule="input") — the
        # compiled scorer must clip where the oracle clips, not where
        # the engine's compute dtype lands (see scorers.py
        # _neg_log_loss)
        proba_rule = getattr(family, "proba_dtype_rule", "float64")
        # the dtype that matters is the one sklearn's own validation
        # would hand the estimator: float32 stays float32, EVERYTHING
        # else (float64, ints, lists, frames — check_array's numeric
        # rule) becomes float64.  Resolve it after coercion: sparse
        # matrices and ndarrays expose .dtype directly; other inputs
        # (lists, DataFrames) go through np.asarray like sklearn's
        # check_array would
        x_dt = getattr(X, "dtype", None)
        if not isinstance(x_dt, np.dtype):
            # dtype-less inputs resolve WITHOUT copying the dataset:
            # DataFrames promote their column dtypes; lists/tuples
            # resolve from their first row (a float32-ndarray row list
            # stays float32 under np.asarray, everything else becomes
            # float64 under check_array's numeric rule)
            col_dtypes = getattr(X, "dtypes", None)
            if col_dtypes is not None and len(col_dtypes):
                x_dt = np.result_type(*col_dtypes)
            elif isinstance(X, (list, tuple)) and len(X) \
                    and isinstance(X[0], np.ndarray):
                x_dt = X[0].dtype
            elif isinstance(X, (list, tuple)):
                x_dt = np.dtype(np.float64)
            else:
                x_dt = np.asarray(X).dtype
        oracle_proba_dt = np.float64 if (
            proba_rule == "float64" or x_dt != np.float32) else np.float32
        # the pre-densified X (what sklearn estimators would see): the
        # supervisor's per-candidate host fallback fits on THIS, so a
        # bisection that bottoms out reproduces sklearn exactly
        X_host = X
        # data tier (search/stream.py): "device" is the legacy resident
        # path, "stream" folds sample shards through the pipeline,
        # "sparse" keeps a scipy CSR as a device BCOO end to end
        import scipy.sparse as _scipy_sparse

        from spark_sklearn_tpu.search import stream as _stream
        data_mode = _stream.resolve_data_mode(config)
        sparse_op = None
        if data_mode == "sparse" and _scipy_sparse.issparse(X):
            if not getattr(family, "supports_sparse", False):
                raise ValueError(
                    f"data_mode='sparse' requires a family with BCOO "
                    f"fit/predict programs; {family.name} has none.  "
                    "Use data_mode='device' (densified upload) or "
                    "backend='host'.")
            if config.n_data_shards > 1:
                raise ValueError(
                    "data_mode='sparse' does not compose with "
                    "n_data_shards>1 (BCOO operands replicate only)")
            from spark_sklearn_tpu.sparse.csr import register_bcoo_export
            register_bcoo_export()
            X = X.tocsr()
            data, meta = family.prepare_data_sparse(X, y, dtype=dtype)
            sparse_op = data["X"]
        else:
            if data_mode == "stream":
                _stream.check_stream_supported(family, self.scoring,
                                               config)
            X = self._densify(X, dtype)
            data, meta = family.prepare_data(X, y, dtype=dtype)
        meta["logloss_clip_eps"] = float(np.finfo(oracle_proba_dt).eps)
        if self.scoring is not None:
            if "y" not in data:
                raise ValueError(
                    f"scoring={self.scoring!r} needs labels, but none "
                    f"reached the device ({family.name} is unsupervised: "
                    "y was absent or not numerically encodable; only its "
                    "default scorer applies)")
            from spark_sklearn_tpu.search.scorers import (
                compiled_name_for_scorer)

            def _canon(s):
                return s if isinstance(s, str) \
                    else compiled_name_for_scorer(s)
            if isinstance(self.scoring, str):
                wanted = [self.scoring]
            elif isinstance(self.scoring, dict):
                # dict values name the metrics; keys are display labels
                wanted = [_canon(s) for s in self.scoring.values()]
            elif isinstance(self.scoring, (list, tuple, set)):
                wanted = [_canon(s) for s in self.scoring]
            else:
                wanted = [_canon(self.scoring)]
            wanted = [s for s in wanted if s is not None]
            if any(s in CLASSIFICATION_SCORERS for s in wanted) and \
                    "n_classes" not in meta:
                raise ValueError(
                    f"scoring={self.scoring!r} requires a classifier "
                    f"family; {family.name} has no class structure")
            if any(s in BINARY_ONLY_SCORERS for s in wanted) and \
                    meta.get("n_classes", 2) > 2:
                # sklearn's semantics for these on multiclass (averaging
                # options, undefined-metric warnings) live on the host path
                raise ValueError(
                    f"scoring={self.scoring!r} on multiclass targets is "
                    "not compiled; use backend='host'")
        n_samples = X.shape[0]
        train_masks, test_masks = fold_masks(splits, n_samples, dtype=dtype)
        # families whose validity depends on fold geometry (e.g. KNN's
        # n_neighbors <= smallest train fold) check this in
        # observe_candidates, so both backends raise on the same grids
        meta["min_fold_train_count"] = int(
            np.sum(train_masks > 0, axis=1).min())
        n_folds = len(splits)
        n_cand = len(candidates)
        return_train = self.return_train_score

        # sample_weight enters the compiled tier as mask multiplies: the
        # estimator's weights scale the FIT masks, the scorer's weights
        # scale the SCORING masks (sklearn routes the two independently —
        # a scorer that rejects sample_weight scores unweighted even when
        # the fit was weighted)
        fit_masks = train_masks
        if fit_weight is not None:
            fw = np.asarray(fit_weight, dtype=dtype)
            if fw.shape != (n_samples,):
                raise ValueError(
                    f"sample_weight has shape {fw.shape}, expected "
                    f"({n_samples},)")
            fit_masks = train_masks * fw[None, :]
        if score_weight is not None:
            sw = np.asarray(score_weight, dtype=dtype)
            if sw.shape != (n_samples,):
                raise ValueError(
                    f"scorer sample_weight has shape {sw.shape}, expected "
                    f"({n_samples},)")
            test_sc_masks = test_masks * sw[None, :]
            train_sc_masks = train_masks * sw[None, :]
        else:
            test_sc_masks = test_masks
            train_sc_masks = train_masks
        # scorers whose sklearn twin rejects sample_weight score unweighted
        # even in a weighted search (_MultimetricScorer forwards per-scorer)
        from spark_sklearn_tpu.search.scorers import SAMPLE_WEIGHT_BLIND_FNS
        sw_blind = frozenset(
            name for name, fn in scorers.items()
            if fn in SAMPLE_WEIGHT_BLIND_FNS)
        need_unweighted = score_weight is not None and bool(sw_blind)

        base_params = family.extract_params(self.estimator)
        # sklearn raises InvalidParameterError inside fit() for
        # out-of-range hyperparameters (LinearSVC C=0, negative alpha...);
        # the compiled solvers accept any finite value, so reproduce the
        # per-candidate failure host-side BEFORE launching: invalid
        # candidates are excluded from the compiled launch entirely (a
        # static value like degree='junk' would crash tracing) and get
        # error_score on every fold with ZERO fit/score times, exactly
        # like a raising est.fit (upstream test_search_cv_timing).
        # set_params stays outside the try: unknown param KEYS abort the
        # whole search, as in sklearn.
        with get_tracer().span("prevalidate", n_candidates=len(candidates)):
            preval_failed, preval_exc = \
                self._prevalidate_candidates(candidates)
        if preval_exc is not None and isinstance(self.error_score, str) \
                and self.error_score == "raise":
            # marker consumed by _dispatch: re-raise instead of the usual
            # fall-back-to-host (sklearn raises this exact exception with
            # no fallback warning and no duplicate host work)
            preval_exc._sst_no_fallback = True
            raise preval_exc

        launch_index = None
        launch_candidates = candidates
        if preval_failed.any():
            launch_index = np.flatnonzero(~preval_failed)
            launch_candidates = [candidates[i] for i in launch_index]
        if hasattr(family, "observe_candidates"):
            # e.g. tree families need the grid-wide max n_estimators to fix
            # the compiled program's static tree count (valid candidates
            # only — an invalid static value would crash the observation)
            family.observe_candidates(launch_candidates, base_params, meta)
        dyn_names = list(family.dynamic_params)
        groups = build_compile_groups(
            launch_candidates, dyn_names, family.dynamic_params)
        if launch_index is not None:
            for g in groups:
                g.candidate_indices = launch_index[
                    np.asarray(g.candidate_indices)]

        mesh = build_mesh(config)
        n_task_shards = mesh.shape[mesh_lib.TASK_AXIS]
        logger.info(
            "compiled search: family=%s, %d candidates x %d folds, "
            "%d compile group(s), mesh=%s", family.name, n_cand, n_folds,
            len(groups), dict(mesh.shape))
        repl = mesh_lib.replicated_sharding(mesh)
        task_shard = mesh_lib.task_sharding(mesh)

        # device data plane: a fingerprint-keyed, sharding-aware LRU of
        # device arrays shared by every search in the process — X/y and
        # the fold masks upload ONCE per content+placement and are
        # reused across chunks, compile groups, calibration and
        # subsequent searches (the persistent sc.broadcast).  Disabled
        # (dataplane_bytes=0) restores per-search device_put.
        from spark_sklearn_tpu.parallel import dataplane as _dataplane
        plane = _dataplane.plane_for(config)
        dp_before = _dataplane.snapshot_counters(plane)
        if rung is not None:
            if rung.dp_before is None:
                rung.dp_before = dp_before
            dp_before = rung.dp_before
        # device-memory ledger (parallel/memledger.py): model each
        # launch's footprint from its abstract shapes, reconcile
        # against jax memory_stats at launch boundaries, cap planned
        # widths to the HBM budget and render search_report["memory"].
        # Disabled (memory_ledger=False) the report and cv_results_
        # stay byte-identical to the pre-ledger engine.
        from spark_sklearn_tpu.obs import memory as _obs_memory
        from spark_sklearn_tpu.parallel import memledger as _memledger
        ledger = _memledger.ledger_for(config)
        mem_before = _memledger.snapshot_counters(ledger)
        if ledger is not None and (rung is None or rung.itr == 0):
            mem_stats = ledger.sample(force=True)
            self._memory_ctx = {
                "groups": [],
                "resident_bytes": 0,
                "budget_bytes": _obs_memory.resolve_hbm_budget(
                    config, mem_stats),
                "device_limit_bytes": _obs_memory.
                detect_device_memory_bytes(mem_stats),
                "measured_baseline_bytes": max(
                    (r["bytes_in_use"] for r in mem_stats), default=0),
            }
        if rung is not None:
            if rung.mem_before is None:
                rung.mem_before = mem_before
            mem_before = rung.mem_before
        # in-flight heartbeats (obs/heartbeat.py): allocate ONE hub
        # scope per fit (halving rungs share it) so the report block
        # aggregates exactly this search's segments — cid_ns is empty
        # for plain fits and cannot key the hub.  Off is an exact
        # no-op: no ctx, no block, no beacon traced.
        from spark_sklearn_tpu.obs import heartbeat as _heartbeat
        _hb_enabled = _heartbeat.resolve_heartbeat(config)
        if _hb_enabled and (rung is None or rung.itr == 0):
            self._hb_ctx = {"scope": _heartbeat.get_hub().new_scope()}
        hb_ctx = getattr(self, "_hb_ctx", None) if _hb_enabled else None
        # a search submitted through a session's SearchExecutor charges
        # its broadcast residents to its tenant's data-plane quota
        from spark_sklearn_tpu import serve as _serve
        _binding = _serve.current_binding()
        _tenant = _binding.tenant if _binding is not None else None

        def _bput(v, sharding, label):
            from spark_sklearn_tpu.sparse.csr import SparseOperand
            if isinstance(v, SparseOperand):
                # a sparse operand uploads as its two nnz-proportional
                # components (each content-fingerprinted and accounted
                # separately) and reassembles the device BCOO — upload
                # bytes and plane keys price nnz, never n x d
                return v.to_bcoo(
                    values=_bput(v.values, sharding, label + ".values"),
                    indices=_bput(v.indices, sharding,
                                  label + ".indices"))
            if plane is not None:
                return plane.put(v, sharding, label=label,
                                 tenant=_tenant)
            return _dataplane.upload(v, sharding, label=label)

        _t_upload0 = time.perf_counter()
        if data_mode == "stream":
            # streaming tier: X/y and the masks stay host-side — each
            # sample shard crosses host->device on the pipeline's stage
            # thread inside run_stream, overlapped with the previous
            # shard's compute
            data_dev = {}
            fit_dev = test_dev = train_sc_dev = None
            test_unw_dev = train_unw_dev = None
        elif config.n_data_shards > 1:
            # large-X mode: shard samples over the "data" mesh axis instead
            # of replicating (the TPU-native answer to X not fitting one
            # chip's HBM) — sample-axis reductions inside the families
            # become XLA collectives over ICI automatically.  Sample counts
            # are padded to the shard count with zero-weight rows.
            from jax.sharding import NamedSharding, PartitionSpec as P
            nd = config.n_data_shards
            n_pad = mesh_lib.pad_to_multiple(n_samples, nd)
            if n_pad != n_samples:
                pad = n_pad - n_samples
                data = {k: np.concatenate(
                    [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                    for k, v in data.items()}

                def _padm(m, pad=pad):
                    return np.concatenate(
                        [m, np.zeros((n_folds, pad), m.dtype)], axis=1)
                train_sc_aliases_fit = train_sc_masks is fit_masks
                fit_masks = _padm(fit_masks)
                test_sc_masks = _padm(test_sc_masks)
                train_sc_masks = (fit_masks if train_sc_aliases_fit
                                  else _padm(train_sc_masks))
                if need_unweighted:
                    test_masks = _padm(test_masks)
                    train_masks = _padm(train_masks)
            sample_shard = NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
            mask_shard = NamedSharding(mesh, P(None, mesh_lib.DATA_AXIS))
            data_dev = {k: _bput(v, sample_shard, f"data.{k}")
                        for k, v in data.items()}
            put_masks = mask_shard
        else:
            data_dev = {k: _bput(v, repl, f"data.{k}")
                        for k, v in data.items()}
            put_masks = repl
        # one device buffer per DISTINCT mask array: in the unweighted case
        # fit/train-scoring masks are the same object, so they share one
        # upload and one HBM allocation (the plane's content keys make
        # the dedup hold even across separately-built equal arrays).
        # A halving rung's subsampled masks carry a RUNG-SCOPED label
        # ("mask.r1.fit"): the next rung's barrier then demotes exactly
        # the previous rung's buffers — plane keys are shared by
        # content, so a bare "mask." sweep could un-charge a sibling
        # search's live masks under the same tenant
        mask_ns = (f"mask.{rung.ns}." if rung is not None
                   and rung.resource == "n_samples" else "mask.")
        if data_mode != "stream":
            fit_dev = _bput(fit_masks, put_masks, mask_ns + "fit")
            test_dev = _bput(test_sc_masks, put_masks, mask_ns + "test")
            train_sc_dev = (fit_dev if train_sc_masks is fit_masks
                            else _bput(train_sc_masks, put_masks,
                                       mask_ns + "train"))
            if need_unweighted:
                test_unw_dev = _bput(test_masks, put_masks,
                                     mask_ns + "test_unw")
                train_unw_dev = _bput(train_masks, put_masks,
                                      mask_ns + "train_unw")
            else:
                test_unw_dev, train_unw_dev = test_dev, train_sc_dev
        get_tracer().record_span(
            "device_put.broadcast", _t_upload0, time.perf_counter(),
            n_samples=n_samples, n_data_shards=config.n_data_shards)

        test_scores = {s: np.empty((n_cand, n_folds)) for s in scorer_names}
        train_scores = ({s: np.empty((n_cand, n_folds))
                         for s in scorer_names} if return_train else None)
        fit_times = np.empty((n_cand, n_folds))
        score_times = np.empty((n_cand, n_folds))
        # per-(candidate, fold) fit-failure flags: a compiled fit that
        # diverges to NaN parameters is a failed fit and gets error_score,
        # exactly like a raising est.fit on the host path (SURVEY §5.3:
        # "error_score must be reimplemented explicitly")
        fit_failed = np.zeros((n_cand, n_folds), bool)
        fit_failed[preval_failed, :] = True

        ckpt = None
        if config.checkpoint_dir:
            from spark_sklearn_tpu.utils.checkpoint import (
                SearchCheckpoint, fingerprint)
            if sparse_op is not None:
                # CSR content enters by its canonical components — a
                # sparse head-slice repr() carries no values, and any
                # dense staging here would defeat the whole tier
                _x_head = sparse_op.values[:4096]
                _x_moments = (
                    *sparse_op.signature(),
                    float(np.sum(sparse_op.values, dtype=np.float64)),
                    float(np.sum(np.square(sparse_op.values,
                                           dtype=np.float64))),
                    float(np.sum(sparse_op.indices, dtype=np.float64)))
            else:
                _x_head = X[: min(64, n_samples)]
                # whole-dataset moments so ANY changed X row or label
                # set breaks the fingerprint (head rows can collide)
                _x_moments = (
                    X.shape, float(np.sum(X, dtype=np.float64)),
                    float(np.sum(np.square(X, dtype=np.float64))))
            key = fingerprint(
                type(self.estimator).__name__, base_params, candidates,
                scorer_names, n_folds, return_train,
                # result-affecting config: resuming under a different matmul
                # precision or dtype must not reuse the other run's scores
                (bool(config.bf16_matmul), str(config.dtype)),
                _x_head,
                _x_moments,
                self._hashable_labels(y),
                np.asarray(train_masks),
                # weighted searches must not resume an unweighted run's
                # chunks (and vice versa); arrays go in as bare top-level
                # parts so fingerprint() hashes their bytes (tuples would
                # be repr()'d, which numpy truncates past 1000 elements)
                "fitw",
                np.asarray(fit_weight, np.float64)
                if fit_weight is not None else "none",
                "scw",
                np.asarray(score_weight, np.float64)
                if score_weight is not None else "none",
                # halving rungs are distinct resumable units: the rung
                # index (and its resource) joins the fingerprint even
                # though the candidate set / masks already differ, so
                # two rungs can never alias one journal file
                *(("halving", rung.itr, rung.n_resources)
                  if rung is not None else ()),
                # a streamed run's journal holds per-shard accumulator
                # records addressed by the stream geometry — never let a
                # device-mode resume read (or extend) it
                *(("stream",) if data_mode == "stream" else ()))
            _keycheck.note(
                "checkpoint", key,
                fields={"bf16_matmul": bool(config.bf16_matmul),
                        "dtype": str(config.dtype)},
                detail=type(self.estimator).__name__)
            ckpt = SearchCheckpoint(config.checkpoint_dir, key)

        profiler_cm = None
        if config.profile_dir:
            import jax.profiler as _prof
            profiler_cm = _prof.trace(config.profile_dir)
            profiler_cm.__enter__()
        debug_ctx = (jax.debug_nans(True) if config.debug_nans
                     else _nullcontext())
        # search_report = the rendered view of a typed registry whose
        # schema lives in obs.metrics.SEARCH_REPORT_SCHEMA (keys
        # materialize here in the legacy order, so the report is
        # key-for-key identical to the pre-registry dict).  A halving
        # search's rungs share ONE registry: counters (n_launches,
        # walls, n_chunks_resumed) accumulate across rungs and the
        # struct blocks render the whole search's deltas.
        if rung is not None and rung.registry is not None:
            metrics = rung.registry
        else:
            metrics = search_registry("tpu")
            if rung is not None:
                rung.registry = metrics
        ncg = metrics.gauge("n_compile_groups")
        if rung is not None:
            # like the counters: the whole search's group total, not
            # the last rung's
            ncg.set(int(ncg.value) + len(groups))
        else:
            ncg.set(len(groups))
        metrics.counter("n_launches")
        metrics.counter("n_chunks_resumed")
        metrics.gauge("fit_wall_s")
        metrics.gauge("score_wall_s")
        metrics.struct("mesh").update(
            {"task": n_task_shards, "data": config.n_data_shards})
        self._search_metrics = metrics
        self._search_report = metrics.data

        # self-protection context (deadline shed, quarantine, partial-
        # results degradation — see parallel/faults.py protection_block).
        # Search-scoped: one ctx spans every halving rung, so the
        # deadline covers the WHOLE search; the done mask is per-call
        # (each rung owns fresh result arrays).  protection off -> ctx
        # is None and every path below is untouched (byte-identical
        # reports).
        if _faults.protection_enabled(config):
            pctx = getattr(self, "_protection_ctx", None)
            if pctx is None or rung is None or rung.itr == 0:
                t_dl = None
                if getattr(config, "search_deadline_s", None):
                    # the executor stamps the deadline at SUBMIT (queue
                    # wait spends the budget); a sessionless fit starts
                    # the clock here
                    hd = getattr(getattr(_binding, "handle", None),
                                 "t_deadline", None)
                    t_dl = hd if hd is not None else (
                        time.perf_counter()
                        + float(config.search_deadline_s))
                pctx = self._protection_ctx = {
                    "t_start": time.perf_counter(),
                    "t_deadline": t_dl,
                    "deadline_hit": False,
                    "shed": [],
                    "quarantined": [],
                }
            # candidates with written cells: prevalidation failures
            # already carry error_score, so degradation never
            # overwrites them
            pctx["done"] = preval_failed.copy()
        else:
            self._protection_ctx = None

        # bound peak HBM: chunk each compile group so one launch holds at
        # most max_tasks_per_batch (candidate x fold) program instances;
        # every chunk of a group is padded to one uniform width so the
        # group's two jitted programs compile exactly once
        max_tasks = config.max_tasks_per_batch
        hint = getattr(family, "max_tasks_hint", None)
        if hint is not None:
            # families with big per-task workspaces (e.g. SVC kernel and
            # decision caches) bound their own launch width
            max_tasks = min(max_tasks, max(n_folds, hint(n_samples, meta)))
        max_cand_per_batch = max(
            n_task_shards,
            mesh_lib.pad_to_multiple(
                max(1, max_tasks // max(n_folds, 1)),
                n_task_shards))

        host_scorer_cache: List[Any] = []

        def host_eval(cand_indices):
            """Per-candidate host execution for the supervisor's OOM
            bottom-out: real `clone(est).set_params(**p)` fits via
            sklearn `_fit_and_score` — exact sklearn error_score
            semantics — returning (test, train) score dicts shaped
            (len(cand_indices), n_folds) under the compiled scorer
            names."""
            from sklearn.metrics import check_scoring
            from sklearn.model_selection._validation import (
                _fit_and_score, _warn_or_raise_about_fit_failures)

            if not host_scorer_cache:
                if self.scoring is None or isinstance(self.scoring, str) \
                        or callable(self.scoring):
                    host_scorer_cache.append(
                        check_scoring(self.estimator, self.scoring))
                else:
                    from sklearn.metrics._scorer import (
                        _MultimetricScorer, _check_multimetric_scoring)
                    sc = _check_multimetric_scoring(
                        self.estimator, self.scoring)
                    if set(sc) != set(scorer_names):
                        # compiled names must address the same cells the
                        # host scorer produces; a mismatch cannot be
                        # recovered into cv_results_
                        raise RuntimeError(
                            "host fallback scorer names "
                            f"{sorted(sc)} do not match compiled names "
                            f"{sorted(scorer_names)}")
                    host_scorer_cache.append(_MultimetricScorer(
                        scorers=sc,
                        raise_exc=(self.error_score == "raise")))
            scorer = host_scorer_cache[0]
            host_fit_params = ({"sample_weight": fit_weight}
                               if fit_weight is not None else None)
            host_score_params = ({"sample_weight": score_weight}
                                 if score_weight is not None else None)
            results = []
            for ci in cand_indices:
                for tr_idx, te_idx in splits:
                    results.append(_fit_and_score(
                        clone(self.estimator), X_host, y, scorer=scorer,
                        train=tr_idx, test=te_idx, verbose=0,
                        parameters=candidates[int(ci)],
                        fit_params=host_fit_params,
                        score_params=host_score_params,
                        return_train_score=return_train,
                        return_times=True,
                        error_score=self.error_score))
            _warn_or_raise_about_fit_failures(results, self.error_score)
            n = len(cand_indices)
            te = {s: np.empty((n, n_folds)) for s in scorer_names}
            tr = ({s: np.empty((n, n_folds)) for s in scorer_names}
                  if return_train else {})
            for t, res in enumerate(results):
                i, f = divmod(t, n_folds)
                ts = res["test_scores"]
                if not isinstance(ts, dict):
                    ts = {s: ts for s in scorer_names}
                for s in scorer_names:
                    te[s][i, f] = ts.get(s, np.nan)
                if return_train:
                    trs = res.get("train_scores", {})
                    if not isinstance(trs, dict):
                        trs = {s: trs for s in scorer_names}
                    for s in scorer_names:
                        tr[s][i, f] = trs.get(s, np.nan)
            return te, tr

        if ledger is not None:
            # launch-boundary sampling (pipeline._record) is live only
            # while a ledger-enabled search runs — refcounted so
            # concurrent searches compose and memory_ledger=False
            # stays an exact no-op
            ledger.activate()
        try:
            with debug_ctx:
                if data_mode == "stream":
                    _stream.run_stream(
                        self, groups=groups, base_params=base_params,
                        family=family, meta=meta,
                        scorer_names=scorer_names, data=data,
                        fit_masks=fit_masks,
                        test_sc_masks=test_sc_masks,
                        train_sc_masks=train_sc_masks, repl=repl,
                        config=config, n_task_shards=n_task_shards,
                        max_cand_per_batch=max_cand_per_batch,
                        n_folds=n_folds, dtype=dtype,
                        return_train=return_train,
                        test_scores=test_scores,
                        train_scores=train_scores, fit_times=fit_times,
                        score_times=score_times, ckpt=ckpt,
                        fit_failed=fit_failed, candidates=candidates)
                else:
                    # content fp of host X for the shared-prefix derived
                    # cache key — only worth hashing when the family can
                    # actually stage prefixes (compiled Pipeline with
                    # transformer steps, dense host X)
                    data_fp = None
                    if (hasattr(family, "prefix_digest")
                            and getattr(family, "steps", None)
                            and isinstance(data.get("X"), np.ndarray)):
                        data_fp = _dataplane.fingerprint(data["X"])
                    self._run_groups(
                        groups=groups, base_params=base_params,
                        family=family,
                        meta=meta, scorers=scorers,
                        scorer_names=scorer_names,
                        data_dev=data_dev, fit_dev=fit_dev,
                        test_dev=test_dev, train_sc_dev=train_sc_dev,
                        test_unw_dev=test_unw_dev,
                        train_unw_dev=train_unw_dev,
                        sw_blind=sw_blind,
                        fit_masks=fit_masks, mesh=mesh,
                        config=config, n_task_shards=n_task_shards,
                        task_shard=task_shard,
                        max_cand_per_batch=max_cand_per_batch,
                        n_folds=n_folds,
                        dtype=dtype, return_train=return_train,
                        test_scores=test_scores,
                        train_scores=train_scores,
                        fit_times=fit_times, score_times=score_times,
                        ckpt=ckpt,
                        fit_failed=fit_failed, candidates=candidates,
                        host_eval=host_eval, data_fp=data_fp)
        finally:
            if profiler_cm is not None:
                profiler_cm.__exit__(None, None, None)
            # this search's broadcast-cache traffic (hits = arrays
            # reused with zero transfer; bytes_uploaded = cacheable
            # bytes actually shipped; bytes_staged = per-chunk dyn
            # params) — schema in obs.metrics.DATAPLANE_BLOCK_SCHEMA
            mask_tiling = ("n/a" if not hasattr(family, "fit_task_batched")
                           else "device" if plane is not None else "host")
            metrics.put("dataplane", _dataplane.report_block(
                plane, dp_before, mask_tiling=mask_tiling))
            # this search's AOT-store traffic (hits = programs served
            # from serialized artifacts with zero tracing; publishes =
            # artifacts written for the next cold process) — schema in
            # obs.metrics.PROGRAMSTORE_BLOCK_SCHEMA
            metrics.put("programstore", _programstore.report_block(
                pstore, ps_before))
            # this search's device-memory view (modeled per-group
            # footprints, budget/ceiling state, measured watermark) —
            # schema in obs.metrics.MEMORY_BLOCK_SCHEMA.  Rendered
            # ONLY when the ledger is on: off, the report shape is
            # byte-identical to the pre-ledger engine.
            if ledger is not None:
                ledger.deactivate()
                metrics.put("memory", _memledger.report_block(
                    ledger, mem_before,
                    getattr(self, "_memory_ctx", {}) or {}))
            # this search's in-flight heartbeat view (beats/steps,
            # cadence percentiles, staleness, overhead estimate) —
            # schema in obs.metrics.HEARTBEAT_BLOCK_SCHEMA.  Rendered
            # ONLY when heartbeat is on: off, the report shape is
            # byte-identical to the beacon-less engine.
            if hb_ctx is not None:
                metrics.put("heartbeat", _heartbeat.heartbeat_block(
                    hb_ctx["scope"]))
            # the search's protection verdict (deadline/shed/quarantine
            # state) — schema in obs.metrics.PROTECTION_BLOCK_SCHEMA.
            # Rendered ONLY when protection is on: off, the report is
            # byte-identical to the unprotected engine.  A halving
            # search re-puts each rung; the shared ctx accumulates, so
            # the last put covers the whole search.
            pctx_fin = getattr(self, "_protection_ctx", None)
            if pctx_fin is not None:
                metrics.put("protection", _faults.protection_block(
                    config, deadline_hit=pctx_fin["deadline_hit"],
                    shed=pctx_fin["shed"],
                    quarantined=pctx_fin["quarantined"],
                    elapsed_s=time.perf_counter()
                    - pctx_fin["t_start"]))
        if preval_failed.any():
            # failed fits never ran: sklearn records 0.0 for their times
            fit_times[preval_failed, :] = 0.0
            score_times[preval_failed, :] = 0.0
            if self.verbose > 1:
                # excluded from every launch -> their END lines (showing
                # error_score, like sklearn's failed fits) print here
                self._print_task_end_lines(
                    candidates, np.flatnonzero(preval_failed), n_folds,
                    scorer_names, test_scores, train_scores, return_train,
                    0.0, fit_failed)

        # failed-fit accounting, sklearn error_score semantics
        # (_warn_or_raise_about_fit_failures): two detectors feed it —
        #   1. NaN hyperparameters (sklearn raises at validation; our
        #      solvers won't blow up, so the chance-level score they
        #      produce must not masquerade as a result).  inf stays legal —
        #      sklearn itself uses C=np.inf for "no penalty".
        #   2. per-(candidate, fold) NaN model parameters detected on
        #      device after each launch (_run_groups): a diverging MLP or
        #      an ill-conditioned solve is a failed fit, not a result.
        # Genuinely non-finite SCORES from finite models pass through,
        # like sklearn's (_format_results warns about those separately).
        for group in groups:
            for arr in group.dynamic_params.values():
                if np.issubdtype(arr.dtype, np.floating):
                    fit_failed[group.candidate_indices[
                        np.isnan(arr)], :] = True
        if fit_failed.any():
            n_bad = int(fit_failed.sum())
            if isinstance(self.error_score, str) and \
                    self.error_score == "raise":
                raise ValueError(
                    f"{n_bad} fits failed with non-finite parameters and "
                    "error_score='raise'")
            if fit_failed.all():
                # sklearn's _warn_or_raise_about_fit_failures raises when
                # EVERY fit failed, even with a numeric error_score (the
                # host tier inherits this from sklearn directly).  Only
                # host-reproducible failures (invalid params caught by
                # prevalidation) suppress the host fallback: an all-NaN
                # outcome from the float32 device solvers might still
                # succeed under sklearn's float64 host fits
                all_failed = ValueError(
                    f"\nAll the {n_cand * n_folds} fits failed.\n"
                    "It is very likely that your model is misconfigured.\n"
                    "You can try to debug the error by setting "
                    "error_score='raise'.")
                if preval_failed.all():
                    all_failed._sst_no_fallback = True
                raise all_failed
            from sklearn.exceptions import FitFailedWarning
            warnings.warn(
                f"\n{n_bad} fits failed out of a total of "
                f"{n_cand * n_folds}.\nThe score on these train-test "
                "partitions for these parameters will be set to "
                f"{self.error_score}. (cause: non-finite model "
                "parameters or hyperparameters)", FitFailedWarning)
            for s in scorer_names:
                test_scores[s][fit_failed] = self.error_score
                if return_train:
                    train_scores[s][fit_failed] = self.error_score
        # scorer_ keeps the sklearn-facing objects so .score() works the
        # sklearn way even though CV scoring ran compiled
        if self.scoring is None or isinstance(self.scoring, str):
            scorer_attr = check_scoring(self.estimator, self.scoring)
        else:
            from sklearn.metrics._scorer import _check_multimetric_scoring
            scorer_attr = _check_multimetric_scoring(
                self.estimator, self.scoring)
        return (test_scores, train_scores, fit_times, score_times,
                scorer_names, scorer_attr)

    def _run_groups(self, *, groups, base_params, family, meta, scorers,
                    scorer_names, data_dev, fit_dev, test_dev, train_sc_dev,
                    test_unw_dev, train_unw_dev, sw_blind,
                    fit_masks, mesh, config, n_task_shards, task_shard,
                    max_cand_per_batch, n_folds, dtype, return_train,
                    test_scores, train_scores, fit_times, score_times, ckpt,
                    fit_failed, candidates, host_eval=None, data_fp=None):
        """Chunked launch schedule, executed through the pipelined chunk
        executor (parallel/pipeline.py).

        Every chunk of every compile group becomes one (or, for the
        calibration chunk, three) `LaunchItem`s: host staging of chunk
        k+1, the result gather of chunk k-1, and the next compile
        group's lowering/compile all overlap chunk k's device compute at
        `config.pipeline_depth >= 1`; depth 0 runs the identical item
        sequence synchronously (the bit-for-bit escape hatch).  Scores
        are independent of the depth — only host work is reordered."""
        from spark_sklearn_tpu.parallel.pipeline import (
            ChunkPipeline, FuseSpec, LaunchItem, persistent_cache_counts)
        from spark_sklearn_tpu.parallel.taskgrid import pad_chunk

        #: successive-halving rung owner (search/halving.py, via the
        #: launch-ownership protocol): this call is one rung of a
        #: multi-rung search.  Chunk ids carry the rung namespace,
        #: geometry re-plans (or pins) the survivors' widths, and the
        #: pipeline/registry/baselines are shared across rungs.
        rung = _ownership.current_owner(self, kind="rung")
        cid_ns = f"{rung.ns}:" if rung is not None else ""
        # tiled-mask labels share the broadcast masks' rung namespace
        # (see _fit_compiled_impl): the rung barrier's demote targets
        # only the previous rung's buffers
        mask_ns = (f"mask.{rung.ns}." if rung is not None
                   and rung.resource == "n_samples" else "mask.")
        tiled_label = mask_ns + "fit.tiled"
        task_batched = hasattr(family, "fit_task_batched")
        if config.n_data_shards > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            tb_mask_shard = NamedSharding(
                mesh, P(mesh_lib.TASK_AXIS, mesh_lib.DATA_AXIS))
        else:
            tb_mask_shard = task_shard
        metrics = self._search_metrics
        donate = bool(config.donate_chunk_buffers)
        # the session-scoped device data plane (same instance the
        # broadcast uploads went through) serves the task-batched mask
        # tiling on device and the cached all-static pad operand; the
        # staging ring double-buffers per-chunk dynamic params behind
        # donate_chunk_buffers (pad_chunk writes into reused host
        # buffers instead of allocating per chunk)
        from spark_sklearn_tpu.parallel import dataplane as _dataplane
        plane = _dataplane.plane_for(config)
        # the device-memory ledger (parallel/memledger.py): the per-
        # search accumulator was initialized by _fit_compiled_impl;
        # this method models the per-group footprints once geometry
        # resolves, caps planned widths to the HBM budget, and stamps
        # modeled-vs-budget bytes onto OOM fault events
        from spark_sklearn_tpu.parallel import memledger as _memledger
        ledger = _memledger.ledger_for(config)
        mem_ctx = getattr(self, "_memory_ctx", None) \
            if ledger is not None else None
        # the multi-tenant executor binding (serve/executor.py): set
        # when this search was submitted to a TpuSession's
        # SearchExecutor — its LaunchItems then route through the
        # session's shared fair-share dispatch queue, and its plane
        # uploads are charged to its tenant
        from spark_sklearn_tpu import serve as _serve
        binding = _serve.current_binding()
        sched_tenant = binding.tenant if binding is not None else None
        # self-protection (deadline shed / quarantine / degradation):
        # None when protection is off — every guarded path below then
        # collapses to the unprotected engine
        pctx = getattr(self, "_protection_ctx", None)
        # the score a protected search writes for work it never ran:
        # sklearn's numeric error_score, or NaN under error_score=
        # 'raise' (shed cells are DECLARED in the protection block,
        # never routed through fit_failed — a deadline is not a failed
        # fit, and must not trip the all-fits-failed raise)
        errval = (np.nan if isinstance(self.error_score, str)
                  else self.error_score)
        # multi-controller runs force depth 0 below; resolved here so
        # the staging ring can size itself to the in-flight window
        depth = config.pipeline_depth if jax.process_count() == 1 else 0
        ring = _dataplane.StagingRing(depth + 2) if donate else None
        #: the fold masks' content digest, hashed once per search (the
        #: plane's tiled-mask keys need it; fit_masks never mutates)
        _fm_fp: List[str] = []

        def fit_masks_fp():
            if not _fm_fp:
                _fm_fp.append(_dataplane.fingerprint(fit_masks))
            return _fm_fp[0]

        # score path: every registry scorer decomposes into model views
        # (pred/decision/proba) + a metric core, so views are computed
        # ONCE per launch over the flat task axis — for linear families
        # one wide matmul for ALL (candidate x fold) tasks
        # (`views_task_batched`) instead of a matvec per task per scorer
        # — then the cheap reduction cores vmap over tasks.  Custom
        # scorers without a core (family default_scorer like KMeans
        # -inertia) keep the nested path.
        import os as _os
        # same boolean spelling as the other SST_* switches: "0"/"off"
        # must NOT force the nested control arm
        _nested_env = _os.environ.get(
            "SST_NESTED_SCORE", "").strip().lower() in (
                "1", "true", "on", "yes")
        all_cores = all(hasattr(fn, "core") for fn in scorers.values()) \
            and not getattr(config, "nested_score", False) \
            and not _nested_env
        needed_views = frozenset(
            v for fn in scorers.values()
            for v in getattr(fn, "views", ()))
        # fused launch (default): fit + NaN-health + scoring in ONE
        # compiled program per chunk — the model pytree stays on device.
        # The FIRST live chunk of each multi-chunk group still runs as
        # separate fit/score launches plus a warm calibration score
        # launch that measures the steady-state score cost later fused
        # chunks attribute out of their single-launch wall.
        fused_mode = all_cores and config.fuse_fit_score
        # device-resident chunk loop (chunk_loop="scan"): roll the
        # compile group's chunk loop INTO the program via lax.scan so a
        # whole scan segment — ideally the whole group, or a whole
        # halving rung including its on-device top_k elimination —
        # executes as ONE launch.  The scan body is the group's fused
        # program, so scan requires the fused score path: a search that
        # asks for scan without it (custom scorer on the nested path,
        # fuse_fit_score=False) runs per-chunk and the chunkloop block
        # records why.  Per-chunk stays the default and the
        # resumable/faultable fallback.
        from spark_sklearn_tpu.parallel.taskgrid import (
            plan_scan_segments, resolve_chunk_loop)
        chunk_loop = resolve_chunk_loop(config)
        scan_mode = (chunk_loop == "scan") and fused_mode
        cl_state = chunkloop_block(
            metrics.struct("chunkloop"), mode=chunk_loop,
            enabled=scan_mode,
            score_attribution="folded" if scan_mode else "calibrated")
        if chunk_loop == "scan" and not fused_mode:
            cl_state["fallbacks"].append("unfused-score-path")
        # shared-prefix search graphs (search/prefix.py): group the
        # Pipeline grid's candidates by their transformer-chain digest,
        # compute each DISTINCT prefix once per fold on device (stage
        # 1, below, after geometry resolves), and fan the suffix
        # candidates over the cached matrices through the ordinary
        # chunk/scan machinery.  Ineligible searches run the atomic
        # path unchanged and record the reason; prefix_reuse=False is
        # the byte-identical escape hatch.
        from spark_sklearn_tpu.search import prefix as _prefix
        px_on = _prefix.resolve_prefix_reuse(config)
        px_state = _prefix.prefix_block(
            metrics.struct("prefix"),
            mode="shared" if px_on else "atomic", enabled=False)
        px_reason = None
        if px_on:
            px_reason = _prefix.prefix_fallback_reason(
                family, all_cores=all_cores,
                n_data_shards=int(config.n_data_shards),
                x_dev=data_dev.get("X"))
            if px_reason is None and plane is None:
                # the derived-buffer cache IS the data plane; without
                # it there is nowhere resident to fan suffixes over
                px_reason = "dataplane-disabled"
            if px_reason is None and data_fp is None:
                px_reason = "no-x-fingerprint"
            if px_reason is not None \
                    and px_reason not in px_state["fallbacks"]:
                px_state["fallbacks"].append(px_reason)
        px_stage = px_on and px_reason is None
        if scan_mode:
            from jax import lax
            from jax.sharding import NamedSharding, PartitionSpec as P
            # stacked per-chunk operands carry a leading scan-step axis;
            # each step's slice keeps the per-chunk task sharding
            scan_shard = NamedSharding(
                mesh, P(None, mesh_lib.TASK_AXIS))
            repl_shard = mesh_lib.replicated_sharding(mesh)
        # in-flight heartbeats (obs/heartbeat.py): the scanned step body
        # beacons (segment token, step index) through jax.debug.callback
        # while the device is mid-launch, so progress/ETA and the
        # heartbeat watchdog see liveness per scan step.  The scope was
        # created by _fit_compiled_impl; hb_on gates EVERY heartbeat
        # touch below, so off is an exact no-op (no callback traced —
        # the "hb" cache-key component in build_scan keeps on/off
        # programs from ever aliasing).
        from spark_sklearn_tpu.obs import heartbeat as _heartbeat
        _hb_ctx = getattr(self, "_hb_ctx", None)
        hb_on = _hb_ctx is not None \
            and _heartbeat.resolve_heartbeat(config)
        hb_scope = _hb_ctx["scope"] if hb_on else ""
        hb_handle = binding.handle.id \
            if (hb_on and binding is not None) else ""
        hb_tenant = binding.tenant \
            if (hb_on and binding is not None) else ""
        if hb_on:
            # the geometry cost model's prior prices the ETA blend's
            # model side: per scan step, one chunk's padded lanes at
            # lane_cost_s plus the launch overhead amortized over the
            # segment (config overrides win, like plan_geometry's)
            from spark_sklearn_tpu.parallel.taskgrid import (
                geometry_cost_model)
            _cm_snap = geometry_cost_model().snapshot()
            hb_overhead_s = getattr(config, "geometry_overhead_s", None)
            if hb_overhead_s is None:
                hb_overhead_s = float(
                    _cm_snap.get("launch_overhead_s", 0.0))
            hb_lane_cost_s = getattr(config, "geometry_lane_cost_s",
                                     None)
            if hb_lane_cost_s is None:
                hb_lane_cost_s = float(_cm_snap.get("lane_cost_s", 0.0))
        else:
            hb_overhead_s = hb_lane_cost_s = 0.0
        # cross-search launch fusion (serve/executor.py): steady-state
        # fused chunks of an executor-submitted search offer a FuseSpec
        # so same-program chunks from OTHER searches coalesce into one
        # wide launch.  Donated buffers are excluded (a fused re-stage
        # would read host rows a donated solo launch may have consumed),
        # first-chunk fit/score/calibration items never fuse (they
        # share cross-item group state), and scanned segments never
        # fuse (one segment already serves many chunks; its lanes are
        # billed to DRR by the member count instead).
        fusion_on = (fused_mode and binding is not None and not donate
                     and not scan_mode and _serve.resolve_fusion(config))
        score_key = tuple(sorted(scorers.items()))
        # deterministic identity parts for the persistent program store
        # (parallel/programstore.py): everything in a store key must
        # repr identically across processes, so the family OBJECT
        # becomes its registry name, the mesh its topology, and the
        # scorer closures their registry names (their implementations
        # are pinned by the package version in the store's environment
        # fingerprint).  Donated programs skip the store: the exported
        # wrapper would silently drop the donation.
        mesh_desc = ("mesh", tuple(sorted(dict(mesh.shape).items())),
                     tuple(int(d.id)
                           for d in np.asarray(mesh.devices).flat))
        store_score_names = tuple(sorted(scorers))
        store_sw_key = tuple(sorted(sw_blind))
        # THIS search's store (None when its config doesn't enable one:
        # a store-less search must never resolve programs through a
        # store an earlier search in the process activated)
        from spark_sklearn_tpu.parallel import programstore as _pstore
        search_store = _pstore.activate_store(config)

        # ------------------------------------------------------------------
        # group plans: chunk geometry + (lazily built) programs
        # ------------------------------------------------------------------
        plans = []
        for gi, group in enumerate(groups):
            static = {**base_params, **group.static_params}
            nc = group.n_candidates

            # convergence-sorted chunking: a lockstep launch executes the
            # MAX iteration count over its lanes, so one wide launch pays
            # the slowest candidate's iterations for every lane.  When
            # the family knows a difficulty proxy (e.g. GLM: larger C =
            # weaker regularisation = slower convergence), sort the
            # group's candidates by it and split into several narrower
            # launches — all chunks of a group share ONE compiled program
            # (uniform width), so this costs dispatches, not compiles,
            # and easy launches early-exit at their own iteration count.
            # cv_results_ order is unaffected (cells are written through
            # candidate_indices).
            sorted_chunks = False
            proxy_hook = getattr(family, "convergence_proxy", None)
            if proxy_hook is not None and config.sort_candidates:
                proxy = proxy_hook(group.dynamic_params, static)
                if proxy is not None:
                    proxy = np.asarray(proxy)
                    if len(proxy) >= getattr(
                            family, "min_sort_candidates", 32) \
                            and np.unique(proxy).size > 1:
                        order = np.argsort(proxy, kind="stable")
                        group.candidate_indices = np.asarray(
                            group.candidate_indices)[order]
                        group.dynamic_params = {
                            k: np.asarray(v)[order]
                            for k, v in group.dynamic_params.items()}
                        sorted_chunks = True

            sorted_cap = None
            if sorted_chunks:
                # ~8 difficulty-graded launches per group (bounded below
                # by the task-shard multiple so sharding stays uniform)
                sorted_cap = min(
                    mesh_lib.pad_to_multiple(nc, n_task_shards),
                    max_cand_per_batch,
                    max(n_task_shards,
                        mesh_lib.pad_to_multiple(
                            -(-nc // _SORTED_LAUNCHES), n_task_shards)))
            plans.append({
                "gi": gi, "group": group, "static": static, "nc": nc,
                "sorted": sorted_chunks, "sorted_cap": sorted_cap})

        # per-group prefix digests (stage-1 grouping): groups map
        # many-to-one onto digests — groups differing only in
        # final-step statics share the digest, and therefore the
        # cached transformed matrix
        px_digests = [None] * len(plans)
        if px_stage:
            px_digests = _prefix.group_prefix_digests(
                groups, base_params, family)
            if all(d is None for d in px_digests):
                px_stage = False
                px_state["fallbacks"].append("undigestable-prefix")
        for plan, dg in zip(plans, px_digests):
            plan["prefix"] = dg if px_stage else None

        # ------------------------------------------------------------------
        # waste-aware launch geometry (parallel/taskgrid.plan_geometry):
        # per-group chunk widths from power-of-two bucketing over the
        # measured cost model, minimizing launch overhead + padding
        # waste.  The chosen plan is pinned into the checkpoint journal
        # so a resumed search replays the EXACT same chunk ids; a
        # structurally different journalled geometry is a hard error,
        # never a silent mix of chunk ids.
        # ------------------------------------------------------------------
        from spark_sklearn_tpu.parallel.taskgrid import (
            GeometryMismatchError, GeometryPlan, freeze,
            geometry_cost_model, plan_geometry)
        import dataclasses as _dc
        # ledger-informed width ceiling: resident broadcast bytes (one
        # count per distinct device buffer) plus each group's modeled
        # per-candidate slope bound the widest chunk the HBM budget
        # holds — a chunk the model says cannot fit is never planned,
        # so OOM bisection becomes the fallback, not the discovery
        # mechanism.  No budget (CPU default, or hbm_budget_bytes=0)
        # means no caps: planning is bit-identical to the pre-ledger
        # engine.
        mem_caps = None
        resident_est = 0
        mem_kw = None
        if ledger is not None:
            seen_bufs = set()
            for dev_arr in list(data_dev.values()) + [
                    fit_dev, test_dev, train_sc_dev, test_unw_dev,
                    train_unw_dev]:
                if id(dev_arr) in seen_bufs:
                    continue
                seen_bufs.add(id(dev_arr))
                # leaf-wise so a BCOO data operand prices its
                # values+indices components (nnz-proportional; the
                # wrapper itself has no nbytes) — dense arrays are
                # their own single leaf, so this is the same number
                # the old getattr spelling produced
                for leaf in jax.tree_util.tree_leaves(dev_arr):
                    resident_est += int(getattr(leaf, "nbytes", 0))
            mem_kw = dict(
                task_batched=task_batched,
                n_samples=int(fit_masks.shape[1]),
                mask_itemsize=int(fit_masks.dtype.itemsize),
                n_scorers=len(scorers), return_train=return_train,
                dtype_itemsize=int(np.dtype(dtype).itemsize))
            budget = int(mem_ctx.get("budget_bytes", 0)) \
                if mem_ctx is not None else 0
            if budget:
                mem_caps = [
                    _memledger.width_cap(
                        budget, resident_est,
                        _memledger.model_group_footprint(
                            p["group"].dynamic_params, 1, n_folds,
                            **mem_kw)["per_candidate_bytes"],
                        n_task_shards, max_cand_per_batch,
                        ledger.safety_margin)
                    for p in plans]
        geo_kwargs = dict(
            sizes=[p["nc"] for p in plans],
            sorted_caps=[p["sorted_cap"] for p in plans],
            n_folds=n_folds, n_task_shards=n_task_shards,
            max_width=max_cand_per_batch,
            mode=getattr(config, "geometry_mode", "auto"),
            cost_model=geometry_cost_model(),
            overhead_override=getattr(config, "geometry_overhead_s", None),
            lane_cost_override=getattr(config, "geometry_lane_cost_s",
                                       None),
            width_caps=mem_caps,
            # fleet-wide padding: under cross-search fusion a padded
            # lane is fillable by a same-program peer, so it prices at
            # half the solo waste; 0.0 keeps pre-fusion plans
            # byte-identical
            fusion_lane_discount=0.5 if fusion_on else 0.0,
            # chunk widths are loop-mode-invariant (chunk ids must stay
            # byte-identical across modes so journals and the per-chunk
            # OOM fallback interoperate); the key field keeps the two
            # modes' plans distinct cache residents all the same
            chunk_loop=chunk_loop,
            # per-group shared-prefix digests join the PlanKey: a
            # prefix-staged plan (suffix programs over cached (F, n,
            # d') matrices) must never alias an atomic plan with the
            # same sizes in the plan cache or plans.json
            prefix=[p["prefix"] for p in plans])
        #: per-group structure identity ACROSS rungs: the static params
        #: minus the budgeted resource (survivor groups at rung k+1
        #: carry the same key as the rung-0 group they came from, even
        #: when the resource itself is static for the family)
        rung_keys = None
        if rung is not None:
            rung_keys = [
                freeze({k: v for k, v in p["static"].items()
                        if k != rung.resource})
                for p in plans]
        if rung is None or rung.itr == 0:
            # the first rung (and every exhaustive search) prices the
            # full grid exactly as before, plan-cache included
            geo = plan_geometry(reuse=True, **geo_kwargs)
        else:
            # mid-search re-plan: the survivors' geometry is a
            # search-local decision fed by the PREVIOUS rungs' measured
            # timelines (the cost model observed each rung's pipeline
            # on the way out), so it bypasses the cross-search plan
            # cache.  With lane reclamation on, widths shrink to the
            # surviving sizes — width-affine to already-compiled
            # widths, priced by the model's measured compile wall;
            # off, survivors stay pinned to rung-0 widths and ride
            # along as padding (the A/B baseline).  Widths are pure
            # geometry: cv_results_ is identical either way.
            with get_tracer().span("geometry.replan", iter=rung.itr,
                                   replan=bool(rung.replan)):
                if rung.replan:
                    geo = plan_geometry(
                        reuse=False, min_width=rung.min_rung_width,
                        preferred=[rung.last_widths.get(k)
                                   for k in rung_keys],
                        **geo_kwargs)
                    geo = _dc.replace(geo, source="halving-replan")
                else:
                    geo = plan_geometry(reuse=False, **geo_kwargs)
                    pinned = []
                    for gg, k in zip(geo.groups, rung_keys):
                        base_w = rung.base_widths.get(k)
                        if base_w is not None \
                                and base_w % n_task_shards == 0 \
                                and base_w <= max_cand_per_batch:
                            gg = _dc.replace(
                                gg, width=int(base_w),
                                n_chunks=-(-gg.n_candidates
                                           // int(base_w)))
                        pinned.append(gg)
                    geo = _dc.replace(geo, groups=pinned,
                                      source="halving-pinned")
        if ckpt is not None:
            journalled = ckpt.get_meta("geometry_plan")
            if journalled is not None:
                jplan = GeometryPlan.from_dict(journalled)
                if jplan.signature() != geo.signature():
                    raise GeometryMismatchError(
                        "checkpoint was written under a different launch "
                        "geometry (journalled per-group (n_candidates, "
                        f"sorted) = {jplan.signature()}, current = "
                        f"{geo.signature()}); resuming would mix chunk "
                        "ids across geometries.  Delete "
                        f"{ckpt.path!r} or restore the original "
                        "sort_candidates/grid configuration.")
                # the journalled widths must still be valid under the
                # CURRENT mesh and HBM bound: every other width path
                # guarantees shard-multiple widths within
                # max_cand_per_batch, and replaying a stale plan would
                # silently break that (e.g. resumed on a smaller mesh,
                # or after lowering max_tasks_per_batch to dodge an OOM)
                bad = [g.width for g in jplan.groups
                       if g.width % n_task_shards != 0
                       or g.width > max_cand_per_batch]
                if bad:
                    raise GeometryMismatchError(
                        f"journalled chunk widths {bad} are invalid "
                        f"under the current configuration (task shards="
                        f"{n_task_shards}, max width per launch="
                        f"{max_cand_per_batch}); the checkpoint was "
                        "written on a different mesh or "
                        "max_tasks_per_batch.  Delete "
                        f"{ckpt.path!r} or restore the original "
                        "configuration.")
                # replay: widths come from the journal, so chunk ids —
                # and therefore resume hits — match the original run
                # even if the cost model has since drifted
                import dataclasses as _dc
                geo = _dc.replace(jplan, source="journal")
            else:
                ckpt.put_meta("geometry_plan", geo.to_dict())
            # the prefix grouping journals beside the geometry: chunk
            # results written under a prefix-staged run carry suffix
            # semantics (same numbers, but per-group programs keyed on
            # the digest), and a resume whose digests drifted — grid
            # edited, step params changed, prefix_reuse toggled off —
            # must fail loudly like any other geometry drift, never
            # mix.  Atomic searches journal NO prefix meta (their
            # checkpoint artifacts stay byte-compatible with the
            # pre-prefix format and the prefix_reuse=False escape
            # hatch), so an atomic checkpoint may resume under shared
            # staging: the durable chunks are bit-exact either way and
            # the meta then records the shared grouping going forward
            px_cur = [p["prefix"] for p in plans]
            px_journalled = ckpt.get_meta("prefix_plan")
            if px_journalled is not None:
                if list(px_journalled) != list(px_cur):
                    raise GeometryMismatchError(
                        "checkpoint was written under a different "
                        "shared-prefix grouping (journalled per-group "
                        f"digests = {px_journalled}, current = "
                        f"{px_cur}); resuming would mix prefix-staged "
                        "and atomic chunk results.  Delete "
                        f"{ckpt.path!r} or restore the original grid/"
                        "prefix_reuse configuration.")
            elif any(d is not None for d in px_cur):
                ckpt.put_meta("prefix_plan", px_cur)
        metrics.put("geometry", geo.report_block())
        if rung is not None:
            # rung bookkeeping: remember rung-0 widths (the pin/affinity
            # anchors) and account the lanes this rung's re-plan
            # reclaimed vs. running the SAME survivors at rung-0 widths
            for gg, k in zip(geo.groups, rung_keys):
                rung.base_widths.setdefault(k, int(gg.width))
                rung.last_widths[k] = int(gg.width)
            rung_rec = rung.current
            if rung_rec is not None:
                rung_rec["widths"] = [int(g.width) for g in geo.groups]
                rung_rec["n_launches_planned"] = int(
                    sum(g.n_chunks for g in geo.groups))
                rung_rec["cost_observations"] = int(
                    geo.cost_model.get("n_observations", 0))
                if rung.itr > 0:
                    base_lanes = act_lanes = 0
                    for gg, k in zip(geo.groups, rung_keys):
                        bw = rung.base_widths.get(k, gg.width)
                        base_lanes += (-(-gg.n_candidates // bw)) \
                            * bw * n_folds
                        act_lanes += gg.n_chunks * gg.width * n_folds
                    reclaimed = max(0, base_lanes - act_lanes)
                    rung_rec["lanes_reclaimed"] = int(reclaimed)
                    rung_rec["padding_saved_frac"] = round(
                        reclaimed / base_lanes, 6) if base_lanes else 0.0
                    rung.lanes_reclaimed_total += int(reclaimed)

        for plan, gg in zip(plans, geo.groups):
            gi, nc = plan["gi"], plan["nc"]
            sorted_chunks = plan["sorted"]
            nc_batch = plan["nc_batch"] = int(gg.width)
            # chunk resume state resolved up front: the calibration
            # structure (which chunk calibrates, which chunks fuse) must
            # be known before dispatch, not discovered mid-pipeline
            chunks = []
            for lo in range(0, nc, nc_batch):
                hi = min(lo + nc_batch, nc)
                # sorted chunks write cells through a PERMUTED index set:
                # a checkpoint from an unsorted run must not resume into
                # them (and vice versa), so the id carries the mode.
                # Halving rungs prefix their namespace ("r2:...") so the
                # journal, fault events and trace stay rung-addressable
                # and supervisor bisection keys can never collide
                # across rungs
                chunk_id = cid_ns + f"{gi}:{lo}:{hi}" + \
                    (":s" if sorted_chunks else "")
                rec = ckpt.get(chunk_id) if ckpt is not None else None
                if rec is not None and return_train and \
                        rec.get("train") is None:
                    rec = None  # written without train scores: recompute
                chunks.append((lo, hi, chunk_id, rec))
            plan["chunks"] = chunks
            plan["n_live"] = sum(1 for c in chunks if c[3] is None)

        if ledger is not None and mem_ctx is not None:
            # register every (group, chosen width) footprint with the
            # ledger — the per-group records search_report["memory"]
            # renders, the memory.footprint trace instants
            # trace_summary digests, and the modeled bytes OOM events
            # report against the budget
            mem_ctx["resident_bytes"] = resident_est
            for plan, gg in zip(plans, geo.groups):
                fp = _memledger.model_group_footprint(
                    plan["group"].dynamic_params, plan["nc_batch"],
                    n_folds, **mem_kw)
                rec = {"group": cid_ns + str(plan["gi"]),
                       "width": int(plan["nc_batch"]),
                       "capped": bool(getattr(gg, "capped", False)),
                       "resident_bytes": int(resident_est), **fp}
                plan["mem_chunk_bytes"] = int(fp["chunk_bytes"])
                ledger.note_group(rec)
                mem_ctx["groups"].append(rec)

        def plan_data(plan):
            """The launch data dict: prefix-staged plans swap the raw
            X for their cached per-fold transformed matrices
            (``data_d["X_folds"]``, (F, n, d')); atomic plans share
            the search-wide broadcast dict."""
            return plan.get("data_dev") or data_dev

        # ------------------------------------------------------------------
        # stage 1 — shared-prefix compute (search/prefix.py): one
        # launch per DISTINCT transformer-chain digest, vectorized
        # over folds, with the stacked (F, n, d') matrix cached in the
        # DataPlane as a derived buffer (tenant-charged, labelled with
        # the rung namespace so halving's barrier can demote retired
        # rungs' matrices).  Completion is journaled with a durable
        # npz payload, so kill-resume re-UPLOADS a finished prefix
        # instead of recomputing it.  Digests with no live chunks are
        # skipped entirely — a fully-journaled rung replays without
        # touching the device.
        # ------------------------------------------------------------------
        px_label = (f"prefix.{rung.ns}." if rung is not None
                    and rung.resource == "n_samples" else "prefix.")
        if px_stage:
            from spark_sklearn_tpu.utils import checkpoint as _ckpt_mod
            t_px0 = time.perf_counter()
            distinct = {}
            for plan in plans:
                if plan["prefix"] is not None and plan["n_live"] > 0:
                    distinct.setdefault(plan["prefix"],
                                        []).append(plan)
            x_sharding = getattr(data_dev["X"], "sharding", None)
            base_no_x = {k: v for k, v in data_dev.items()
                         if k != "X"}
            n_computed = n_resumed = n_reused = 0
            px_bytes = 0
            ck_dir = (_os.path.dirname(ckpt.path)
                      if ckpt is not None else None)
            with get_tracer().span("prefix.stage",
                                   n_distinct=len(distinct)):
                for dg, dplans in distinct.items():
                    rep = dplans[0]

                    def _build(_s=rep["static"]):
                        return jax.jit(
                            lambda data_d, w_f:
                            family.prefix_transform(_s, data_d, w_f))

                    # keyed on the DIGEST, not the group statics: two
                    # groups differing only in final-step params share
                    # one compiled transform
                    tf_jit = _cached_program(
                        ("prefix", family, dg, meta, mesh), _build,
                        check_fields={"prefix_digest": dg})
                    aval = jax.eval_shape(tf_jit, data_dev, fit_dev)
                    nbytes = (int(np.prod(aval.shape))
                              * np.dtype(aval.dtype).itemsize)
                    key_parts = (dg, fit_masks_fp(), data_fp,
                                 _dataplane._sharding_key(x_sharding))
                    kp_fp = _ckpt_mod.fingerprint(*key_parts)
                    npz_path = (_os.path.join(ck_dir,
                                              f"prefix_{kp_fp}")
                                if ck_dir is not None else None)
                    ck_meta = (ckpt.get_meta(f"prefix:{kp_fp}")
                               if ckpt is not None else None)
                    how = {}

                    def maker(_ckm=ck_meta, _jit=tf_jit,
                              _path=npz_path, _how=how):
                        if _ckm is not None and _path is not None:
                            try:
                                host = _ckpt_mod.load_pytree(_path)
                                _how["src"] = "resumed"
                                return _dataplane.upload(
                                    np.asarray(host), x_sharding,
                                    label=px_label + "xt")
                            # a journal meta whose npz payload is
                            # missing/torn (killed mid-write) is not
                            # an error: the recompute below is
                            # bit-exact with what the payload held
                            # sstlint: disable=swallowed-exception
                            except Exception:
                                _how.pop("src", None)
                        _how["src"] = "computed"
                        return _jit(data_dev, fit_dev)

                    xt_dev, cache_hit = plane.derived(
                        key_parts, maker, nbytes,
                        label=px_label + "xt", tenant=sched_tenant)
                    if cache_hit:
                        n_reused += 1
                    elif how.get("src") == "resumed":
                        n_resumed += 1
                    else:
                        n_computed += 1
                        jax.block_until_ready(xt_dev)
                        if ckpt is not None and npz_path is not None:
                            _ckpt_mod.save_pytree(
                                npz_path, np.asarray(xt_dev))
                            ckpt.put_meta(f"prefix:{kp_fp}",
                                          {"path": npz_path})
                    px_bytes += nbytes
                    for p in dplans:
                        p["data_dev"] = {**base_no_x,
                                         "X_folds": xt_dev}
            px_state["enabled"] = True
            n_cand_px = sum(p["nc"] for ps in distinct.values()
                            for p in ps)
            px_state["n_candidates_total"] += n_cand_px
            px_state["n_prefixes_distinct"] += len(distinct)
            px_state["n_prefix_launches"] += n_computed
            px_state["n_prefix_reused"] += n_reused
            px_state["n_prefix_resumed"] += n_resumed
            px_state["recompute_saved"] += max(
                0, n_cand_px - n_computed)
            px_state["bytes_cached"] += px_bytes
            px_state["prefix_wall_s"] += round(
                time.perf_counter() - t_px0, 6)

        def build_programs(plan, width=None):
            """The group's jitted programs (cross-search cached); built
            on first need so fully-resumed groups never trace.  `width`
            overrides the group's uniform chunk width — the supervisor's
            OOM bisection relaunches at half width, which is a distinct
            compiled program."""
            nc_batch = width or plan["nc_batch"]
            cache = plan.setdefault("progs_by_width", {})
            progs = cache.get(nc_batch)
            if progs is not None:
                return progs
            static = plan["static"]
            donate_kw = {"donate_argnums": (0,)} if donate else {}
            # prefix-staged plans fit/score the SUFFIX family over the
            # cached per-fold matrices (data_d["X_folds"][fold]); the
            # digest joins every cache/store key below so suffix
            # programs — traced on transformed shapes — never alias
            # the atomic pipeline's programs
            px = plan.get("prefix")
            suffix_fam = family.suffix_family() if px else None

            def _fold_data(data_d, Xf):
                return {**{k: v for k, v in data_d.items()
                           if k != "X_folds"}, "X": Xf}

            if task_batched:
                # flatten (candidate x fold) into one leading task axis and
                # let the family turn it into wide-matmul width (candidate-
                # major order: task t = (cand t//n_folds, fold t%n_folds))

                def fit_batch_tb(dyn_t, data_d, w_t,
                                 static={**static, "__n_folds__": n_folds,
                                         "__bf16__": config.bf16_matmul}):
                    model = family.fit_task_batched(
                        dyn_t, static, data_d, w_t, meta)
                    return jax.tree_util.tree_map(
                        lambda l: l.reshape(
                            (nc_batch, n_folds) + l.shape[1:]), model)

                # the mesh joins the in-memory key exactly as
                # mesh_desc joins the store key (declared-vs-actual
                # drift from the pre-store key path: every other
                # program key already carries it, and a same-shape
                # search on a re-built mesh must not reuse a program
                # whose store proxy was keyed to the old one)
                fit_jit = _cached_program(
                    ("fit_tb", family, static, meta, nc_batch, n_folds,
                     bool(config.bf16_matmul), donate, mesh),
                    lambda: jax.jit(fit_batch_tb, **donate_kw),
                    store_parts=None if donate else (
                        "fit_tb", family.name, static, meta, nc_batch,
                        n_folds, bool(config.bf16_matmul), mesh_desc),
                    store=search_store,
                    check_fields={
                        "bf16_matmul": bool(config.bf16_matmul),
                        "donate_chunk_buffers": donate,
                        "mesh": mesh_desc})

            def fit_batch(dyn_arrs, data_d, train_m, static=static):
                def one_cand(dyn_scalars):
                    if px:
                        # suffix fit: fold f consumes its own cached
                        # transformed matrix — same ops, same order as
                        # the fused inline transform (bit-exact by
                        # construction, pinned by test_prefix.py)
                        def one_fold_px(w, Xf):
                            return suffix_fam.fit(
                                dyn_scalars, static,
                                _fold_data(data_d, Xf), w, meta)
                        return jax.vmap(one_fold_px)(
                            train_m, data_d["X_folds"])

                    def one_fold(w):
                        return family.fit(dyn_scalars, static, data_d, w,
                                          meta)
                    return jax.vmap(one_fold)(train_m)
                return jax.vmap(one_cand)(dyn_arrs)

            def score_batch_wide(models, data_d, test_m, train_m, test_u,
                                 train_u, static=static):
                leaf = jax.tree_util.tree_leaves(models)[0]
                ncb, nf = leaf.shape[0], leaf.shape[1]
                n_tasks = ncb * nf
                flat = jax.tree_util.tree_map(
                    lambda l: l.reshape((n_tasks,) + l.shape[2:]), models)
                views = {}
                if px:
                    # suffix views: task t scores on its fold's cached
                    # matrix — the per-task gather X_folds[t % nf]
                    # fuses into the view matmul under vmap, so no
                    # (T, n, d') operand ever materializes
                    fi_all = jnp.arange(n_tasks, dtype=jnp.int32) % nf
                    xf = data_d["X_folds"]
                    for name in needed_views:
                        views[name] = jax.vmap(
                            lambda m, fi, name=name: build_view(
                                name, suffix_fam, m, static,
                                _fold_data(data_d, xf[fi]), meta))(
                                    flat, fi_all)
                else:
                    wide = getattr(family, "views_task_batched", None)
                    if wide is not None:
                        views = dict(wide(flat, static, data_d, meta,
                                          needed_views))
                    for name in needed_views:
                        if name not in views:
                            views[name] = jax.vmap(
                                lambda m, name=name: build_view(
                                    name, family, m, static, data_d,
                                    meta))(flat)

                y = data_d.get("y")
                # fold masks are indexed per task (t % n_folds,
                # candidate-major flattening) instead of tiled to (T, n):
                # the gather fuses into the reduction cores, where a tile
                # would materialize ncb copies of every mask buffer
                fold_idx = jnp.arange(n_tasks, dtype=jnp.int32) % nf

                def one_task(view_t, fi):
                    wte, wtr = test_m[fi], train_m[fi]
                    wteu, wtru = test_u[fi], train_u[fi]
                    te = {s: fn.core(view_t, y,
                                     wteu if s in sw_blind else wte, meta)
                          for s, fn in scorers.items()}
                    tr = ({s: fn.core(view_t, y,
                                      wtru if s in sw_blind else wtr, meta)
                           for s, fn in scorers.items()}
                          if return_train else {})
                    return te, tr

                te, tr = jax.vmap(one_task)(views, fold_idx)
                return (jax.tree_util.tree_map(
                            lambda a: a.reshape(ncb, nf), te),
                        jax.tree_util.tree_map(
                            lambda a: a.reshape(ncb, nf), tr))

            def score_batch_nested(models, data_d, test_m, train_m, test_u,
                                   train_u, static=static):
                def one_cand(model_c):
                    def one_fold(model, w_test, w_train, w_test_u,
                                 w_train_u):
                        te = {s: fn(family, model, static, data_d, meta,
                                    w_test_u if s in sw_blind else w_test)
                              for s, fn in scorers.items()}
                        tr = ({s: fn(family, model, static, data_d, meta,
                                     w_train_u if s in sw_blind
                                     else w_train)
                               for s, fn in scorers.items()}
                              if return_train else {})
                        return te, tr
                    return jax.vmap(one_fold)(
                        model_c, test_m, train_m, test_u, train_u)
                return jax.vmap(one_cand)(models)

            score_batch = score_batch_wide if all_cores \
                else score_batch_nested

            fused_jit = None
            if fused_mode:
                fit_core = fit_batch_tb if task_batched else fit_batch

                def fused_batch(dyn_t, data_d, w_fit, test_m, train_m,
                                test_u, train_u):
                    models = fit_core(dyn_t, data_d, w_fit)
                    bad = _models_health(models)
                    if bad is None:
                        leaf = jax.tree_util.tree_leaves(models)[0]
                        bad = jnp.zeros(leaf.shape[:2], bool)
                    # executed-iteration counts for FLOP/MFU accounting
                    # (-1 sentinel: family has no iterative solver).
                    # max = lockstep meaning (a launch executes the max
                    # over its lanes); sum = per-lane meaning (scan-
                    # sequential families like SVC execute each lane's
                    # own count) — consumers pick the one that matches
                    # the family's execution model.
                    iters = jnp.int32(-1)
                    iters_sum = jnp.int32(-1)
                    if isinstance(models, dict):
                        it = models.get("n_iter_exec",
                                        models.get("n_iter"))
                        if it is not None:
                            iters = jnp.max(it).astype(jnp.int32)
                            iters_sum = jnp.sum(it).astype(jnp.int32)
                    te, tr = score_batch_wide(models, data_d, test_m,
                                              train_m, test_u, train_u)
                    return te, tr, bad, iters, iters_sum

                fused_jit = _cached_program(
                    ("fused", family, static, meta, nc_batch, n_folds,
                     bool(config.bf16_matmul), mesh, score_key,
                     return_train, sw_blind, donate, px),
                    lambda: jax.jit(fused_batch, **donate_kw),
                    store_parts=None if donate else (
                        "fused", family.name, static, meta, nc_batch,
                        n_folds, bool(config.bf16_matmul), mesh_desc,
                        store_score_names, store_sw_key, return_train,
                        px),
                    store=search_store,
                    check_fields={
                        "bf16_matmul": bool(config.bf16_matmul),
                        "donate_chunk_buffers": donate,
                        "mesh": mesh_desc})
            # separate fit/score programs: the non-fused path runs them
            # for every chunk; the fused path runs them for each group's
            # first live chunk to calibrate the score share that splits
            # later fused walls (sklearn's fit/score time columns must
            # never be a silent 0.0 — VERDICT r4 next #4).  jax.jit is
            # lazy, so a program a search never calls is never traced or
            # compiled.
            if not task_batched:
                fit_jit = _cached_program(
                    ("fit", family, static, meta, mesh, donate, px),
                    lambda: jax.jit(fit_batch, out_shardings=task_shard,
                                    **donate_kw),
                    store_parts=None if donate else (
                        "fit", family.name, static, meta, mesh_desc,
                        px),
                    store=search_store,
                    check_fields={
                        "donate_chunk_buffers": donate,
                        "mesh": mesh_desc})
            # mesh in the in-memory key for the same reason as fit_tb
            # above: the store key always carried mesh_desc, the
            # pre-store in-memory key never did
            score_jit = _cached_program(
                ("score", family, static, meta, score_key, return_train,
                 sw_blind, bool(all_cores), px, mesh),
                lambda: jax.jit(score_batch),
                store_parts=("score", family.name, static, meta,
                             mesh_desc, store_score_names, store_sw_key,
                             return_train, bool(all_cores), px),
                store=search_store,
                check_fields={"mesh": mesh_desc})
            progs = {"fit": fit_jit, "score": score_jit,
                     "fused": fused_jit,
                     # the raw (un-jitted) fused body: the scan program
                     # below wraps it as its lax.scan step function
                     "fused_body": fused_batch if fused_mode else None}
            cache[nc_batch] = progs
            return progs

        def build_scan(plan, n_steps, topk_k=0, hb=False):
            """ONE jitted program executing `n_steps` chunks of the
            group as a `lax.scan` over the stacked chunk axis — the
            melted launch boundary.  The step function is the group's
            fused body, so every lane computes exactly what its solo
            fused launch would (scan carries no cross-lane state into
            the step), and XLA's loop buffer aliasing keeps ONE set of
            model/score working buffers live across steps — the donated
            carry the per-chunk path only gets via donate_chunk_buffers.

            `topk_k > 0` additionally folds the halving rung's
            elimination on device: a score carry (one row per group
            candidate position plus a dump row for padded lanes)
            accumulates each chunk's first-scorer test scores, and the
            program returns the top-k candidate POSITIONS mirroring
            sklearn's `_top_k` (ascending mean with NaNs rolled to the
            front) — rung N+1's candidate set never round-trips scores
            to host.

            `hb=True` threads the heartbeat beacon into the step body:
            the step index rides the scan xs and a jax.debug.callback
            emits (token, step) to the HeartbeatHub while the device is
            mid-launch.  The token is a RUNTIME operand (never baked
            into the trace), so ONE compiled program serves every
            search's segments; the flag joins the cache key below so
            on/off programs never alias, and off leaves the key (and
            the traced program) byte-identical to the beacon-less one.
            """
            cache = plan.setdefault("scan_progs", {})
            ck = (int(n_steps), int(topk_k)) + (("hb",) if hb else ())
            prog = cache.get(ck)
            if prog is not None:
                return prog
            fused_body = build_programs(plan)["fused_body"]
            nc = int(plan["nc"])
            donate_kw = {"donate_argnums": (0,)} if donate else {}
            score0 = scorer_names[0]

            def scan_batch(dyn_st, idx_st, data_d, w_fit, test_m,
                           train_m, test_u, train_u, hb_tok=None):
                if topk_k:
                    carry0 = jnp.full((nc + 1, n_folds),
                                      jnp.float32(errval))
                else:
                    carry0 = jnp.zeros((), jnp.float32)

                def step(carry, xs):
                    if hb:
                        dyn_c, idx_c, step_i = xs
                    else:
                        dyn_c, idx_c = xs
                    te, tr, bad, im, isum = fused_body(
                        dyn_c, data_d, w_fit, test_m, train_m,
                        test_u, train_u)
                    if hb:
                        # in-flight beat: fires on jax's callback
                        # thread as each scan step executes; unordered
                        # (no token threading cost) — the hub takes
                        # the max step either way
                        jax.debug.callback(
                            _heartbeat.device_beat, hb_tok, step_i,
                            ordered=False)
                    if topk_k:
                        # mirror the host-side error_score substitution
                        # BEFORE the mean, so the device ranking sees
                        # the same scores sklearn's _top_k would
                        sc = jnp.where(
                            bad, jnp.float32(errval),
                            te[score0].astype(jnp.float32))
                        carry = carry.at[idx_c].set(sc)
                    return carry, (te, tr, bad, im, isum)

                xs = (dyn_st, idx_st)
                if hb:
                    xs = xs + (jnp.arange(n_steps, dtype=jnp.int32),)
                carry, ys = lax.scan(step, carry0, xs)
                if topk_k:
                    mean = carry[:nc].mean(axis=1)
                    order = jnp.roll(jnp.argsort(mean),
                                     jnp.count_nonzero(jnp.isnan(mean)))
                    surv = order[-topk_k:].astype(jnp.int32)
                else:
                    surv = jnp.zeros((0,), jnp.int32)
                return ys, surv

            # the nan error_score (the default) breaks dict-key
            # equality (nan != nan), so the key carries its repr; scan
            # programs skip the persistent program store — the
            # exported-wrapper path has no scan coverage yet, and a
            # store-warm process still skips the python->HLO walk via
            # this cache
            # the beacon's presence joins the cache key ONLY when on:
            # the off-state tuple is byte-identical to the beacon-less
            # engine's, and on/off programs can never alias (a cached
            # beacon-less program must not serve a heartbeat fit)
            scan_jit = _cached_program(
                ("scan", family, plan["static"], meta, plan["nc_batch"],
                 n_folds, int(n_steps), bool(config.bf16_matmul), mesh,
                 score_key, return_train, sw_blind, donate,
                 int(topk_k), nc, repr(float(errval)),
                 plan.get("prefix"))
                + (("hb",) if hb else ()),
                lambda: jax.jit(scan_batch, **donate_kw),
                store_parts=None,
                check_fields={
                    "bf16_matmul": bool(config.bf16_matmul),
                    "donate_chunk_buffers": donate,
                    "heartbeat": bool(hb),
                    "mesh": mesh_desc})
            cache[ck] = scan_jit
            return scan_jit

        def group_masks(plan):
            """The group's fit-mask device buffer.  Task-batched families
            consume the fold masks tiled to the launch width — under the
            data plane the tile is a cached ON-DEVICE broadcast of the
            already-resident base masks (uploaded at most once per
            search, reused across groups sharing a width, OOM relaunches
            and subsequent searches); the legacy path host-tiles lazily
            on the stage thread, once per group."""
            if not task_batched:
                return fit_dev
            if plane is not None:
                # memoized per plan: stage() asks once per chunk, and
                # re-hashing the full mask array every launch would put
                # serial host work back on the stage thread
                w = plan.get("w_task_dev")
                if w is None:
                    w = plan["w_task_dev"] = plane.tiled(
                        fit_masks, fit_dev, plan["nc_batch"],
                        tb_mask_shard, label=tiled_label,
                        fp=fit_masks_fp(), tenant=sched_tenant)
                return w
            w = plan.get("w_task_dev")
            if w is None:
                w = _dataplane.upload(
                    np.tile(fit_masks, (plan["nc_batch"], 1)),
                    tb_mask_shard, label=tiled_label)
                plan["w_task_dev"] = w
            return w

        #: guards the per-plan staged-chunk bookkeeping: stage normally
        #: runs on the single stage thread, but supervisor retries
        #: re-stage on whichever thread is recovering
        stage_lock = named_lock("grid.stage_lock")

        cache0 = persistent_cache_counts()
        builds0 = _program_build_count()
        if rung is not None:
            # whole-search baselines: the final pipeline block's
            # n_compiles / persistent-cache deltas cover every rung
            if rung.cache0 is None:
                rung.cache0, rung.builds0 = cache0, builds0
            cache0, builds0 = rung.cache0, rung.builds0
        # multi-controller runs gather through process_allgather — a
        # cross-process COLLECTIVE.  Issuing collectives from background
        # threads would need every process to interleave them in the
        # same order as its peers; the synchronous schedule guarantees
        # that, the pipelined one does not — so multihost forces depth 0
        # (`depth` was resolved with the data-plane setup above)
        if rung is not None and rung.pipeline is not None:
            # rung barrier = drain + re-stage: the rungs of one halving
            # search share ONE pipeline (run() accumulates the timeline
            # and wall), so its compile thread stays warm and the final
            # report covers the whole search.  The previous rung's
            # close was a drain() — no straggler AOT job outlives its
            # rung's jax config.
            pipe = rung.pipeline
        else:
            pipe = ChunkPipeline(depth, verbose=self.verbose,
                                 heartbeat=hb_on)
            if rung is not None:
                rung.pipeline = pipe

        def submit_precompile(plan):
            """AOT-lower/compile the group's fused program on the compile
            thread so the group boundary does not stall the device.  The
            executable is bit-identical to the jit path (same jaxpr, same
            compile options); failure here only means the jit path
            compiles at first dispatch, as it always did."""
            if plan.get("aot_submitted") or pipe.depth == 0 \
                    or not fused_mode or scan_mode \
                    or plan["n_live"] < 2:
                # scan mode has no per-chunk fused dispatch to warm:
                # its program compiles once at the segment launch
                return
            plan["aot_submitted"] = True
            try:
                progs = build_programs(plan)
                nc_batch = plan["nc_batch"]
                lanes = nc_batch * n_folds
                dyn_spec = {}
                for k, arr in plan["group"].dynamic_params.items():
                    shape = ((lanes,) if task_batched
                             else (nc_batch,)) + arr.shape[1:]
                    dyn_spec[k] = jax.ShapeDtypeStruct(
                        shape, arr.dtype, sharding=task_shard)
                if not dyn_spec and not task_batched:
                    dyn_spec["_pad"] = jax.ShapeDtypeStruct(
                        (nc_batch,), dtype, sharding=task_shard)
                if task_batched:
                    w_spec = jax.ShapeDtypeStruct(
                        (lanes,) + fit_masks.shape[1:],
                        fit_masks.dtype, sharding=tb_mask_shard)
                else:
                    w_spec = fit_dev
                plan["aot_future"] = pipe.submit_precompile(
                    progs["fused"], dyn_spec, plan_data(plan), w_spec,
                    test_dev, train_sc_dev, test_unw_dev, train_unw_dev,
                    label=f"fused group {plan['gi']}")
            # sstlint: disable=launch-except-taxonomy — AOT compile-ahead
            # is an optimization only: any failure here means the jit
            # path compiles at first dispatch, exactly as it always did
            except Exception as exc:   # AOT is an optimization only
                logger.debug("fused precompile submission failed: %r", exc)

        def resolve_fused(plan):
            """The callable for this group's fused chunks: the AOT
            executable when the compile thread produced one, the plain
            jit program otherwise (identical results either way)."""
            call = plan.get("fused_call")
            if call is not None:
                return call
            jit_fn = build_programs(plan)["fused"]
            call = jit_fn
            fut = plan.pop("aot_future", None)
            if fut is not None:
                try:
                    exe = fut.result()

                    def call(*args, _exe=exe, _jit=jit_fn, _plan=plan):
                        try:
                            return _exe(*args)
                        except (TypeError, ValueError):
                            # aval/sharding mismatch only: drop to jit
                            # forever.  Genuine runtime failures (OOM,
                            # XlaRuntimeError) must propagate — retrying
                            # the identical program via jit would only
                            # recompile and fail again with the original
                            # context lost
                            _plan["fused_call"] = _jit
                            return _jit(*args)
                # sstlint: disable=launch-except-taxonomy — consuming a
                # failed AOT future: the plain jit program below is the
                # sanctioned identical-results fallback
                except Exception as exc:
                    logger.debug("fused precompile failed (%r); "
                                 "falling back to jit", exc)
            plan["fused_call"] = call
            return call

        # ------------------------------------------------------------------
        # OOM recovery: bisected relaunch + per-candidate host bottom-out
        # (hooks consumed by the launch supervisor, parallel/faults.py)
        # ------------------------------------------------------------------
        def host_fused_range(plan, lo, hi, sup, chunk_id):
            """Candidates [lo, hi) of the plan's group on the host —
            sklearn `_fit_and_score` per (candidate, fold) with exact
            error_score semantics — shaped like the fused gather."""
            idx = plan["group"].candidate_indices[lo:hi]
            sup.record_host_fallback(f"{chunk_id}[{lo}:{hi}]",
                                     plan["gi"], len(idx) * n_folds)
            te, tr = host_eval(idx)
            bad = np.zeros((hi - lo, n_folds), bool)
            return te, tr, bad, -1, -1

        def merge_fused(a, b):
            te = {s: np.concatenate([a[0][s], b[0][s]]) for s in a[0]}
            tr = {s: np.concatenate([a[1][s], b[1][s]]) for s in a[1]}
            bad = np.concatenate([a[2], b[2]])
            im = max(a[3], b[3])
            isum = a[4] + b[4] if a[4] >= 0 and b[4] >= 0 \
                else max(a[4], b[4])
            return te, tr, bad, im, isum

        def exec_fused_range(plan, lo, hi, sup, chunk_id):
            """Relaunch candidates [lo, hi) as one fused program at the
            narrowest padded width (lanes re-padded via
            taskgrid.pad_chunk), recursing on further OOMs down to
            single candidates and finally the host path.  Returns
            host-side (te, tr, bad, iters, iters_sum) with exactly
            hi - lo real rows — per-lane results are bit-identical to
            the full-width launch (vmap lanes are independent), so a
            successful recovery keeps cv_results_ exact."""
            group = plan["group"]
            n = hi - lo
            width = max(n_task_shards,
                        mesh_lib.pad_to_multiple(n, n_task_shards))
            key = f"{chunk_id}[{lo}:{hi}]"

            def attempt():
                progs = build_programs(plan, width=width)
                dyn = {}
                for k, arr in group.dynamic_params.items():
                    dyn[k] = _dataplane.upload(
                        pad_chunk(arr, lo, hi, width,
                                  n_folds if task_batched else 1),
                        task_shard, label="dyn.recover")
                if not dyn and not task_batched:
                    dyn["_pad"] = (
                        plane.zeros(width, dtype, task_shard,
                                    tenant=sched_tenant)
                        if plane is not None and not donate else
                        _dataplane.upload(np.zeros(width, dtype=dtype),
                                          task_shard, label="dyn.pad"))
                if task_batched:
                    # the bisected width's tiled masks come from the
                    # same plane cache — a recovery revisiting a width
                    # re-tiles on device at most once, never per
                    # relaunch (the old per-relaunch host np.tile)
                    w = (plane.tiled(fit_masks, fit_dev, width,
                                     tb_mask_shard,
                                     label=tiled_label,
                                     fp=fit_masks_fp(),
                                     tenant=sched_tenant)
                         if plane is not None else
                         _dataplane.upload(
                             np.tile(fit_masks, (width, 1)),
                             tb_mask_shard, label=tiled_label))
                else:
                    w = fit_dev
                out = progs["fused"](dyn, plan_data(plan), w, test_dev,
                                     train_sc_dev, test_unw_dev,
                                     train_unw_dev)
                out = sup.wait_ready(out, key=key, group=plan["gi"])
                te_d, tr_d, bad_d, im_d, isum_d = out
                te = {s: np.asarray(mesh_lib.device_get_tree(v))[:n]
                      for s, v in te_d.items()}
                tr = {s: np.asarray(mesh_lib.device_get_tree(v))[:n]
                      for s, v in tr_d.items()}
                bad = np.asarray(mesh_lib.device_get_tree(bad_d))[:n]
                return te, tr, bad, int(im_d), int(isum_d)

            try:
                return sup.call(attempt, key=key, group=plan["gi"],
                                n_real=n)
            except Exception as exc:
                if _faults.is_oom(exc):
                    if n <= 1:
                        return host_fused_range(plan, lo, hi, sup,
                                                chunk_id)
                    sup.record_bisection(key, plan["gi"])
                    from spark_sklearn_tpu.parallel.taskgrid import (
                        split_range)
                    lo_, mid, hi_ = split_range(lo, hi)
                    return merge_fused(
                        exec_fused_range(plan, lo_, mid, sup, chunk_id),
                        exec_fused_range(plan, mid, hi_, sup, chunk_id))
                # poison-candidate quarantine (best_effort only — the
                # supervisor arms quarantine_k solely under
                # partial_results='best_effort'): FATAL ranges split
                # like OOM; a single-lane range that still faults K
                # times is quarantined to error_score instead of
                # killing the search
                if not getattr(sup, "quarantine_k", 0) \
                        or getattr(exc, "_sst_cancelled", False) \
                        or _faults.classify_error(exc) != _faults.FATAL:
                    raise
                if n > 1:
                    sup.record_bisection(key, plan["gi"],
                                         fault_class=_faults.FATAL)
                    from spark_sklearn_tpu.parallel.taskgrid import (
                        split_range)
                    lo_, mid, hi_ = split_range(lo, hi)
                    return merge_fused(
                        exec_fused_range(plan, lo_, mid, sup, chunk_id),
                        exec_fused_range(plan, mid, hi_, sup, chunk_id))
                n_faults = sup.note_fatal(key)
                if n_faults < sup.quarantine_k:
                    return exec_fused_range(plan, lo, hi, sup, chunk_id)
                sup.record_quarantine(key, plan["gi"], exc, n_faults)
                if pctx is not None:
                    pctx["quarantined"].append({
                        "key": key,
                        "group": int(plan["gi"]),
                        "candidates": [
                            int(i) for i in
                            plan["group"].candidate_indices[lo:hi]],
                        "error": f"{type(exc).__name__}: {exc}"[:300],
                        "n_faults": int(n_faults)})
                te = {s: np.full((n, n_folds), errval)
                      for s in scorer_names}
                tr = ({s: np.full((n, n_folds), errval)
                       for s in scorer_names} if return_train else {})
                bad = np.zeros((n, n_folds), bool)
                return te, tr, bad, -1, -1

        def make_bisect_fused(plan, lo, hi, chunk_id):
            def bisect(sup):
                if hi - lo <= 1:
                    return host_fused_range(plan, lo, hi, sup, chunk_id)
                sup.record_bisection(chunk_id, plan["gi"])
                from spark_sklearn_tpu.parallel.taskgrid import split_range
                lo_, mid, hi_ = split_range(lo, hi)
                return merge_fused(
                    exec_fused_range(plan, lo_, mid, sup, chunk_id),
                    exec_fused_range(plan, mid, hi_, sup, chunk_id))
            return bisect

        # ------------------------------------------------------------------
        # cross-search launch fusion (the executor's FusedLaunch seam):
        # a FuseSpec is this chunk's offer to share one wide device
        # launch with same-program chunks from OTHER searches.  Equal
        # keys guarantee the members run the SAME compiled fused
        # program on the SAME resident broadcast buffers (the data
        # plane dedups identical uploads, so shared X/y means shared
        # device objects), so concatenating their real rows and
        # re-padding once is exactly the bisection-recovery relaunch
        # shape — per-lane results are bit-identical to each member's
        # solo launch (vmap lanes are independent).
        # ------------------------------------------------------------------
        def make_fuse_spec(plan, lo, hi, chunk_id):
            group = plan["group"]
            fkey = (
                "sst-fuse-v1", family.name, freeze(plan["static"]),
                freeze(meta), int(n_folds),
                bool(config.bf16_matmul), mesh_desc,
                store_score_names, store_sw_key, bool(return_train),
                bool(sw_blind), str(np.dtype(dtype)),
                int(n_task_shards), bool(task_batched),
                tuple(sorted(group.dynamic_params)), fit_masks_fp(),
                plan.get("prefix"),
                # device-buffer identities: live refs are held by the
                # member closures, so ids are stable for the launch's
                # lifetime, and the plane's dedup makes equal content
                # mean equal objects across searches (the prefix-staged
                # plans pass their own derived per-fold matrices here)
                tuple(id(leaf) for leaf in
                      jax.tree_util.tree_leaves(plan_data(plan))),
                id(fit_dev), id(test_dev), id(train_sc_dev),
                id(test_unw_dev), id(train_unw_dev))
            _keycheck.note(
                "fuse_spec", fkey,
                fields={"bf16_matmul": bool(config.bf16_matmul)},
                detail=family.name)

            def rows(group=group, lo=lo, hi=hi):
                return {k: np.asarray(arr[lo:hi])
                        for k, arr in group.dynamic_params.items()}

            def run(specs, plan=plan):
                total = sum(int(s.n) for s in specs)
                width = max(n_task_shards,
                            mesh_lib.pad_to_multiple(total,
                                                     n_task_shards))
                repeat = n_folds if task_batched else 1
                progs = build_programs(plan, width=width)
                member_rows = [s.rows() for s in specs]
                dyn = {}
                for k in sorted(member_rows[0]):
                    cat = np.concatenate(
                        [np.asarray(r[k]) for r in member_rows])
                    dyn[k] = _dataplane.upload(
                        pad_chunk(cat, 0, total, width, repeat),
                        task_shard, label="dyn.fuse")
                if not dyn and not task_batched:
                    dyn["_pad"] = (
                        plane.zeros(width, dtype, task_shard,
                                    tenant=sched_tenant)
                        if plane is not None else
                        _dataplane.upload(
                            np.zeros(width, dtype=dtype),
                            task_shard, label="dyn.pad"))
                if task_batched:
                    w = (plane.tiled(fit_masks, fit_dev, width,
                                     tb_mask_shard, label=tiled_label,
                                     fp=fit_masks_fp(),
                                     tenant=sched_tenant)
                         if plane is not None else
                         _dataplane.upload(
                             np.tile(fit_masks, (width, 1)),
                             tb_mask_shard, label=tiled_label))
                else:
                    w = fit_dev
                return progs["fused"](dyn, plan_data(plan), w, test_dev,
                                      train_sc_dev, test_unw_dev,
                                      train_unw_dev)

            def slice_out(out, off, n):
                te, tr, bad, im, isum = out
                return ({s: v[off:off + n] for s, v in te.items()},
                        {s: v[off:off + n] for s, v in tr.items()},
                        bad[off:off + n], im, isum)

            # the fused width may legitimately exceed one chunk's solo
            # batch bound (that is the point of fusion); the honest
            # ceiling is the HBM width cap when the ledger modeled one
            # (0 = unbounded — an over-wide fused OOM still recovers,
            # each member bisecting its own range)
            cap = mem_caps[plan["gi"]] if mem_caps is not None else None
            return FuseSpec(key=fkey, n=hi - lo,
                            shard=int(n_task_shards),
                            max_width=int(cap) if cap else 0,
                            rows=rows, run=run, slice_out=slice_out)

        # quarantine armed: the first-chunk fit/score items also carry
        # an isolate hook (below), so a poison candidate in ANY chunk
        # routes through the fused-range recursion instead of the
        # whole-search degradation path.  Off (the default), those
        # items keep exactly their pre-protection shape.
        quarantine_armed = (
            pctx is not None
            and str(getattr(config, "partial_results", "raise")
                    or "raise") == "best_effort"
            and int(getattr(config, "quarantine_fatal_k", 3) or 0) > 0)

        def make_bisect_fit(plan, lo, hi, chunk_id, cstate, lanes):
            inner = make_bisect_fused(plan, lo, hi, chunk_id)

            def bisect(sup):
                te, tr, bad, im, isum = inner(sup)
                # the score item consumes the recovered cells instead
                # of launching (same contract as the OOM host fallback)
                cstate["host"] = (te, tr)
                if im >= 0:
                    record_iters(im, isum, lanes)
                return np.asarray(bad, bool), None
            return bisect

        def make_bisect_score(plan, lo, hi, chunk_id):
            inner = make_bisect_fused(plan, lo, hi, chunk_id)

            def bisect(sup):
                te, tr, bad, im, isum = inner(sup)
                return te, tr
            return bisect

        def write_cells(plan, idx, lo, hi, chunk_id, te, tr, t_fit,
                        t_score, count_launch=True):
            # charge the launch wall to the REAL candidates in the chunk
            # (not the padded lane count), so summing ALL per-split
            # fit-time cells (mean_fit_time x n_splits over candidates)
            # reconstructs the true device wall; XLA fuses all lanes
            # into one program, so a finer per-candidate split is not
            # measurable (ROADMAP)
            n_real = (hi - lo) * n_folds
            fit_times[idx, :] = t_fit / n_real
            score_times[idx, :] = t_score / n_real
            for s in scorer_names:
                test_scores[s][idx, :] = np.asarray(te[s])[:hi - lo]
                if return_train:
                    train_scores[s][idx, :] = \
                        np.asarray(tr[s])[:hi - lo]
            if count_launch:
                # scan segments call this once per MEMBER chunk (the
                # per-chunk journal records give segment-granular
                # resume for free) but count their one real launch in
                # the segment finalize instead
                metrics.counter("n_launches").inc()
            metrics.gauge("fit_wall_s").add(t_fit)
            metrics.gauge("score_wall_s").add(t_score)
            lanes_launch = plan["nc_batch"] * n_folds
            metrics.histogram("padding_waste").observe(
                (lanes_launch - n_real) / lanes_launch)
            # per-compile-group walls: candidates in different groups
            # (or chunks) carry genuinely different launch timings —
            # only candidates fused into ONE launch share a per-launch
            # average (XLA executes them as one program, so a finer
            # split is not measurable; see ROADMAP)
            rec = per_group_rec(plan)
            if count_launch:
                rec["n_launches"] += 1
            rec["fit_wall_s"] += t_fit
            rec["score_wall_s"] += t_score
            if self.verbose > 1:
                self._print_task_end_lines(
                    candidates, idx, n_folds, scorer_names,
                    test_scores, train_scores, return_train,
                    (t_fit + t_score) / n_real, fit_failed)
            if ckpt is not None:
                ckpt.put(chunk_id, {
                    "test": {s: test_scores[s][idx, :].tolist()
                             for s in scorer_names},
                    "train": ({s: train_scores[s][idx, :].tolist()
                               for s in scorer_names}
                              if return_train else None),
                    "fit_t": t_fit / n_real,
                    "score_t": t_score / n_real,
                    "failed": fit_failed[idx, :].tolist()})
            if pctx is not None:
                # degradation never overwrites a candidate with real
                # (or host-recovered) cells
                pctx["done"][idx] = True

        def per_group_rec(plan):
            pg = metrics.struct("per_group")
            # rung-namespaced key: a halving search's shared registry
            # must not merge rung 2's group 0 into rung 0's group 0
            key = cid_ns + str(plan["gi"]) if rung is not None \
                else plan["gi"]
            return pg.setdefault(key, {
                "static_params": repr(plan["group"].static_params),
                "n_launches": 0, "fit_wall_s": 0.0, "score_wall_s": 0.0,
                "score_path": ("scan-fused" if scan_mode else
                               "wide-fused" if fused_mode else
                               "wide" if all_cores else "nested")})

        def record_iters(it_max, it_sum, lanes):
            metrics.series("solver_iters_per_launch").append(int(it_max))
            metrics.series("solver_iters_sum_per_launch").append(
                int(it_sum))
            metrics.series("lanes_per_launch").append(int(lanes))

        def replay_chunk(idx, rec):
            """Write a journalled chunk's cells back — shared by the
            per-chunk and scan dispatch paths, so resume semantics are
            loop-mode-invariant."""
            for s_ in scorer_names:
                test_scores[s_][idx, :] = np.asarray(rec["test"][s_])
                if return_train:
                    train_scores[s_][idx, :] = np.asarray(
                        rec["train"][s_])
            fit_times[idx, :] = rec["fit_t"]
            score_times[idx, :] = rec["score_t"]
            if rec.get("failed") is not None:
                fit_failed[idx, :] |= np.asarray(rec["failed"], bool)
            metrics.counter("n_chunks_resumed").inc()
            if pctx is not None:
                pctx["done"][idx] = True

        def shed_chunk(idx, chunk_id):
            """True when the search deadline expired and this chunk was
            shed to error_score (best_effort); raises under
            partial_results='raise'.  Shared by both dispatch paths."""
            if pctx is None or pctx["t_deadline"] is None \
                    or time.perf_counter() < pctx["t_deadline"]:
                return False
            elapsed = time.perf_counter() - pctx["t_start"]
            if str(getattr(config, "partial_results", "raise")
                   or "raise") != "best_effort":
                raise _faults.SearchDeadlineError(
                    float(config.search_deadline_s), elapsed,
                    n_remaining=int((~pctx["done"]).sum()))
            if not pctx["deadline_hit"]:
                pctx["deadline_hit"] = True
                _telemetry.note_protection("deadline_hit")
                logger.warning(
                    "search deadline %.3gs expired after %.3fs: "
                    "shedding the remaining chunks to error_score "
                    "(partial_results='best_effort')",
                    float(config.search_deadline_s), elapsed,
                    chunk=chunk_id)
            # un-run candidates carry sklearn's error_score with ZERO
            # times (like a fit that never ran) — declared in the
            # protection block, NOT routed through fit_failed
            for s_ in scorer_names:
                test_scores[s_][idx, :] = errval
                if return_train:
                    train_scores[s_][idx, :] = errval
            fit_times[idx, :] = 0.0
            score_times[idx, :] = 0.0
            pctx["done"][idx] = True
            pctx["shed"].append({
                "reason": "deadline", "chunk": chunk_id,
                "candidates": [int(i) for i in idx]})
            _telemetry.note_protection("shed", len(idx))
            return True

        def scan_plan_items(plan):
            """The plan's live chunks as scan-segment LaunchItems: each
            segment stacks its member chunks' operands along a leading
            step axis and executes them as ONE `lax.scan` launch
            (build_scan above).  Segment length is planned against the
            memory ledger (taskgrid.plan_scan_segments): the stacked
            operands and the top-k carry are priced BEFORE launch, and
            an OOM that still slips through falls back to the
            per-chunk path for that segment only (the bisect hook)."""
            gi, group = plan["gi"], plan["group"]
            nc_batch = plan["nc_batch"]
            lanes = nc_batch * n_folds
            repeat = n_folds if task_batched else 1
            live = []
            for lo, hi, chunk_id, rec in plan["chunks"]:
                idx = group.candidate_indices[lo:hi]
                if rec is not None:
                    replay_chunk(idx, rec)
                    continue
                if shed_chunk(idx, chunk_id):
                    continue
                live.append((lo, hi, chunk_id))
            if not live:
                return
            # device-resident rung elimination is gated to the shapes
            # where the carry's candidate-position rows are the whole
            # rung: one compile group, one scorer, zero resumed/shed
            # chunks, and (below) a single segment — any partial shape
            # falls back to sklearn's host _top_k, which reads the
            # same scores from cv_results_ either way
            topk_k = 0
            if rung is not None and len(plans) == 1 \
                    and len(scorer_names) == 1 \
                    and len(live) == len(plan["chunks"]):
                k = int(getattr(rung, "keep_next", 0) or 0)
                if 0 < k < int(plan["nc"]):
                    topk_k = k
            carry_bytes = (int(plan["nc"]) + 1) * n_folds * 4 \
                if topk_k else 0
            # per-step stacked bytes: the dynamic operand rows plus the
            # stacked per-step outputs (scores/bad/iters) — the model
            # working set itself is step-reused by XLA's loop aliasing
            # and is priced once via reserved_bytes
            chunk_dyn_bytes = 0
            for arr in group.dynamic_params.values():
                per = 1
                for d in arr.shape[1:]:
                    per *= int(d)
                chunk_dyn_bytes += nc_batch * repeat * per \
                    * int(arr.dtype.itemsize)
            out_bytes = nc_batch * n_folds * (
                len(scorer_names) * (2 if return_train else 1)
                * int(np.dtype(dtype).itemsize) + 1) + 8
            budget = int(mem_ctx.get("budget_bytes", 0)) \
                if mem_ctx is not None else 0
            seg_plan = plan_scan_segments(
                len(live), chunk_bytes=chunk_dyn_bytes + out_bytes,
                carry_bytes=carry_bytes, budget_bytes=budget,
                reserved_bytes=int(resident_est)
                + int(plan.get("mem_chunk_bytes", 0)))
            if seg_plan.capped:
                topk_k = 0   # the carry cannot cross launches
                cl_state["fallbacks"].append(
                    f"segment-capped:{cid_ns}{gi}")
            cl_state["n_segments"] += seg_plan.n_segments

            for si, (slo, shi) in enumerate(seg_plan.segments()):
                members = live[slo:shi]
                n_steps = len(members)
                seg_key = cid_ns + f"{gi}:scan{si}"
                seg_tasks = sum((hi - lo) * n_folds
                                for lo, hi, _ in members)
                seg_topk = topk_k if n_steps == len(live) else 0
                # per-step cost estimate seeding the ETA blend: the
                # geometry model's launch overhead amortizes across the
                # scanned steps, lane cost scales with the segment's
                # lane width — observed beat cadence refines this as
                # beats arrive (heartbeat._Segment.blended_step_s)
                hb_est = hb_overhead_s / max(1, n_steps) \
                    + hb_lane_cost_s * lanes

                def stage(members=members, plan=plan, n_steps=n_steps,
                          seg_key=seg_key, si=si, hb_est=hb_est):
                    with get_tracer().span(
                            "chunkloop.segment", group=plan["gi"],
                            n_chunks=n_steps):
                        dyn = {}
                        for k, arr in \
                                plan["group"].dynamic_params.items():
                            rows = np.stack([
                                pad_chunk(arr, lo, hi, nc_batch, repeat)
                                for lo, hi, _ in members])
                            dyn[k] = _dataplane.upload(
                                rows, scan_shard, label="dyn.scan")
                        if not dyn and not task_batched:
                            dyn["_pad"] = _dataplane.upload(
                                np.zeros((n_steps, nc_batch),
                                         dtype=dtype),
                                scan_shard, label="dyn.scan.pad")
                        # per-step candidate POSITIONS for the top-k
                        # carry scatter (padded lanes hit the dump
                        # row); always staged — the non-topk program
                        # ignores it, and the shape keeps one item
                        # contract for both
                        idx_rows = np.full((n_steps, nc_batch),
                                           int(plan["nc"]), np.int32)
                        for i, (lo, hi, _) in enumerate(members):
                            idx_rows[i, :hi - lo] = np.arange(
                                lo, hi, dtype=np.int32)
                        idx_st = _dataplane.upload(
                            idx_rows, repl_shard, label="dyn.scan.idx")
                        w = group_masks(plan)
                        with stage_lock:
                            done = plan.setdefault("staged_ids", set())
                            for _, _, cid in members:
                                done.add(cid)
                            if len(done) >= plan["n_live"]:
                                plan.pop("w_task_dev", None)
                        # heartbeat segment registration happens at
                        # stage time (before dispatch) so a launch
                        # that never produces a beat still shows up
                        # stale to the watchdog
                        tok = None
                        if hb_on:
                            tok = _heartbeat.get_hub().register_segment(
                                seg_key, group=plan["gi"], segment=si,
                                n_steps=n_steps, scope=hb_scope,
                                handle=hb_handle, tenant=hb_tenant,
                                est_step_s=hb_est)
                        return dyn, idx_st, w, tok

                def launch(payload, plan=plan, n_steps=n_steps,
                           seg_topk=seg_topk):
                    dyn, idx_st, w, tok = payload
                    # the trace pin for "no score round-trip": a rung
                    # scanned with topk > 0 ran its elimination inside
                    # this one launch
                    with get_tracer().span(
                            "chunkloop.scan", group=plan["gi"],
                            n_chunks=n_steps, topk=seg_topk):
                        if tok is not None:
                            # token as RUNTIME operand — the compiled
                            # scan program is shared across searches
                            return build_scan(
                                plan, n_steps, seg_topk, hb=True)(
                                dyn, idx_st, plan_data(plan), w, test_dev,
                                train_sc_dev, test_unw_dev,
                                train_unw_dev,
                                np.asarray(tok, np.int32))
                        return build_scan(plan, n_steps, seg_topk)(
                            dyn, idx_st, plan_data(plan), w, test_dev,
                            train_sc_dev, test_unw_dev, train_unw_dev)

                def gather(out, members=members, seg_topk=seg_topk):
                    ys, surv = out
                    te_st, tr_st, bad_st, im_st, isum_st = ys
                    te_h = {s: np.asarray(mesh_lib.device_get_tree(v))
                            for s, v in te_st.items()}
                    tr_h = {s: np.asarray(mesh_lib.device_get_tree(v))
                            for s, v in tr_st.items()}
                    bad_h = np.asarray(mesh_lib.device_get_tree(bad_st))
                    im_h = np.asarray(mesh_lib.device_get_tree(im_st))
                    isum_h = np.asarray(
                        mesh_lib.device_get_tree(isum_st))
                    chunks = []
                    for i in range(len(members)):
                        chunks.append((
                            {s: v[i] for s, v in te_h.items()},
                            {s: v[i] for s, v in tr_h.items()},
                            bad_h[i], int(im_h[i]), int(isum_h[i])))
                    surv_h = (np.asarray(
                        mesh_lib.device_get_tree(surv))
                        if seg_topk else None)
                    return {"chunks": chunks, "survivors": surv_h}

                def bisect(sup, members=members, plan=plan,
                           seg_key=seg_key):
                    # OOM on the scanned segment: fall back to the
                    # per-chunk path for THIS segment only — each
                    # member relaunches through the existing fused
                    # bisection recursion (host bottom-out included),
                    # and the rung's elimination reverts to host
                    # _top_k (survivors never set)
                    sup.record_bisection(seg_key, plan["gi"])
                    cl_state["fallbacks"].append(
                        f"oom-per-chunk:{cid_ns}{plan['gi']}")
                    chunks = [exec_fused_range(plan, lo, hi, sup, cid)
                              for lo, hi, cid in members]
                    return {"chunks": chunks, "survivors": None}

                def finalize(host, tm, members=members, plan=plan,
                             seg_topk=seg_topk, lanes=lanes,
                             seg_key=seg_key):
                    if hb_on:
                        # runs after scan success AND after the OOM
                        # per-chunk fallback (bisect), so progress
                        # always lands on steps_total for the segment
                        _heartbeat.get_hub().complete_segment(seg_key)
                    chunks = host["chunks"]
                    wall = tm.dispatch_s + tm.compute_s + tm.gather_s
                    total_real = sum((hi - lo) * n_folds
                                     for lo, hi, _ in members)
                    for (lo, hi, chunk_id), \
                            (te, tr, bad, im, isum) in \
                            zip(members, chunks):
                        idx = plan["group"].candidate_indices[lo:hi]
                        n_real = (hi - lo) * n_folds
                        # the melted boundary makes per-chunk walls
                        # unmeasurable: the segment wall splits by
                        # real lanes and scoring is folded into fit
                        # ("folded" attribution in the chunkloop
                        # block) — time columns are estimates, scores
                        # are exact
                        t_fit = wall * n_real / max(1, total_real)
                        fit_failed[idx, :] |= np.asarray(
                            bad[:hi - lo], bool)
                        if im >= 0:
                            record_iters(im, isum, lanes)
                        write_cells(plan, idx, lo, hi, chunk_id, te,
                                    tr, t_fit, 0.0, count_launch=False)
                    metrics.counter("n_launches").inc()
                    rec = per_group_rec(plan)
                    rec["n_launches"] += 1
                    cl_state["n_chunks_scanned"] += len(members)
                    cl_state["segment_lengths"].append(len(members))
                    cl_state["n_launches_saved"] += len(members) - 1
                    surv = host.get("survivors")
                    if surv is not None and rung is not None:
                        # device positions -> rung candidate indices,
                        # in sklearn _top_k order (ascending mean) —
                        # halving consumes these instead of its host
                        # elimination
                        rung.device_survivors = np.asarray(
                            plan["group"].candidate_indices)[
                                np.asarray(surv, int)]
                        cl_state["rung_topk_device"] += 1

                yield LaunchItem(
                    key=seg_key, kind="scan", group=gi,
                    n_tasks=seg_tasks, n_chunks=n_steps, stage=stage,
                    launch=launch, gather=gather, finalize=finalize,
                    bisect=bisect)

        def chunk_items():
            """Yield this search's LaunchItems in dispatch order.  Runs
            on the dispatching thread: the group-level work between
            yields (program build, AOT future consumption) overlaps the
            already-dispatched launches' device compute."""
            for pi, plan in enumerate(plans):
                if scan_mode:
                    # device-resident chunk loop: the whole group rolls
                    # into scan-segment launches (one, memory allowing)
                    yield from scan_plan_items(plan)
                    continue
                gi, group = plan["gi"], plan["group"]
                nc_batch = plan["nc_batch"]
                lanes = nc_batch * n_folds
                # compile-ahead: this group's fused program (overlaps
                # its own calibration launches) and the next group's
                # (overlaps this whole group)
                submit_precompile(plan)
                if pi + 1 < len(plans):
                    submit_precompile(plans[pi + 1])
                #: group-shared state: the calibrated warm score cost
                #: per task, set by the calibration item's finalize —
                #: which the (serial, in-order) finalize stream runs
                #: before any fused chunk of the group finalizes
                gstate = {"sspt": None}
                live_seen = 0
                for lo, hi, chunk_id, rec in plan["chunks"]:
                    idx = group.candidate_indices[lo:hi]
                    if rec is not None:
                        replay_chunk(idx, rec)
                        continue
                    if shed_chunk(idx, chunk_id):
                        continue
                    live_seen += 1
                    n_real = (hi - lo) * n_folds

                    def stage(lo=lo, hi=hi, plan=plan, chunk_id=chunk_id):
                        dyn = {}
                        repeat = n_folds if task_batched else 1
                        for k, arr in plan["group"].dynamic_params.items():
                            if ring is not None:
                                # donate mode: pad into a reused host
                                # buffer (double-buffer ring) instead of
                                # allocating per chunk; the slot blocks
                                # on its previous consumer before reuse
                                slot = ring.slot(
                                    (plan["gi"], k),
                                    (plan["nc_batch"] * repeat,)
                                    + arr.shape[1:], arr.dtype)
                                host = pad_chunk(
                                    arr, lo, hi, plan["nc_batch"],
                                    repeat, out=slot.array)
                                dev = _dataplane.upload(
                                    host, task_shard, label="dyn")
                                slot.commit(dev)
                            else:
                                dev = _dataplane.upload(
                                    pad_chunk(arr, lo, hi,
                                              plan["nc_batch"], repeat),
                                    task_shard, label="dyn")
                            dyn[k] = dev
                        if not dyn and not task_batched:
                            # all-static group: vmap still needs a
                            # batched operand to define the candidate
                            # axis (families ignore unknown keys).  The
                            # plane caches the zeros across chunks AND
                            # searches — except under donation, where a
                            # cached operand would be invalidated by the
                            # launch that consumed it
                            dyn["_pad"] = (
                                plane.zeros(plan["nc_batch"], dtype,
                                            task_shard,
                                            tenant=sched_tenant)
                                if plane is not None and not donate else
                                _dataplane.upload(
                                    np.zeros(plan["nc_batch"],
                                             dtype=dtype),
                                    task_shard, label="dyn.pad"))
                        w = group_masks(plan)
                        # once the group's last live chunk has staged,
                        # drop the plan's tiled-mask reference (each
                        # payload keeps its own) so one group's masks
                        # never outlive its launches.  Tracked as a set
                        # of chunk ids under a lock: the supervisor's
                        # transient retries re-stage on the recovering
                        # thread, concurrent with the stage thread, and
                        # a re-staged chunk must not count twice
                        with stage_lock:
                            done = plan.setdefault("staged_ids", set())
                            done.add(chunk_id)
                            if len(done) >= plan["n_live"]:
                                plan.pop("w_task_dev", None)
                        return dyn, w

                    if fused_mode and live_seen > 1:
                        # steady state: ONE fused launch per chunk

                        def launch(payload, plan=plan):
                            dyn, w = payload
                            return resolve_fused(plan)(
                                dyn, plan_data(plan), w, test_dev,
                                train_sc_dev, test_unw_dev, train_unw_dev)

                        def gather(out):
                            te, tr, bad, it_max, it_sum = out
                            return (mesh_lib.device_get_tree(te),
                                    mesh_lib.device_get_tree(tr),
                                    np.asarray(
                                        mesh_lib.device_get_tree(bad)),
                                    int(it_max), int(it_sum))

                        def finalize(host, tm, plan=plan, idx=idx, lo=lo,
                                     hi=hi, chunk_id=chunk_id,
                                     gstate=gstate, lanes=lanes):
                            te, tr, bad, im, isum = host
                            wall = tm.dispatch_s + tm.compute_s \
                                + tm.gather_s
                            # one launch: attribute the group's measured
                            # warm score cost — scaled by the PADDED
                            # lane count, which is what the launch
                            # actually computes — the rest is fit, so
                            # the score-time column is an estimate,
                            # never a silent 0.0 (unless calibration
                            # itself was lost to OOM recovery: sspt 0.0)
                            t_score = min((gstate["sspt"] or 0.0) * lanes,
                                          wall)
                            t_fit = wall - t_score
                            fit_failed[idx, :] |= np.asarray(
                                bad[:hi - lo], bool)
                            if im >= 0:
                                record_iters(im, isum, lanes)
                            write_cells(plan, idx, lo, hi, chunk_id,
                                        te, tr, t_fit, t_score)

                        yield LaunchItem(
                            key=chunk_id, kind="fused", group=gi,
                            n_tasks=n_real, stage=stage, launch=launch,
                            gather=gather, finalize=finalize,
                            bisect=make_bisect_fused(plan, lo, hi,
                                                     chunk_id),
                            fuse=(make_fuse_spec(plan, lo, hi, chunk_id)
                                  if fusion_on else None))
                        continue

                    # first live chunk of the group (or the never-fused
                    # path): separate fit and score launches with exact
                    # per-phase walls, plus — when later chunks will
                    # fuse — a warm calibration score launch measuring
                    # the steady-state score cost
                    cstate = {}
                    calibrate = fused_mode and live_seen < plan["n_live"]

                    def launch_fit(payload, plan=plan, cstate=cstate):
                        dyn, w = payload
                        models = build_programs(plan)["fit"](
                            dyn, plan_data(plan), w)
                        cstate["models"] = models
                        bad = _models_health(models)
                        it_arr = None
                        if isinstance(models, dict) and (
                                "n_iter" in models
                                or "n_iter_exec" in models):
                            # prefer the solver's true executed count
                            # over any sklearn-facing rescale (FISTA
                            # reports n_iter on the caller's max_iter
                            # axis but runs a larger internal budget)
                            it_arr = models.get("n_iter_exec",
                                                models.get("n_iter"))
                        return models, bad, it_arr

                    def gather_fit(out):
                        _, bad, it_arr = out
                        bad_h = (np.asarray(mesh_lib.device_get_tree(bad))
                                 if bad is not None else None)
                        it_h = (np.asarray(
                            mesh_lib.device_get_tree(it_arr))
                            if it_arr is not None else None)
                        return bad_h, it_h

                    def fin_fit(host, tm, idx=idx, lo=lo, hi=hi,
                                cstate=cstate, lanes=lanes):
                        bad_h, it_h = host
                        if bad_h is not None:
                            fit_failed[idx, :] |= bad_h[:hi - lo]
                        if it_h is not None:
                            record_iters(np.max(it_h), np.sum(it_h),
                                         lanes)
                        cstate["t_fit"] = tm.dispatch_s + tm.compute_s

                    def host_fb_fit(idx=idx, cstate=cstate):
                        # the whole chunk (fit AND scores) degrades to
                        # per-candidate host execution; the score item
                        # consumes the stashed cells instead of
                        # launching
                        te, tr = host_eval(idx)
                        cstate["host"] = (te, tr)
                        return (None, None)

                    yield LaunchItem(
                        key=chunk_id + ":fit", kind="fit", group=gi,
                        n_tasks=n_real, stage=stage, launch=launch_fit,
                        gather=gather_fit, finalize=fin_fit,
                        host_fallback=host_fb_fit,
                        bisect=(make_bisect_fit(plan, lo, hi, chunk_id,
                                                cstate, lanes)
                                if quarantine_armed else None))

                    def launch_score(payload, plan=plan, cstate=cstate):
                        if "host" in cstate:
                            return None   # chunk recovered on the host
                        return build_programs(plan)["score"](
                            cstate["models"], plan_data(plan), test_dev,
                            train_sc_dev, test_unw_dev, train_unw_dev)

                    def gather_score(out, cstate=cstate):
                        if out is None and "host" in cstate:
                            return cstate.pop("host")
                        te, tr = out
                        return (mesh_lib.device_get_tree(te),
                                mesh_lib.device_get_tree(tr))

                    def host_fb_score(idx=idx, cstate=cstate):
                        if "host" in cstate:
                            return cstate.pop("host")
                        cstate.pop("models", None)
                        return host_eval(idx)

                    def fin_score(host, tm, plan=plan, idx=idx, lo=lo,
                                  hi=hi, chunk_id=chunk_id, cstate=cstate,
                                  calibrate=calibrate):
                        te, tr = host
                        t_score = tm.dispatch_s + tm.compute_s \
                            + tm.gather_s
                        if not calibrate:
                            cstate.pop("models", None)
                        write_cells(plan, idx, lo, hi, chunk_id, te, tr,
                                    cstate["t_fit"], t_score)

                    yield LaunchItem(
                        key=chunk_id + ":score", kind="score", group=gi,
                        n_tasks=n_real, launch=launch_score,
                        gather=gather_score, finalize=fin_score,
                        host_fallback=host_fb_score,
                        bisect=(make_bisect_score(plan, lo, hi,
                                                  chunk_id)
                                if quarantine_armed else None))

                    if calibrate:
                        # calibration: a SECOND, warm score launch (the
                        # first's wall includes trace+compile) measures
                        # the steady-state score cost later fused chunks
                        # attribute out of their single-launch wall.
                        # It is real device work: counted in n_launches
                        # and score_wall_s (not in any candidate's
                        # cells — sklearn never ran it)

                        def launch_cal(payload, plan=plan,
                                       cstate=cstate, gstate=gstate):
                            models = cstate.pop("models", None)
                            if models is None:
                                # the chunk recovered on the host: no
                                # device models to calibrate with
                                gstate["cal_skip"] = True
                                return None
                            return build_programs(plan)["score"](
                                models, plan_data(plan), test_dev,
                                train_sc_dev, test_unw_dev,
                                train_unw_dev)

                        def host_fb_cal(cstate=cstate, gstate=gstate):
                            cstate.pop("models", None)
                            gstate["cal_skip"] = True
                            return None

                        def fin_cal(host, tm, plan=plan, gstate=gstate,
                                    lanes=lanes):
                            if gstate.pop("cal_skip", False):
                                # calibration lost to recovery: later
                                # fused chunks attribute a zero score
                                # share (documented estimate, not a
                                # silent wrong one)
                                gstate["sspt"] = 0.0
                                return
                            wall = tm.dispatch_s + tm.compute_s
                            # per PADDED lane: the launch computes
                            # nc_batch lanes regardless of how many are
                            # real, and fused chunks scale back up by
                            # the same padded count
                            gstate["sspt"] = wall / lanes
                            metrics.counter("n_launches").inc()
                            metrics.gauge("score_wall_s").add(wall)
                            rec = per_group_rec(plan)
                            rec["n_launches"] += 1
                            rec["score_wall_s"] += wall
                            rec["score_s_per_task_calibrated"] = round(
                                gstate["sspt"], 7)

                        yield LaunchItem(
                            key=chunk_id + ":calibrate", kind="calibrate",
                            group=gi, n_tasks=n_real, launch=launch_cal,
                            finalize=fin_cal, host_fallback=host_fb_cal)

        # every LaunchItem runs under the fault supervisor: transient
        # retry with backoff, OOM bisection through the hooks above, a
        # watchdog on the blocking wait, and deterministic injection for
        # tests — identical at every pipeline depth (same item order)
        from spark_sklearn_tpu.parallel.faults import LaunchSupervisor
        memory_info = None
        if ledger is not None:
            # OOM forensics: every OOM fault event carries the failing
            # chunk's modeled bytes next to the budget, and the FIRST
            # OOM per chunk trains the ledger's safety margin — so
            # bisection outcomes tighten the width ceiling instead of
            # repeating.  Bisected sub-ranges ("id[lo:hi]") share
            # their parent chunk's model.
            mem_oom_lock = named_lock("grid.mem_oom_lock")
            oom_trained: set = set()

            def memory_info(key, group):
                plan = plans[group] if 0 <= group < len(plans) else None
                modeled = int(resident_est) + (
                    int(plan.get("mem_chunk_bytes", 0))
                    if plan is not None else 0)
                budget = int(mem_ctx.get("budget_bytes", 0)) \
                    if mem_ctx is not None else 0
                base_key = key.split("[", 1)[0]
                with mem_oom_lock:
                    fresh = base_key not in oom_trained
                    if fresh:
                        oom_trained.add(base_key)
                if fresh:
                    ledger.observe_oom(modeled, budget)
                return {"modeled_bytes": modeled,
                        "budget_bytes": budget}

        supervisor = LaunchSupervisor(
            config, faults=metrics.struct("faults"), ckpt=ckpt,
            verbose=self.verbose, memory_info=memory_info,
            # later rungs accumulate into the shared faults struct
            # instead of zeroing the earlier rungs' recovery record
            reset_faults=(rung is None or rung.itr == 0))
        items = chunk_items()
        if binding is not None:
            # executor wrapping sits UNDER the supervisor: a routed
            # launch that fails re-enters the supervisor on THIS
            # search's threads (retries re-queue fairly; one tenant's
            # OOM bisection never blocks the shared dispatch loop)
            n_live_total = sum(p["n_live"] for p in plans)
            if rung is not None:
                # progress() spans the whole halving search: planned
                # chunks accumulate rung by rung as geometry resolves
                rung.planned_total += n_live_total
                n_live_total = rung.planned_total
            binding.executor.note_planned(binding.handle, n_live_total)
            items = binding.executor.wrap_items(binding.handle, items)
        resumed0 = int(metrics.data.get("n_chunks_resumed", 0))
        try:
            pipe.run(supervisor.wrap(items))
        except Exception as exc:
            # graceful degradation: under partial_results='best_effort'
            # a persistent non-memory fault (retries exhausted, a
            # watchdog timeout) stops the search WITHOUT killing it —
            # every candidate still missing cells is declared shed and
            # written to error_score.  Cancellation, OOM (the bisection
            # hooks own it) and raise-mode searches propagate
            # unchanged.
            degradable = (
                pctx is not None
                and str(getattr(config, "partial_results", "raise")
                        or "raise") == "best_effort"
                and not getattr(exc, "_sst_cancelled", False)
                and not _faults.is_oom(exc))
            if not degradable:
                raise
            left = np.flatnonzero(~pctx["done"])
            for s_ in scorer_names:
                test_scores[s_][left, :] = errval
                if return_train:
                    train_scores[s_][left, :] = errval
            fit_times[left, :] = 0.0
            score_times[left, :] = 0.0
            pctx["done"][left] = True
            pctx["shed"].append({
                "reason": "fault",
                "chunk": None,
                "candidates": [int(i) for i in left],
                "error": f"{type(exc).__name__}: {exc}"[:300]})
            _telemetry.note_protection("shed", len(left))
            logger.warning(
                "persistent fault under partial_results='best_effort' "
                "(%r): %d candidate(s) shed to error_score, the search "
                "returns declared-partial results", exc, len(left))
        finally:
            # the scheduler's per-search view (queue waits, interleave,
            # measured tenant shares) — zeroed enabled=False shape for
            # a standalone fit, so the report schema never changes
            metrics.put("scheduler", _serve.report_block(binding))
            # the compile thread traces under this search's jax config
            # (e.g. temporarily-enabled x64): join it before returning.
            # A halving rung only DRAINS it — no queued AOT job crosses
            # the rung boundary's config restore, but the thread stays
            # warm for the next rung (halving closes the shared
            # pipeline when the last rung ends).
            if rung is None:
                pipe.close()
            else:
                pipe.drain()
            pr = pipe.report()
            cache1 = persistent_cache_counts()
            pr["persistent_cache_hits"] = cache1["hits"] - cache0["hits"]
            pr["persistent_cache_misses"] = \
                cache1["misses"] - cache0["misses"]
            # distinct traced-program constructions this search (program-
            # cache misses; each is one python->jaxpr->HLO walk whether
            # the compile then ran on the AOT thread or at jit dispatch)
            total_builds = _program_build_count() - builds0
            pr["n_compiles"] = total_builds
            metrics.put("pipeline", pr)
            metrics.put("chunkloop", chunkloop_block(
                metrics.struct("chunkloop"), mode=chunk_loop,
                enabled=scan_mode,
                score_attribution="folded" if scan_mode
                else "calibrated"))
            metrics.put("prefix", _prefix.prefix_block(
                metrics.struct("prefix"),
                mode="shared" if px_on else "atomic",
                enabled=bool(px_state.get("enabled"))))
            # feed the measured per-launch overhead / per-lane cost back
            # into the geometry planner's cost model: the NEXT search
            # over a new structure prices its widths from real walls
            # (plans already computed this process keep their widths via
            # the plan cache, so drift never forces recompiles).  For a
            # halving search this runs at EVERY rung boundary over that
            # rung's timeline slice — rung k+1's re-plan prices its
            # widths from rung k's measured overhead and lane cost, not
            # from cross-search priors.
            # n_builds normalizes the compile lane PER PROGRAM: a
            # scanned group compiles once however many chunks it
            # serves, and the old per-timeline-median heuristic would
            # double-count that one compile into every launch's excess
            launches = pr.get("launches") or []
            if rung is not None:
                new_launches = launches[rung.launches_seen:]
                rung.launches_seen = len(launches)
                nb = total_builds - int(
                    getattr(rung, "builds_observed", 0))
                rung.builds_observed = total_builds
                geometry_cost_model().observe(new_launches, n_builds=nb)
                rung_rec = rung.current
                if rung_rec is not None:
                    rung_rec["n_chunks_resumed"] = int(
                        metrics.data.get("n_chunks_resumed", 0)) \
                        - resumed0
                    wall = float(pr.get("wall_s", 0.0))
                    rung_rec["pipe_wall_s"] = round(
                        max(0.0, wall - rung.prev_pipe_wall), 4)
                    rung.prev_pipe_wall = wall
                    # the rung's end boundary in the shared pipeline's
                    # cumulative launch timeline — what the attribution
                    # analyzer slices per-rung lanes from
                    rung_rec["launches_end"] = len(launches)
            else:
                geometry_cost_model().observe(launches,
                                              n_builds=total_builds)
            # persist the plan cache + cost-model state next to the AOT
            # artifacts: a fresh process then plans the SAME chunk
            # widths — and resolves the same stored programs — without
            # re-measuring (parallel/programstore.py plans.json)
            if search_store is not None:
                from spark_sklearn_tpu.parallel.taskgrid import (
                    export_plan_state)
                search_store.save_plan_state(export_plan_state())

    def _print_task_end_lines(self, candidates, idx, n_folds, scorer_names,
                              test_scores, train_scores, return_train,
                              t_task, fit_failed):
        """sklearn's `_fit_and_score` verbose>1 "[CV i/n] END ..." lines,
        emitted post-launch (compiled tasks execute fused, so per-task
        lines appear when their launch completes — same completion-report
        contract as the callback hooks).  Format mirrors the installed
        sklearn/model_selection/_validation.py:892-915.  Cells already
        known to be failed fits print error_score (sklearn prints the
        substituted score, never the garbage the lane computed)."""
        from joblib.logger import short_format_time

        err = self.error_score if not isinstance(self.error_score, str) \
            else np.nan

        def cell(scores, gidx, f):
            return err if fit_failed[gidx, f] else scores[gidx, f]

        for gidx in idx:
            params = candidates[gidx]
            params_msg = ", ".join(
                f"{k}={params[k]}" for k in sorted(params))
            for f in range(n_folds):
                progress_msg = (f" {f + 1}/{n_folds}"
                                if self.verbose > 2 else "")
                result_msg = params_msg + (";" if params_msg else "")
                # scores appear at verbose > 2 only — sklearn's exact
                # gating (_fit_and_score: `if verbose > 2:`)
                if self.verbose > 2 and len(scorer_names) > 1:
                    for s in sorted(scorer_names):
                        result_msg += f" {s}: ("
                        if return_train:
                            result_msg += ("train="
                                           f"{cell(train_scores[s], gidx, f):.3f}, ")
                        result_msg += f"test={cell(test_scores[s], gidx, f):.3f})"
                elif self.verbose > 2:
                    s = scorer_names[0]
                    result_msg += ", score="
                    if return_train:
                        result_msg += (
                            f"(train={cell(train_scores[s], gidx, f):.3f}, "
                            f"test={cell(test_scores[s], gidx, f):.3f})")
                    else:
                        result_msg += f"{cell(test_scores[s], gidx, f):.3f}"
                result_msg += f" total time={short_format_time(t_task)}"
                end_msg = f"[CV{progress_msg}] END "
                end_msg += "." * max(0, 80 - len(end_msg) - len(result_msg))
                end_msg += result_msg
                # stdout-parity channel: byte-for-byte sklearn's
                # _fit_and_score END line (pinned by test_obs.py)
                logger.print(end_msg, candidate=int(gidx), fold=f)

    # ------------------------------------------------------------------
    # Tier B: host fallback (full sklearn generality)
    # ------------------------------------------------------------------
    def _fit_host(self, X, y, candidates, splits, fit_params,
                  score_params=None, eval_ctxs=None, fallback_exc=None):
        from joblib import Parallel, delayed
        from sklearn.metrics import check_scoring
        from sklearn.metrics._scorer import _check_multimetric_scoring
        from sklearn.model_selection._validation import _fit_and_score

        estimator = self.estimator
        if callable(self.scoring):
            # a callable may return a scalar (single metric) or a dict
            # (multimetric, sklearn contract) — discovered from results
            scorer_attr: Any = self.scoring
            scorer_for_fs: Any = self.scoring
            scorer_names = None
        elif self.scoring is None or isinstance(self.scoring, str):
            scorer_obj = check_scoring(estimator, self.scoring)
            scorer_attr = scorer_obj
            scorer_for_fs = scorer_obj
            scorer_names = ["score"]
        else:
            from sklearn.metrics._scorer import _MultimetricScorer
            scorers = _check_multimetric_scoring(estimator, self.scoring)
            scorer_attr = dict(scorers)
            scorer_for_fs = _MultimetricScorer(
                scorers=scorers,
                raise_exc=(self.error_score == "raise"))
            scorer_names = list(scorers)

        n_folds = len(splits)
        tasks = [
            (ci, fi, params, train, test)
            for ci, params in enumerate(candidates)
            for fi, (train, test) in enumerate(splits)
        ]
        metrics = search_registry("host")
        metrics.gauge("n_tasks").set(len(tasks))
        metrics.gauge("n_jobs").set(
            self.n_jobs if self.n_jobs is not None else 1)
        faults = metrics.struct("faults")
        if fallback_exc is not None:
            # the caught exception type that pushed the compiled tier to
            # fall back here (the compiled registry — and its faults
            # journal — was replaced by this host one)
            faults["fallback_exception"] = (
                f"{type(fallback_exc).__name__}: "
                f"{fallback_exc}"[:200])
        self._search_metrics = metrics
        self._search_report = metrics.data

        from inspect import signature as _sig
        _fs_params = _sig(_fit_and_score).parameters

        def run(params, train, test, callback_ctx):
            # caller/callback_ctx exist only on the sklearn callback
            # branch; stock releases reject unknown kwargs
            extra = {}
            if "caller" in _fs_params:
                extra["caller"] = self
            if "callback_ctx" in _fs_params:
                extra["callback_ctx"] = callback_ctx
            return _fit_and_score(
                clone(estimator), X, y, scorer=scorer_for_fs,
                train=train, test=test, verbose=self.verbose,
                parameters=params, fit_params=fit_params or None,
                score_params=score_params or None,
                return_train_score=self.return_train_score,
                return_times=True, error_score=self.error_score,
                **extra)

        ctxs = eval_ctxs if eval_ctxs is not None else [None] * len(tasks)
        n_jobs = self.n_jobs if self.n_jobs is not None else 1
        with get_tracer().span("host.fit_and_score", n_tasks=len(tasks),
                               n_jobs=n_jobs):
            results = Parallel(n_jobs=n_jobs)(
                delayed(run)(params, train, test, ctx)
                for (_, _, params, train, test), ctx in zip(tasks, ctxs))

        # sklearn's own failure accounting: FitFailedWarning with the
        # "n fits failed out of a total of m" format, ValueError when all
        # fits failed (_search.py:1107 _warn_or_raise_about_fit_failures)
        from sklearn.model_selection._validation import (
            _warn_or_raise_about_fit_failures)
        _warn_or_raise_about_fit_failures(results, self.error_score)

        if scorer_names is None:
            # callable scoring: multimetric iff it returned a dict
            scorer_names = ["score"]
            for res in results:
                if isinstance(res["test_scores"], dict):
                    scorer_names = list(res["test_scores"])
                    break

        n_cand = len(candidates)
        test_scores = {s: np.empty((n_cand, n_folds)) for s in scorer_names}
        train_scores = ({s: np.empty((n_cand, n_folds))
                        for s in scorer_names}
                        if self.return_train_score else None)
        fit_times = np.empty((n_cand, n_folds))
        score_times = np.empty((n_cand, n_folds))
        for (ci, fi, _, _, _), res in zip(tasks, results):
            ts = res["test_scores"]
            if not isinstance(ts, dict):
                # scalar: single metric, or error_score from a failed
                # multimetric fit — applies to every metric
                ts = {s: ts for s in scorer_names}
            for s in scorer_names:
                test_scores[s][ci, fi] = ts.get(s, np.nan)
            if self.return_train_score:
                trs = res.get("train_scores", {})
                if not isinstance(trs, dict):
                    trs = {s: trs for s in scorer_names}
                for s in scorer_names:
                    train_scores[s][ci, fi] = trs.get(s, np.nan)
            fit_times[ci, fi] = res["fit_time"]
            score_times[ci, fi] = res["score_time"]
        return (test_scores, train_scores, fit_times, score_times,
                scorer_names, scorer_attr)

    # ------------------------------------------------------------------
    # cv_results_ assembly — sklearn _format_results schema
    # (_search.py:1208-1290)
    # ------------------------------------------------------------------
    def _format_results(self, candidates, test_scores, train_scores,
                        fit_times, score_times, scorer_names,
                        more_results=None):
        from scipy.stats import rankdata

        n_candidates = len(candidates)
        # extra columns from a halving-style _run_search come first,
        # as arrays — sklearn's exact layout (_format_results:
        # `results = dict(more_results or {})`, then np.asarray each)
        results: Dict[str, Any] = {
            k: np.asarray(v) for k, v in (more_results or {}).items()}

        def _store(key_name, array, weights=None, splits=False, rank=False):
            array = np.asarray(array, dtype=np.float64).reshape(
                n_candidates, -1)
            if splits:
                for i in range(array.shape[1]):
                    results[f"split{i}_{key_name}"] = array[:, i]
            array_means = np.average(array, axis=1, weights=weights)
            results[f"mean_{key_name}"] = array_means
            if key_name.startswith(("train_", "test_")) and np.any(
                    ~np.isfinite(array_means)):
                # sklearn's exact wording (_search.py:1237)
                warnings.warn(
                    f"One or more of the {key_name.split('_')[0]} scores "
                    f"are non-finite: {array_means}",
                    category=UserWarning)
            array_stds = np.sqrt(np.average(
                (array - array_means[:, None]) ** 2, axis=1,
                weights=weights))
            results[f"std_{key_name}"] = array_stds
            if rank:
                if np.isnan(array_means).any():
                    rank_arr = rankdata(
                        np.where(np.isnan(array_means), np.inf,
                                 -array_means), method="min")
                else:
                    rank_arr = rankdata(-array_means, method="min")
                results[f"rank_{key_name}"] = rank_arr.astype(np.int32)

        _store("fit_time", fit_times)
        _store("score_time", score_times)

        # masked param arrays, sklearn's exact dtype rule
        # (_search.py _yield_masked_array_for_each_param): dtype inferred
        # from the PRESENT values; strings and nested sequences stay object
        param_results: Dict[str, Dict[int, Any]] = defaultdict(dict)
        for cand_idx, params in enumerate(candidates):
            for name, value in params.items():
                param_results[f"param_{name}"][cand_idx] = value
        for key, param_result in param_results.items():
            param_list = list(param_result.values())
            try:
                arr = np.array(param_list)
            except ValueError:
                arr_dtype = np.dtype(object)
            else:
                arr_dtype = (arr.dtype if arr.dtype.kind != "U"
                             and arr.ndim == 1 else object)
            ma = np.ma.MaskedArray(np.empty(n_candidates, dtype=arr_dtype),
                                   mask=True)
            for index, value in param_result.items():
                ma[index] = value
            results[key] = ma
        results["params"] = list(candidates)

        for s in scorer_names:
            _store(f"test_{s}", test_scores[s], splits=True, rank=True)
            if self.return_train_score:
                _store(f"train_{s}", train_scores[s], splits=True)
        return results

    # -- prediction delegation (sklearn parity: available_if makes these
    # methods conditional, so hasattr() reflects the wrapped estimator and
    # refit state exactly like sklearn's BaseSearchCV) ------------------

    @available_if(_search_estimator_has("score_samples"))
    def score_samples(self, X):
        check_is_fitted(self)
        return self.best_estimator_.score_samples(X)

    @available_if(_search_estimator_has("predict"))
    def predict(self, X):
        check_is_fitted(self)
        return self.best_estimator_.predict(X)

    @available_if(_search_estimator_has("predict_proba"))
    def predict_proba(self, X):
        check_is_fitted(self)
        return self.best_estimator_.predict_proba(X)

    @available_if(_search_estimator_has("predict_log_proba"))
    def predict_log_proba(self, X):
        check_is_fitted(self)
        return self.best_estimator_.predict_log_proba(X)

    @available_if(_search_estimator_has("decision_function"))
    def decision_function(self, X):
        check_is_fitted(self)
        return self.best_estimator_.decision_function(X)

    @available_if(_search_estimator_has("transform"))
    def transform(self, X):
        check_is_fitted(self)
        return self.best_estimator_.transform(X)

    @available_if(_search_estimator_has("inverse_transform"))
    def inverse_transform(self, X):
        check_is_fitted(self)
        return self.best_estimator_.inverse_transform(X)

    def _sk_visual_block_(self):
        # sklearn's diagram repr (_search.py _sk_visual_block_): fitted
        # searches display the refit best_estimator_, unfitted ones the
        # wrapped estimator
        from sklearn.utils._repr_html.estimator import _VisualBlock
        if hasattr(self, "best_estimator_"):
            key, estimator = "best_estimator_", self.best_estimator_
        else:
            key, estimator = "estimator", self.estimator
        return _VisualBlock(
            "parallel", [estimator],
            names=[f"{key}: {estimator.__class__.__name__}"],
            name_details=[str(estimator)])

    def __sklearn_tags__(self):
        # full tag delegation to the wrapped estimator, like sklearn's
        # BaseSearchCV (_search.py:490): estimator_type makes
        # is_classifier(search) follow the inner estimator, pairwise lets
        # cv see precomputed metrics
        tags = super().__sklearn_tags__()
        try:
            from copy import deepcopy

            from sklearn.utils import get_tags
            sub = get_tags(self.estimator)
            tags.estimator_type = sub.estimator_type
            tags.classifier_tags = deepcopy(sub.classifier_tags)
            tags.regressor_tags = deepcopy(sub.regressor_tags)
            tags.input_tags.pairwise = sub.input_tags.pairwise
            tags.input_tags.sparse = sub.input_tags.sparse
            tags.array_api_support = sub.array_api_support
        # sstlint: disable=swallowed-exception — sklearn-version compat
        # shim: tag surfaces moved repeatedly across 1.x; missing
        # attributes simply leave the default tags in place
        except Exception:
            pass
        return tags

    def score(self, X, y=None, **params):
        _check_refit(self, "score")
        if not hasattr(self, "best_estimator_"):
            raise AttributeError(
                f"This {type(self).__name__} instance is not fitted yet; "
                "call fit() first.")
        # metadata routing contract: extra params are rejected unless
        # enable_metadata_routing=True, then routed to the scorer
        _raise_for_params(params, self, "score")
        if _routing_enabled():
            score_params = process_routing(
                self, "score", **params).scorer["score"]
        else:
            score_params = {}
        if callable(self.scoring):
            score = self.scoring(self.best_estimator_, X, y, **score_params)
            # a multimetric callable returns a dict; score() is the refit
            # metric's scalar (sklearn _search.py BaseSearchCV.score)
            if getattr(self, "multimetric_", False):
                score = score[self.refit]
            return score
        if self.scorer_ is not None and not isinstance(self.scorer_, dict):
            return self.scorer_(self.best_estimator_, X, y, **score_params)
        if isinstance(self.scorer_, dict) and isinstance(self.refit, str):
            return self.scorer_[self.refit](
                self.best_estimator_, X, y, **score_params)
        return self.best_estimator_.score(X, y, **score_params)


class GridSearchCV(BaseSearchTPU):
    """Exhaustive search over a parameter grid on a TPU mesh.

    Accepts both calling conventions:
      GridSearchCV(estimator, param_grid, ...)            (sklearn)
      GridSearchCV(sc, estimator, param_grid, ...)        (reference legacy —
        `sc` is accepted and ignored; the JAX mesh replaces the Spark
        cluster.  Reference: grid_search.py GridSearchCV(self, sc, ...).)
    """

    def __init__(self, estimator, param_grid=None, legacy_grid=None, *,
                 scoring=None, n_jobs=None, refit=True, cv=None, verbose=0,
                 error_score=np.nan, return_train_score=False, backend=None,
                 config=None):
        # third positional slot exists only for the reference's legacy
        # (sc, estimator, param_grid) convention; it is an explicit named
        # parameter (not *args) because sklearn's get_params/clone/repr
        # introspect __init__ and reject varargs
        if not _looks_like_estimator(estimator) and \
                _looks_like_estimator(param_grid):
            estimator = param_grid
            param_grid = legacy_grid
            legacy_grid = None
        elif legacy_grid is not None:
            # slot exists only for the legacy (sc, est, grid) convention;
            # a stray third positional (e.g. scoring) must not be swallowed
            raise TypeError(
                f"unexpected positional argument {legacy_grid!r}; pass "
                "scoring/cv/... as keywords")
        if param_grid is None:
            raise TypeError("param_grid is required")
        super().__init__(
            estimator, scoring=scoring, n_jobs=n_jobs, refit=refit, cv=cv,
            verbose=verbose, error_score=error_score,
            return_train_score=return_train_score, backend=backend,
            config=config)
        self.param_grid = param_grid
        self.legacy_grid = legacy_grid

    def _get_candidates(self):
        return list(ParameterGrid(self.param_grid))


class RandomizedSearchCV(BaseSearchTPU):
    """Randomized search: candidates drawn by sklearn's ParameterSampler
    (identical sampling semantics — _search.py:2109), evaluated on the mesh.

    Legacy `(sc, estimator, param_distributions)` convention accepted like
    GridSearchCV."""

    def __init__(self, estimator, param_distributions=None,
                 legacy_distributions=None, *, n_iter=10,
                 scoring=None, n_jobs=None, refit=True, cv=None, verbose=0,
                 random_state=None, error_score=np.nan,
                 return_train_score=False, backend=None, config=None):
        if not _looks_like_estimator(estimator) and \
                _looks_like_estimator(param_distributions):
            estimator = param_distributions
            param_distributions = legacy_distributions
            legacy_distributions = None
        elif legacy_distributions is not None:
            raise TypeError(
                f"unexpected positional argument {legacy_distributions!r}; "
                "pass n_iter/scoring/... as keywords")
        if param_distributions is None:
            raise TypeError("param_distributions is required")
        self.legacy_distributions = legacy_distributions
        super().__init__(
            estimator, scoring=scoring, n_jobs=n_jobs, refit=refit, cv=cv,
            verbose=verbose, error_score=error_score,
            return_train_score=return_train_score, backend=backend,
            config=config)
        self.param_distributions = param_distributions
        self.n_iter = n_iter
        self.random_state = random_state

    def _get_candidates(self):
        return list(ParameterSampler(
            self.param_distributions, self.n_iter,
            random_state=self.random_state))
