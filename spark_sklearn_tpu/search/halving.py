"""Successive halving — adaptive search as a compiled scheduler.

sklearn's ``HalvingGridSearchCV`` / ``HalvingRandomSearchCV``
(model_selection/_search_successive_halving.py, the experimental
``enable_halving_search_cv`` surface) spend a shrinking candidate set
against a growing resource: rung k fits every survivor at resource
``r_k = factor**k * min_resources``, keeps the top
``ceil(n / factor)`` by mean test score, and repeats.  Exhaustive
grids pay most of their warm wall fitting candidates that lose; the
bandit argument (Karnin, Koren & Somekh, ICML'13 — and the same
online, budget-aware case "Towards General and Efficient Online
Tuning for Spark" makes for shared clusters) is that early stopping
should be a first-class scheduler property, not a post-hoc filter.

Here each rung is ONE ``evaluate_candidates`` call into the engine's
rung seam (``search/grid.py``), which makes a rung a *planned set of
compile groups*:

  - **resource = 'n_samples'**: the rung's folds come from sklearn's
    own ``_SubsampleMetaSplitter`` (identical subsampling RNG), and
    the subsampled indices become fold masks through the existing
    fold-mask machinery — the compiled programs never change shape;
  - **resource = an estimator parameter** (e.g. ``n_estimators``):
    the resource value lands in each candidate dict, riding the
    masked-prefix trick the forest/boosting families already use for
    dynamic tree counts;
  - **elimination** runs host-side on gathered scores with sklearn's
    own ``_top_k`` (NaN handling and tie order included), so the
    surviving set is byte-for-byte sklearn's;
  - **lane reclamation**: at every rung boundary the geometry planner
    re-plans the survivors into narrower chunks
    (``taskgrid.plan_geometry`` over the surviving sizes, fed by the
    PREVIOUS rung's measured timeline through the cost model), so
    eliminated candidates retire their device lanes instead of riding
    along as padding.  ``TpuConfig(halving_replan=False)`` pins every
    rung to the rung-0 widths — the A/B baseline; ``cv_results_`` is
    identical either way because widths are pure geometry;
  - the rung barrier drains (not closes) the shared chunk pipeline,
    chunk ids carry a rung namespace (``r1:0:0:24``), and each rung
    journals into its own checkpoint file — a search killed mid-rung
    resumes bit-exact, including between a rung's score gather and
    its elimination decision (fully-journalled rungs replay with zero
    launches and re-decide identically).

Observability: ``search_report["halving"]`` (schema pinned in
``obs.metrics.HALVING_BLOCK_SCHEMA``) records per-rung candidate
counts, resources, widths, walls and lanes reclaimed; a submitted
halving search also tells the session executor about each rung
(``SearchExecutor.note_rung``) so its effective in-flight cap and
data-plane tenant charge shrink as candidates retire.
"""

from __future__ import annotations

import time
from math import ceil, floor, log
from typing import Any, Dict, List, Optional

import numpy as np

from sklearn.base import is_classifier
from sklearn.model_selection import ParameterGrid, ParameterSampler, check_cv
from sklearn.model_selection._search_successive_halving import (
    _SubsampleMetaSplitter,
    _top_k,
)
from sklearn.model_selection._split import _yields_constant_splits
from sklearn.utils.multiclass import check_classification_targets
from sklearn.utils.validation import _num_samples

from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.parallel import ownership as _ownership
from spark_sklearn_tpu.parallel.mesh import TpuConfig
from spark_sklearn_tpu.search.grid import BaseSearchTPU

__all__ = ["HalvingGridSearchCV", "HalvingRandomSearchCV"]

logger = get_logger("spark_sklearn_tpu.search.halving")


class _RungContext(_ownership.LaunchOwner):
    """Mutable per-search state threaded from the halving scheduler
    into the engine through the launch-ownership protocol
    (``parallel/ownership.py``): the scheduler attaches it with
    ``attach_owner`` around the rung loop and ``grid._run_groups``
    reads it back via ``current_owner`` — grid never imports halving,
    and the contract is the explicit :class:`LaunchOwner` attribute
    set instead of the old duck-typed ``search._rung_ctx`` probe.

    Single-threaded by construction: every field is written on the
    search's own fit thread (geometry planning and the rung-boundary
    accounting both run there), never on the pipeline workers.
    """

    kind = "rung"

    def __init__(self, resource: str, replan: bool, min_rung_width: int,
                 n_candidates0: int):
        self.resource = resource
        self.replan = bool(replan)
        self.min_rung_width = int(min_rung_width)
        self.n_candidates0 = int(n_candidates0)
        self.itr = 0
        self.ns = "r0"                 # chunk-id namespace
        self.n_resources = 0
        #: one record per rung (the halving block's `rungs` series)
        self.records: List[Dict[str, Any]] = []
        self.current: Optional[Dict[str, Any]] = None
        #: shared across rungs so the final report covers the search
        self.registry = None           # compiled-tier MetricsRegistry
        self.pipeline = None           # shared ChunkPipeline
        self.cache0 = None             # persistent-cache baseline
        self.builds0 = None            # program-build baseline
        self.dp_before = None          # data-plane counter baseline
        self.ps_before = None          # program-store counter baseline
        self.mem_before = None         # memory-ledger counter baseline
        #: cross-rung geometry anchors, keyed by the group's static
        #: params minus the resource (taskgrid.freeze)
        self.base_widths: Dict[Any, int] = {}
        self.last_widths: Dict[Any, int] = {}
        self.planned_total = 0         # cumulative live chunks
        self.launches_seen = 0         # timeline prefix already observed
        self.builds_observed = 0       # build-count prefix already fed
        self.prev_pipe_wall = 0.0
        self.lanes_reclaimed_total = 0
        #: device-resident elimination (grid's chunk_loop="scan" path):
        #: the scheduler announces the NEXT rung's survivor count
        #: before evaluate_candidates; a rung scanned as one launch
        #: runs sklearn's _top_k on device and hands the surviving
        #: candidate indices back here — ascending-mean order, exactly
        #: _top_k's — so the rung boundary skips the score round-trip
        self.keep_next = 0
        self.device_survivors = None

    def begin_rung(self, itr: int, n_resources: int,
                   n_candidates: int) -> Dict[str, Any]:
        self.itr = int(itr)
        self.ns = f"r{int(itr)}"
        self.n_resources = int(n_resources)
        rec = {
            "iter": int(itr),
            "n_candidates": int(n_candidates),
            "n_resources": int(n_resources),
            "wall_s": 0.0,
            "pipe_wall_s": 0.0,
            "widths": [],
            "n_launches_planned": 0,
            "n_chunks_resumed": 0,
            "lanes_reclaimed": 0,
            "padding_saved_frac": 0.0,
            "cost_observations": 0,
            # end boundary in the shared pipeline's cumulative launch
            # timeline (written at rung close) — the attribution
            # analyzer slices its per-rung lanes with it
            "launches_end": 0,
        }
        self.records.append(rec)
        self.current = rec
        return rec


def _render_halving_block(search, rc: _RungContext) -> Dict[str, Any]:
    """The ``search_report["halving"]`` block (schema pinned in
    ``obs.metrics.HALVING_BLOCK_SCHEMA``)."""
    return {
        "enabled": True,
        "factor": float(search.factor),
        "resource": str(search.resource),
        "replan": bool(rc.replan),
        "min_rung_width": int(rc.min_rung_width),
        "n_rungs": len(rc.records),
        "lanes_reclaimed_total": int(rc.lanes_reclaimed_total),
        "rungs": list(rc.records),
    }


class BaseSuccessiveHalvingTPU(BaseSearchTPU):
    """Shared successive-halving engine: candidate generation is the
    subclass hook (``_generate_candidate_params``), the rung loop is
    sklearn's ``BaseSuccessiveHalving._run_search`` driving the
    engine's ``evaluate_candidates(cands, cv, more_results)`` seam."""

    def __init__(self, estimator, *, scoring=None, n_jobs=None, refit=True,
                 cv=5, verbose=0, random_state=None, error_score=np.nan,
                 return_train_score=True, max_resources="auto",
                 min_resources="exhaust", resource="n_samples", factor=3,
                 aggressive_elimination=False, backend=None,
                 config: Optional[TpuConfig] = None):
        super().__init__(
            estimator, scoring=scoring, n_jobs=n_jobs, refit=refit, cv=cv,
            verbose=verbose, error_score=error_score,
            return_train_score=return_train_score, backend=backend,
            config=config)
        self.random_state = random_state
        self.max_resources = max_resources
        self.resource = resource
        self.factor = factor
        self.min_resources = min_resources
        self.aggressive_elimination = aggressive_elimination

    # -- sklearn's input contract ---------------------------------------
    def _check_input_parameters(self, X, y, split_params):
        """sklearn ``BaseSuccessiveHalving._check_input_parameters``,
        reproduced exactly (messages included) so misconfigurations
        fail identically on both engines."""
        if not _yields_constant_splits(self._checked_cv_orig):
            raise ValueError(
                "The cv parameter must yield consistent folds across "
                "calls to split(). Set its random_state to an int, or set "
                "shuffle=False.")
        if (self.resource != "n_samples"
                and self.resource not in self.estimator.get_params()):
            raise ValueError(
                f"Cannot use resource={self.resource} which is not "
                "supported by estimator "
                f"{self.estimator.__class__.__name__}")
        if isinstance(self, HalvingRandomSearchCV):
            if self.min_resources == self.n_candidates == "exhaust":
                raise ValueError(
                    "n_candidates and min_resources cannot be both set "
                    "to 'exhaust'.")
        self.min_resources_ = self.min_resources
        if self.min_resources_ in ("smallest", "exhaust"):
            if self.resource == "n_samples":
                n_splits = self._checked_cv_orig.get_n_splits(
                    X, y, **split_params)
                # sklearn's magic factor (see their source for the
                # justification link)
                magic_factor = 2
                self.min_resources_ = n_splits * magic_factor
                if is_classifier(self.estimator):
                    check_classification_targets(y)
                    n_classes = np.unique(np.asarray(y)).shape[0]
                    self.min_resources_ *= n_classes
            else:
                self.min_resources_ = 1
            # 'exhaust' may raise min_resources_ again in _run_search
        self.max_resources_ = self.max_resources
        if self.max_resources_ == "auto":
            if not self.resource == "n_samples":
                raise ValueError(
                    "resource can only be 'n_samples' when "
                    "max_resources='auto'")
            self.max_resources_ = _num_samples(X)
        if self.min_resources_ > self.max_resources_:
            raise ValueError(
                f"min_resources_={self.min_resources_} is greater "
                f"than max_resources_={self.max_resources_}.")
        if self.min_resources_ == 0:
            raise ValueError(
                f"min_resources_={self.min_resources_}: you might have "
                "passed an empty dataset X.")

    @staticmethod
    def _select_best_index(refit, refit_metric, results):
        """sklearn's halving override: the best candidate of the LAST
        iteration (BaseSearchCV would pick over all iterations)."""
        last_iter = np.max(results["iter"])
        last_iter_indices = np.flatnonzero(results["iter"] == last_iter)
        test_scores = results["mean_test_score"][last_iter_indices]
        if np.isnan(test_scores).all():
            best_idx = 0
        else:
            best_idx = np.nanargmax(test_scores)
        return last_iter_indices[best_idx]

    def fit(self, X, y=None, **params):
        """Run the halving search.  Mirrors sklearn's
        ``BaseSuccessiveHalving.fit``: validate the resource budget,
        then hand the rung loop to the shared engine."""
        if isinstance(self.scoring, (list, tuple, set, dict)):
            # sklearn enforces this via _parameter_constraints: the
            # halving elimination needs ONE mean_test_score column
            raise ValueError(
                "Multimetric scoring is not supported for successive "
                "halving; pass a single scorer name or callable.")
        self._checked_cv_orig = check_cv(
            self.cv, y, classifier=is_classifier(self.estimator))
        routed_params = self._get_routed_params_for_fit(params)
        self._check_input_parameters(
            X=X, y=y, split_params=routed_params.splitter.split)
        self._n_samples_orig = _num_samples(X)
        super().fit(X, y=y, **params)
        # sklearn sets best_score_ explicitly (its refit selection is a
        # custom callable there); ours lands on the same value via
        # _select_best_index, but keep the assignment for the callable-
        # refit corner where the base class skips it
        self.best_score_ = self.cv_results_["mean_test_score"][
            self.best_index_]
        return self

    # -- the rung loop ---------------------------------------------------
    def _run_search(self, evaluate_candidates, *, callback_ctx=None):
        candidate_params = list(self._generate_candidate_params())

        if self.resource != "n_samples" and any(
                self.resource in candidate
                for candidate in candidate_params):
            raise ValueError(
                f"Cannot use parameter {self.resource} as the resource "
                "since it is part of the searched parameters.")

        n_required_iterations = 1 + floor(
            log(len(candidate_params), self.factor))

        if self.min_resources == "exhaust":
            # start with the biggest min_resources so the last
            # (required) iteration uses as many resources as possible
            last_iteration = n_required_iterations - 1
            self.min_resources_ = max(
                self.min_resources_,
                self.max_resources_ // self.factor ** last_iteration)

        n_possible_iterations = 1 + floor(log(
            self.max_resources_ // self.min_resources_, self.factor))

        if self.aggressive_elimination:
            n_iterations = n_required_iterations
        else:
            n_iterations = min(n_possible_iterations,
                               n_required_iterations)

        if self.verbose:
            # stdout-parity channel: sklearn prints these via print()
            logger.print(f"n_iterations: {n_iterations}")
            logger.print(
                f"n_required_iterations: {n_required_iterations}")
            logger.print(
                f"n_possible_iterations: {n_possible_iterations}")
            logger.print(f"min_resources_: {self.min_resources_}")
            logger.print(f"max_resources_: {self.max_resources_}")
            logger.print(
                f"aggressive_elimination: {self.aggressive_elimination}")
            logger.print(f"factor: {self.factor}")

        self.n_resources_ = []
        self.n_candidates_ = []

        cfg = self.config or TpuConfig()
        rc = _RungContext(
            resource=self.resource,
            replan=bool(getattr(cfg, "halving_replan", True)),
            min_rung_width=int(getattr(cfg, "min_rung_width", 0) or 0),
            n_candidates0=len(candidate_params))
        _ownership.attach_owner(self, rc)
        from spark_sklearn_tpu import serve as _serve
        from spark_sklearn_tpu.parallel import dataplane as _dataplane
        binding = _serve.current_binding()
        plane = _dataplane.plane_for(cfg)
        tracer = get_tracer()
        try:
            for itr in range(n_iterations):
                power = itr
                if self.aggressive_elimination:
                    # hold n_resources at the floor while candidates
                    # are still being eliminated, then grow as usual
                    power = max(0, itr - n_required_iterations
                                + n_possible_iterations)
                n_resources = int(
                    self.factor ** power * self.min_resources_)
                n_resources = min(n_resources, self.max_resources_)
                self.n_resources_.append(n_resources)
                n_candidates = len(candidate_params)
                self.n_candidates_.append(n_candidates)

                if self.verbose:
                    logger.print("-" * 10)
                    logger.print(f"iter: {itr}")
                    logger.print(f"n_candidates: {n_candidates}")
                    logger.print(f"n_resources: {n_resources}")

                if binding is not None:
                    # the executor shrinks the tenant's effective
                    # in-flight cap with the surviving fraction
                    binding.executor.note_rung(
                        binding.handle, itr, n_candidates,
                        n_candidates / max(1, rc.n_candidates0))
                if itr and plane is not None and binding is not None \
                        and self.resource == "n_samples":
                    # rung barrier: the PREVIOUS rung's subsampled
                    # fold/tiled masks stop charging this tenant's
                    # plane quota — retired candidates release their
                    # bytes, not just their lanes.  The rung-scoped
                    # label prefix ("mask.r0.") demotes exactly that
                    # rung's buffers: never a sibling search's live
                    # masks under the same tenant, and estimator-
                    # parameter resources (which reuse the same
                    # full-dataset masks every rung) skip demotion
                    # entirely.
                    freed = plane.demote(f"mask.r{itr - 1}.",
                                         binding.tenant)
                    # same barrier for the shared-prefix derived
                    # matrices: an n_samples rung re-derives them from
                    # the NEW subsampled masks, so the previous rung's
                    # (F, n, d') buffers are stale by construction
                    # (estimator-parameter resources keep their masks
                    # — and their prefix buffers — across rungs)
                    freed += plane.demote(f"prefix.r{itr - 1}.",
                                          binding.tenant)
                    if freed:
                        logger.info(
                            "halving rung %d: demoted %d stale mask "
                            "byte(s) from tenant %s", itr, freed,
                            binding.tenant, rung=itr)

                if self.resource == "n_samples":
                    # sklearn's own subsample splitter: identical RNG,
                    # identical per-rung fold indices — they become
                    # fold masks through the engine's existing
                    # machinery
                    cv = _SubsampleMetaSplitter(
                        base_cv=self._checked_cv_orig,
                        fraction=n_resources / self._n_samples_orig,
                        subsample_test=True,
                        random_state=self.random_state)
                else:
                    # copy so the next rung's value does not overwrite
                    candidate_params = [dict(c)
                                        for c in candidate_params]
                    for candidate in candidate_params:
                        candidate[self.resource] = n_resources
                    cv = None     # the search's own (full) splits

                more_results = {
                    "iter": [itr] * n_candidates,
                    "n_resources": [n_resources] * n_candidates,
                }

                rung_rec = rc.begin_rung(itr, n_resources, n_candidates)
                n_candidates_to_keep = ceil(n_candidates / self.factor)
                # announced BEFORE the rung runs so a scanned rung
                # (grid chunk_loop="scan") can fold the elimination
                # into its one device launch
                rc.keep_next = n_candidates_to_keep
                rc.device_survivors = None
                t_rung0 = time.perf_counter()
                with tracer.span("halving.rung", iter=itr,
                                 n_candidates=n_candidates,
                                 n_resources=n_resources):
                    results = evaluate_candidates(
                        candidate_params, cv, more_results=more_results)
                rung_rec["wall_s"] = round(
                    time.perf_counter() - t_rung0, 4)

                surv = rc.device_survivors
                rc.device_survivors = None
                if surv is not None \
                        and len(surv) == n_candidates_to_keep:
                    # device-resident elimination: the scanned rung's
                    # on-device _top_k mirror already picked the
                    # survivors (positions into THIS rung's candidate
                    # list, ascending-mean order) — no score
                    # round-trip between rungs.  For tie-free means
                    # this is bit-identical to _top_k below; exactly
                    # tied means may break ties differently (stable
                    # device sort vs numpy's unstable quicksort) —
                    # both pick an equally-scoring survivor set, the
                    # same arbitrariness sklearn itself has
                    candidate_params = [candidate_params[int(i)]
                                        for i in surv]
                else:
                    # sklearn's own top-k (NaN placement and tie order
                    # included) — the surviving set is byte-exact
                    # theirs
                    candidate_params = list(
                        _top_k(results, n_candidates_to_keep, itr))
                    cl = self._search_metrics.data.get("chunkloop")
                    if cl is not None and cl.get("enabled"):
                        cl["rung_topk_host"] = int(
                            cl.get("rung_topk_host", 0)) + 1
        finally:
            pipe = rc.pipeline
            rc.pipeline = None
            _ownership.detach_owner(self)
            if pipe is not None:
                # the rungs only drained it; the search owns the close
                pipe.close()

        self.n_remaining_candidates_ = len(candidate_params)
        self.n_required_iterations_ = n_required_iterations
        self.n_possible_iterations_ = n_possible_iterations
        self.n_iterations_ = n_iterations
        # the whole-search halving block lands in whichever registry
        # finished the search (compiled or host tier)
        metrics = self._search_metrics
        metrics.put("halving", _render_halving_block(self, rc))

    def _generate_candidate_params(self):
        raise NotImplementedError


class HalvingGridSearchCV(BaseSuccessiveHalvingTPU):
    """Successive-halving grid search on the TPU mesh — sklearn
    ``HalvingGridSearchCV`` parity (``n_resources_``,
    ``n_candidates_``, the ``iter``/``n_resources`` columns in
    ``cv_results_``, last-iteration ``best_*`` selection) with each
    rung executed as a planned set of compile groups and eliminated
    candidates' lanes reclaimed mid-search (see the module
    docstring)."""

    def __init__(self, estimator, param_grid=None, *, factor=3,
                 resource="n_samples", max_resources="auto",
                 min_resources="exhaust", aggressive_elimination=False,
                 cv=5, scoring=None, refit=True, error_score=np.nan,
                 return_train_score=True, random_state=None, n_jobs=None,
                 verbose=0, backend=None, config=None):
        if param_grid is None:
            raise TypeError("param_grid is required")
        super().__init__(
            estimator, scoring=scoring, n_jobs=n_jobs, refit=refit,
            cv=cv, verbose=verbose, random_state=random_state,
            error_score=error_score,
            return_train_score=return_train_score,
            max_resources=max_resources, min_resources=min_resources,
            resource=resource, factor=factor,
            aggressive_elimination=aggressive_elimination,
            backend=backend, config=config)
        self.param_grid = param_grid

    def _generate_candidate_params(self):
        return ParameterGrid(self.param_grid)


class HalvingRandomSearchCV(BaseSuccessiveHalvingTPU):
    """Successive-halving randomized search: candidates drawn by
    sklearn's ``ParameterSampler`` (identical sampling semantics,
    ``n_candidates='exhaust'`` included), evaluated rung by rung on
    the mesh."""

    def __init__(self, estimator, param_distributions=None, *,
                 n_candidates="exhaust", factor=3, resource="n_samples",
                 max_resources="auto", min_resources="smallest",
                 aggressive_elimination=False, cv=5, scoring=None,
                 refit=True, error_score=np.nan, return_train_score=True,
                 random_state=None, n_jobs=None, verbose=0, backend=None,
                 config=None):
        if param_distributions is None:
            raise TypeError("param_distributions is required")
        super().__init__(
            estimator, scoring=scoring, n_jobs=n_jobs, refit=refit,
            cv=cv, verbose=verbose, random_state=random_state,
            error_score=error_score,
            return_train_score=return_train_score,
            max_resources=max_resources, min_resources=min_resources,
            resource=resource, factor=factor,
            aggressive_elimination=aggressive_elimination,
            backend=backend, config=config)
        self.param_distributions = param_distributions
        self.n_candidates = n_candidates

    def _generate_candidate_params(self):
        n_candidates_first_iter = self.n_candidates
        if n_candidates_first_iter == "exhaust":
            # enough candidates that the last iteration exhausts the
            # resource budget (sklearn's rule)
            n_candidates_first_iter = (
                self.max_resources_ // self.min_resources_)
        return ParameterSampler(
            self.param_distributions, n_candidates_first_iter,
            random_state=self.random_state)
