"""Compiled inference over fitted sklearn tree ensembles.

Converter direction sklearn -> TPU for the tree families (VERDICT r3
next #8).  The search-internal histogram-tree families (models/trees.py)
deliberately discard tree structures — the scan keeps one tree's
workspace live — so converted ensembles use a different, exact
representation: the fitted sklearn trees' (feature, threshold, children,
value) arrays padded to a uniform node count, traversed level-by-level
under jit.  Each traversal step is one gather + compare per sample per
tree; max_depth steps land every sample in its leaf.  This is exact
(same thresholds on the same raw X — no histogram binning), so parity
with sklearn predict/predict_proba is at float tolerance.

The reverse direction (our search-internal tree models -> sklearn) is
not supported: those models cache fold predictions, not structures.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def pack_trees(trees):
    """Pad a list of fitted sklearn ``Tree`` objects (``est.tree_``) to
    one (T, N, ...) array set.  Leaves keep children = -1; padding nodes
    are self-loops on node 0 that no traversal ever reaches."""
    T = len(trees)
    N = max(t.node_count for t in trees)
    n_out = trees[0].value.shape[-1]
    feat = np.zeros((T, N), np.int32)
    thr = np.zeros((T, N), np.float32)
    left = np.full((T, N), -1, np.int32)
    right = np.full((T, N), -1, np.int32)
    value = np.zeros((T, N, n_out), np.float32)
    depth = 0
    for i, t in enumerate(trees):
        c = t.node_count
        feat[i, :c] = np.maximum(t.feature, 0)
        thr[i, :c] = t.threshold
        left[i, :c] = t.children_left
        right[i, :c] = t.children_right
        value[i, :c] = t.value.reshape(c, -1)[:, :n_out]
        depth = max(depth, int(t.max_depth))
    return {"feature": feat, "threshold": thr, "left": left,
            "right": right, "value": value, "max_depth": int(depth)}


def ensemble_leaf_values(packed, X):
    """(T, n, n_out) leaf values for every (tree, sample) pair — one
    vmapped level-step per depth, each a gather + compare."""
    import jax
    import jax.numpy as jnp

    feat = jnp.asarray(packed["feature"])
    thr = jnp.asarray(packed["threshold"])
    left = jnp.asarray(packed["left"])
    right = jnp.asarray(packed["right"])
    value = jnp.asarray(packed["value"])
    depth = int(packed["max_depth"])
    n = X.shape[0]

    def one_tree(f_t, th_t, l_t, r_t, v_t):
        node = jnp.zeros((n,), jnp.int32)

        def step(_, node):
            is_leaf = l_t[node] < 0
            go_left = X[jnp.arange(n), f_t[node]] <= th_t[node]
            nxt = jnp.where(go_left, l_t[node], r_t[node])
            return jnp.where(is_leaf, node, nxt)

        node = jax.lax.fori_loop(0, depth, step, node)
        return v_t[node]                                  # (n, n_out)

    return jax.vmap(one_tree)(feat, thr, left, right, value)


class _PackedEnsembleBase:
    """Family-protocol shim consumed by TpuModel: predict/decision/
    predict_proba over the packed representation.  `model` keys:
    packed arrays + "agg" metadata written by the converter."""

    name = "sk_tree_ensemble"

    @classmethod
    def _leaf(cls, model, X):
        return ensemble_leaf_values(model, X)


class PackedForestClassifier(_PackedEnsembleBase):
    is_classifier = True

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        import jax.numpy as jnp
        v = cls._leaf(model, X)                           # (T, n, k)
        # sklearn averages each tree's normalised class distribution
        p = v / jnp.maximum(v.sum(axis=2, keepdims=True), 1e-12)
        return p.mean(axis=0)

    @classmethod
    def predict(cls, model, static, X, meta):
        import jax.numpy as jnp
        return jnp.argmax(cls.predict_proba(model, static, X, meta),
                          axis=1).astype(jnp.int32)

    @classmethod
    def decision(cls, model, static, X, meta):
        p = cls.predict_proba(model, static, X, meta)
        if meta["n_classes"] == 2:
            return p[:, 1] - p[:, 0]
        return p


class PackedForestRegressor(_PackedEnsembleBase):
    is_classifier = False

    @classmethod
    def predict(cls, model, static, X, meta):
        return cls._leaf(model, X)[:, :, 0].mean(axis=0)


class PackedGBRegressor(_PackedEnsembleBase):
    is_classifier = False

    @classmethod
    def predict(cls, model, static, X, meta):
        import jax.numpy as jnp
        v = cls._leaf(model, X)[:, :, 0]                  # (T, n)
        return jnp.asarray(model["init"]) \
            + model["learning_rate"] * v.sum(axis=0)


class PackedGBClassifier(_PackedEnsembleBase):
    is_classifier = True

    @classmethod
    def _raw(cls, model, X):
        import jax.numpy as jnp
        v = cls._leaf(model, X)[:, :, 0]                  # (S*K, n)
        k_eff = int(model["k_eff"])                       # 1 binary
        S = v.shape[0] // k_eff
        per_class = v.reshape(S, k_eff, -1).sum(axis=0).T  # (n, k_eff)
        return jnp.asarray(model["init"])[None, :] \
            + model["learning_rate"] * per_class

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        import jax
        import jax.numpy as jnp
        raw = cls._raw(model, X)
        if int(model["k_eff"]) == 1:
            p1 = jax.nn.sigmoid(raw[:, 0])
            return jnp.stack([1.0 - p1, p1], axis=1)
        return jax.nn.softmax(raw, axis=1)

    @classmethod
    def predict(cls, model, static, X, meta):
        import jax.numpy as jnp
        return jnp.argmax(cls.predict_proba(model, static, X, meta),
                          axis=1).astype(jnp.int32)

    @classmethod
    def decision(cls, model, static, X, meta):
        raw = cls._raw(model, X)
        return raw[:, 0] if int(model["k_eff"]) == 1 else raw


def forest_to_model(est) -> Dict[str, Any]:
    """RandomForest{Classifier,Regressor} -> packed model dict."""
    packed = pack_trees([e.tree_ for e in est.estimators_])
    return packed


def gb_to_model(est) -> Dict[str, Any]:
    """GradientBoosting{Classifier,Regressor} (default init only) ->
    packed model dict with the constant raw init and learning rate."""
    from sklearn.dummy import DummyClassifier, DummyRegressor

    init = est.init_
    if not isinstance(init, (DummyClassifier, DummyRegressor)):
        raise ValueError(
            "Cannot convert GradientBoosting with a custom init "
            "estimator; only the default (constant) init is supported")
    ests = np.asarray(est.estimators_)                    # (S, K)
    S, K = ests.shape
    packed = pack_trees([t.tree_ for t in ests.reshape(-1)])
    # constant raw init: take it from sklearn's own link of the dummy
    X0 = np.zeros((1, est.n_features_in_), np.float32)
    raw0 = est._raw_predict_init(X0)[0]                   # (K,) or (1,)
    packed["init"] = np.asarray(raw0, np.float32)
    packed["learning_rate"] = float(est.learning_rate)
    packed["k_eff"] = int(K)
    return packed
