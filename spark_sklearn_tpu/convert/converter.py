"""Converter — fitted-model interchange.

The reference's Converter (reference: python/spark_sklearn/converter.py)
moves fitted models between sklearn and Spark MLlib's JVM objects via py4j,
supporting exactly LogisticRegression{,Model} and LinearRegression{,Model},
plus `toPandas` for Vector-column DataFrames.  The TPU rebuild has no JVM:
the device-side representation of a fitted model is a **JAX parameter
pytree** (SURVEY §2.3 substrate table, last row).  The Converter therefore
maps:

    sklearn fitted estimator  <->  TpuModel (family + param pytree + meta)

and keeps the reference's method names as aliases (`toSKLearn`, `toTPU` in
place of `toSpark`, `toPandas`).  Families covered (superset of the
reference's two): LogisticRegression, LinearRegression, Ridge,
ElasticNet/Lasso.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from spark_sklearn_tpu.models.base import resolve_family


class TpuModel:
    """A fitted model as the device representation: (family, pytree, meta).

    `predict`/`decision_function` run the family's compiled functions — this
    is what KeyedModel stores per key and what multi-chip inference shards.
    """

    def __init__(self, family, model: Dict[str, Any], static: Dict[str, Any],
                 meta: Dict[str, Any]):
        self.family = family
        self.model = model
        self.static = static
        self.meta = meta

    def _device_X(self, X):
        import jax.numpy as jnp
        Xh = np.asarray(X)
        check = getattr(self.family, "check_predict_X", None)
        if check is not None:
            # families with input constraints sklearn enforces at
            # predict (e.g. CategoricalNB's category range) raise the
            # same errors host-side instead of silently masking
            check(Xh, self.meta)
        return jnp.asarray(Xh)

    def predict(self, X):
        X = self._device_X(X)
        pred = self.family.predict(self.model, self.static, X, self.meta)
        pred = np.asarray(pred)
        if self.family.is_classifier:
            return self.meta["classes"][pred]
        return pred

    def decision_function(self, X):
        X = self._device_X(X)
        return np.asarray(self.family.decision(
            self.model, self.static, X, self.meta))

    def predict_proba(self, X):
        X = self._device_X(X)
        return np.asarray(self.family.predict_proba(
            self.model, self.static, X, self.meta))

    def transform(self, X):
        import jax.numpy as jnp
        X = jnp.asarray(np.asarray(X))
        return np.asarray(self.family.transform(
            self.model, self.static, X, self.meta))

    def __repr__(self):
        return f"TpuModel(family={self.family.name})"


class _BruteKNNShim:
    """Standalone device inference for converted KNeighbors models.

    The search-internal KNN families cache per-fold vote tables (their
    `predict` ignores X), so a converted model instead stores the fitted
    data itself — sklearn's own fitted state for KNN — and evaluates
    brute-force euclidean k-NN as one (q, n) distance matmul per query
    batch, the same MXU identity the search family uses."""

    is_classifier = False
    name = "knn_brute_regressor"

    @staticmethod
    def _neighbor_votes(model, static, X):
        import jax.lax as lax
        import jax.numpy as jnp

        from spark_sklearn_tpu.models.cluster import _sq_dists
        from spark_sklearn_tpu.models.neighbors import _EPS_DIST

        k = int(static.get("n_neighbors", 5))
        negv, idx = lax.top_k(-_sq_dists(X, model["X"]), k)
        if static.get("weights", "uniform") == "distance":
            w = 1.0 / jnp.maximum(jnp.sqrt(-negv), _EPS_DIST)
            # sklearn's _get_weights: a query with ANY exact-duplicate
            # neighbor uses ONLY its zero-distance neighbors (weight 1),
            # zeroing the rest — the eps clamp alone would mix the other
            # neighbors in with tiny weights
            zero = negv >= 0.0          # negv = -dist^2 <= 0
            w = jnp.where(jnp.any(zero, axis=1, keepdims=True),
                          zero.astype(w.dtype), w)
        else:
            w = jnp.ones_like(negv)
        return idx, w

    @classmethod
    def predict(cls, model, static, X, meta):
        import jax.numpy as jnp
        idx, w = cls._neighbor_votes(model, static, X)
        vals = model["y"][idx]                       # (q, k)
        return jnp.sum(vals * w, axis=1) / jnp.sum(w, axis=1)


class _BruteKNNClassifierShim(_BruteKNNShim):
    is_classifier = True
    name = "knn_brute_classifier"

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        import jax
        import jax.numpy as jnp
        idx, w = cls._neighbor_votes(model, static, X)
        oh = jax.nn.one_hot(model["y"][idx], meta["n_classes"],
                            dtype=w.dtype)           # (q, k, C)
        votes = jnp.sum(oh * w[:, :, None], axis=1)
        return votes / jnp.sum(votes, axis=1, keepdims=True)

    @classmethod
    def predict(cls, model, static, X, meta):
        import jax.numpy as jnp
        return jnp.argmax(
            cls.predict_proba(model, static, X, meta), axis=1)


class _PCATransformShim:
    """Transformer-side TpuModel for converted sklearn PCA (the search
    uses PCA only inside compiled pipelines — models/preprocessing.py
    PCAStep — so the converter carries its own shim reusing the step's
    apply)."""

    is_classifier = False
    name = "pca_transform"

    @staticmethod
    def transform(model, static, X, meta):
        from spark_sklearn_tpu.models.preprocessing import PCAStep
        return PCAStep.apply(static, model, X)


class Converter:
    """Convert fitted models between sklearn and the TPU pytree form.

    API mirrors the reference (converter.py): the ctor takes an optional
    legacy context argument (ignored — kept so `Converter(sc)` still works).

    Examples
    --------
    >>> import numpy as np
    >>> from sklearn.linear_model import LinearRegression
    >>> from spark_sklearn_tpu import Converter
    >>> X = np.array([[0.0], [1.0], [2.0]]); y = np.array([0.0, 2.0, 4.0])
    >>> tm = Converter().toTPU(LinearRegression().fit(X, y))
    >>> np.round(tm.predict(np.array([[3.0]])), 3)
    array([6.], dtype=float32)
    >>> type(Converter().toSKLearn(tm)).__name__
    'LinearRegression'
    """

    def __init__(self, sc=None):
        self._sc = sc  # accepted for reference API compatibility; unused

    # -- sklearn -> TPU (reference: toSpark) -----------------------------
    #: families whose fitted state is representable as (coef, intercept)
    _CONVERTIBLE = {"logistic_regression", "ridge", "linear_regression",
                    "elastic_net"}

    def toTPU(self, sklearn_model) -> TpuModel:
        import jax.numpy as jnp
        from spark_sklearn_tpu.models.preprocessing import (PCAStep,
                                                            resolve_step)
        if resolve_step(sklearn_model) is PCAStep:
            return self._pca_to_tpu(sklearn_model)
        family = resolve_family(sklearn_model)
        if family is not None and family.name in ("svc", "nu_svc"):
            return self._svc_to_tpu(sklearn_model, family)
        if family is not None and family.name == "kmeans":
            return self._kmeans_to_tpu(sklearn_model, family)
        if family is not None and family.name in ("kneighbors_classifier",
                                                  "kneighbors_regressor"):
            return self._knn_to_tpu(sklearn_model, family)
        if family is not None and family.name in (
                "gaussian_nb", "multinomial_nb", "bernoulli_nb",
                "complement_nb", "categorical_nb"):
            return self._nb_to_tpu(sklearn_model, family)
        if family is not None and family.name in ("mlp_classifier",
                                                  "mlp_regressor"):
            return self._mlp_to_tpu(sklearn_model, family)
        if family is not None and family.name in (
                "random_forest_classifier", "random_forest_regressor",
                "gradient_boosting_classifier",
                "gradient_boosting_regressor"):
            return self._tree_ensemble_to_tpu(sklearn_model, family)
        if family is None or family.name not in self._CONVERTIBLE:
            raise ValueError(
                f"Cannot convert {type(sklearn_model).__name__}: not a "
                f"convertible family (reference Converter supports "
                f"LogisticRegression/LinearRegression only; this one also "
                f"covers Ridge/ElasticNet/Lasso, SVC/NuSVC, "
                f"MLPClassifier/MLPRegressor, RandomForest/"
                f"GradientBoosting ensembles, KMeans, KNeighbors, PCA "
                f"and the naive Bayes families)")
        if not hasattr(sklearn_model, "coef_"):
            raise ValueError("model must be fitted (missing coef_)")
        static = family.extract_params(sklearn_model)
        coef = np.asarray(sklearn_model.coef_)
        intercept = np.asarray(getattr(sklearn_model, "intercept_", 0.0))
        meta: Dict[str, Any] = {"n_features": int(coef.shape[-1])}
        if family.is_classifier:
            classes = np.asarray(sklearn_model.classes_)
            meta["n_classes"] = len(classes)
            meta["classes"] = classes
            model = {"coef": jnp.asarray(coef, jnp.float32),
                     "intercept": jnp.asarray(
                         np.atleast_1d(intercept), jnp.float32)}
        else:
            model = {"coef": jnp.asarray(coef.ravel(), jnp.float32),
                     "intercept": jnp.asarray(
                         np.asarray(intercept).reshape(()), jnp.float32)}
        return TpuModel(family, model, static, meta)

    # alias keeping the reference's verb ("to the distributed side")
    toSpark = toTPU

    def _svc_to_tpu(self, est, family) -> TpuModel:
        """Fitted sklearn SVC/NuSVC -> representer-form TpuModel.

        Per-pair signed alphas are rebuilt from the public OvO layout:
        a support vector of class c carries k-1 dual coefficients, one
        per classifier involving c, ordered by the other class index —
        so pair (i, j) reads row j-1 on class-i columns and row i on
        class-j columns.  Public dual_coef_/intercept_ give the PUBLIC
        decision orientation directly (sklearn pre-flips the binary
        case), which matches the family's pair_dec convention."""
        import jax.numpy as jnp
        from sklearn.utils.validation import check_is_fitted

        from spark_sklearn_tpu.models.svm import _pairs

        check_is_fitted(est)
        kernel = est.kernel
        if not isinstance(kernel, str) or kernel == "precomputed":
            # precomputed/callable kernels store no usable support
            # vectors for the representer form — converting would
            # silently predict garbage
            raise ValueError(
                f"Cannot convert SVC with kernel={kernel!r}: only "
                "string kernels (rbf/linear/poly/sigmoid) carry the "
                "support-vector form the TPU model evaluates")
        classes = np.asarray(est.classes_)
        k = len(classes)
        pairs = _pairs(k)
        sv = np.asarray(est.support_vectors_, np.float32)
        dual = np.atleast_2d(np.asarray(est.dual_coef_, np.float32))
        icpt = np.atleast_1d(np.asarray(est.intercept_, np.float32))
        starts = np.concatenate(
            [[0], np.cumsum(np.asarray(est.n_support_))])
        P, m = len(pairs), sv.shape[0]
        alphas = np.zeros((P, m), np.float32)
        for p, (i, j) in enumerate(pairs):
            alphas[p, starts[i]:starts[i + 1]] = \
                dual[j - 1, starts[i]:starts[i + 1]]
            alphas[p, starts[j]:starts[j + 1]] = \
                dual[i, starts[j]:starts[j + 1]]
        static = dict(est.get_params(deep=False))
        meta: Dict[str, Any] = {
            "n_classes": k, "classes": classes,
            "n_features": int(sv.shape[1]), "pairs": pairs,
            # gamma resolved against the training stats sklearn used (we
            # no longer have X to re-derive "scale"); static["gamma"]
            # keeps the USER's hyperparameter so a round-tripped
            # estimator refits identically
            "resolved_gamma": float(est._gamma)}
        model = {"sv_X": jnp.asarray(sv),
                 "alphas": jnp.asarray(alphas),
                 "intercepts": jnp.asarray(icpt)}
        from spark_sklearn_tpu.models.svm import _probability_value_on
        if _probability_value_on(getattr(est, "probability", False)) and \
                getattr(est, "_probA", np.empty(0)).size:
            # the private pair is identical to probA_/probB_ without
            # sklearn 1.9's deprecation warning on the public accessor
            model["probA"] = jnp.asarray(est._probA, jnp.float32)
            model["probB"] = jnp.asarray(est._probB, jnp.float32)
        tm = TpuModel(family, model, static, meta)
        # stash what an sklearn round trip needs beyond the pytree
        tm._sv_class_starts = starts
        return tm

    def _mlp_to_tpu(self, est, family) -> TpuModel:
        """Fitted sklearn MLP -> layers-pytree TpuModel (the family's
        native parameter layout: [{"W", "b"}, ...])."""
        import jax.numpy as jnp
        from sklearn.utils.validation import check_is_fitted

        check_is_fitted(est)
        if family.is_classifier and \
                getattr(est, "out_activation_", "") == "logistic" and \
                getattr(est, "n_outputs_", 1) > 1:
            # multilabel head: sklearn applies an elementwise sigmoid
            # per label; the family's softmax head would silently
            # compute different probabilities
            raise ValueError(
                "Cannot convert a multilabel MLPClassifier "
                f"(n_outputs_={est.n_outputs_} with a logistic head); "
                "only binary/multiclass classifiers are supported")
        coefs = [np.asarray(W, np.float32) for W in est.coefs_]
        icpts = [np.asarray(b, np.float32) for b in est.intercepts_]
        static = dict(est.get_params(deep=False))
        meta: Dict[str, Any] = {
            "n_features": int(coefs[0].shape[0])}
        if family.is_classifier:
            classes = np.asarray(est.classes_)
            meta["n_classes"] = len(classes)
            meta["classes"] = classes
            if coefs[-1].shape[1] == 1 and len(classes) == 2:
                # sklearn's binary head is one logistic logit; the
                # family's is two softmax logits — [0, z] is the exact
                # equivalent (softmax([0, z])[1] == sigmoid(z))
                coefs[-1] = np.concatenate(
                    [np.zeros_like(coefs[-1]), coefs[-1]], axis=1)
                icpts[-1] = np.concatenate(
                    [np.zeros_like(icpts[-1]), icpts[-1]])
        else:
            meta["n_targets"] = int(coefs[-1].shape[1])
        layers = [{"W": jnp.asarray(W), "b": jnp.asarray(b)}
                  for W, b in zip(coefs, icpts)]
        return TpuModel(family, {"layers": layers}, static, meta)

    def _kmeans_to_tpu(self, est, family) -> TpuModel:
        """Fitted sklearn KMeans -> centers-pytree TpuModel: the fitted
        state is just `cluster_centers_` (plus inertia/n_iter bookkeeping),
        and the family's own predict/decision evaluate argmin squared
        distance from the stored centers (models/cluster.py)."""
        import jax.numpy as jnp
        from sklearn.utils.validation import check_is_fitted

        check_is_fitted(est)
        static = dict(est.get_params(deep=False))
        centers = np.asarray(est.cluster_centers_, np.float32)
        model = {"centers": jnp.asarray(centers),
                 "inertia": jnp.asarray(float(est.inertia_), jnp.float32),
                 "n_iter": jnp.asarray(int(est.n_iter_), jnp.int32)}
        meta: Dict[str, Any] = {"n_features": int(centers.shape[1])}
        return TpuModel(family, model, static, meta)

    def _knn_to_tpu(self, est, family) -> TpuModel:
        """Fitted sklearn KNeighbors{Classifier,Regressor} -> a TpuModel
        holding the fit data itself (k-NN's entire fitted state) with a
        brute-euclidean device evaluator (_BruteKNNShim).  The guard
        mirrors the search family's compiled-metric envelope."""
        import jax.numpy as jnp
        from sklearn.utils.validation import check_is_fitted

        from spark_sklearn_tpu.models.neighbors import _check_metric

        check_is_fitted(est)
        if np.asarray(est._y).ndim > 1 or getattr(est, "outputs_2d_",
                                                  False):
            # ravel()ing (n, n_outputs) targets would interleave columns
            # into the vote table and predict garbage
            raise ValueError(
                "Cannot convert a multi-output KNeighbors model; only "
                "single-output estimators are supported")
        static = dict(est.get_params(deep=False))
        _check_metric(static)
        fit_X = np.asarray(est._fit_X, np.float32)
        meta: Dict[str, Any] = {"n_features": int(fit_X.shape[1])}
        if family.is_classifier:
            classes = np.asarray(est.classes_)
            meta["n_classes"] = len(classes)
            meta["classes"] = classes
            # sklearn stores _y already encoded against classes_
            y = jnp.asarray(np.asarray(est._y).ravel(), jnp.int32)
            shim = _BruteKNNClassifierShim
        else:
            y = jnp.asarray(np.asarray(est._y).ravel(), jnp.float32)
            shim = _BruteKNNShim
        model = {"X": jnp.asarray(fit_X), "y": y}
        return TpuModel(shim, model, static, meta)

    def _nb_to_tpu(self, est, family) -> TpuModel:
        """Fitted sklearn naive-Bayes -> TpuModel over the family's own
        pytree layout (models/naive_bayes.py): Gaussian carries
        theta/var/log-prior, the discrete families their smoothed
        feature log-probabilities — the complete fitted state, so
        device predict/proba match sklearn at float tolerance."""
        import jax.numpy as jnp
        from sklearn.utils.validation import check_is_fitted

        check_is_fitted(est)
        static = dict(est.get_params(deep=False))
        classes = np.asarray(est.classes_)
        meta: Dict[str, Any] = {
            "n_classes": len(classes), "classes": classes,
            "n_features": int(est.n_features_in_)}
        if family.name == "gaussian_nb":
            model = {
                "theta": jnp.asarray(est.theta_, jnp.float32),
                "var": jnp.asarray(est.var_, jnp.float32),
                "log_prior": jnp.asarray(
                    np.log(np.maximum(est.class_prior_, 0.0)),
                    jnp.float32)}
        elif family.name == "categorical_nb":
            # sklearn keeps a ragged per-feature list; pad to the max
            # category count (padded cells are never gathered — codes
            # stay below each feature's own n_categories_)
            ncat = np.asarray(est.n_categories_, np.int64)
            k, d, C = len(classes), len(ncat), int(ncat.max())
            # zero-pad (NOT -inf): the jll einsum multiplies the one-hot
            # by flp, and 0 * -inf would poison it with NaN; padded
            # cells contribute 0 because the one-hot never lights them
            flp = np.zeros((k, d, C), np.float32)
            for i, f in enumerate(est.feature_log_prob_):
                flp[:, i, :f.shape[1]] = f
            model = {
                "feature_log_prob": jnp.asarray(flp),
                "class_log_prior": jnp.asarray(
                    est.class_log_prior_, jnp.float32),
                "class_count": jnp.asarray(
                    est.class_count_, jnp.float32)}
            meta["n_categories"] = ncat
        else:
            model = {
                "feature_log_prob": jnp.asarray(
                    est.feature_log_prob_, jnp.float32),
                "class_log_prior": jnp.asarray(
                    est.class_log_prior_, jnp.float32),
                "class_count": jnp.asarray(
                    est.class_count_, jnp.float32)}
            if family.name == "bernoulli_nb":
                # the family's jll needs log(1-p); rebuild it from the
                # stored log p (exact: both came from the same counts)
                log_p = np.asarray(est.feature_log_prob_, np.float64)
                model["log_neg_prob"] = jnp.asarray(
                    np.log1p(-np.exp(log_p)), jnp.float32)
        return TpuModel(family, model, static, meta)

    def _pca_to_tpu(self, est) -> TpuModel:
        """Fitted sklearn PCA -> TpuModel over PCAStep's state pytree
        ({mean, components, var}); transform reuses the compiled step
        (models/preprocessing.py PCAStep.apply), so whitening matches.
        The sklearn-only fitted attributes ride along in meta so a round
        trip restores them exactly."""
        import jax.numpy as jnp
        from sklearn.utils.validation import check_is_fitted

        check_is_fitted(est)
        static = dict(est.get_params(deep=False))
        static["n_components"] = int(est.n_components_)
        model = {"mean": jnp.asarray(est.mean_, jnp.float32),
                 "components": jnp.asarray(est.components_, jnp.float32),
                 "var": jnp.asarray(est.explained_variance_, jnp.float32)}
        meta: Dict[str, Any] = {
            "n_features": int(est.n_features_in_),
            "n_samples": int(est.n_samples_),
            "explained_variance_ratio": np.asarray(
                est.explained_variance_ratio_, np.float64),
            "singular_values": np.asarray(est.singular_values_, np.float64),
            "noise_variance": float(est.noise_variance_),
            # float64 originals so toSKLearn round-trips exactly
            "mean64": np.asarray(est.mean_, np.float64),
            "components64": np.asarray(est.components_, np.float64),
            "explained_variance64": np.asarray(
                est.explained_variance_, np.float64),
        }
        return TpuModel(_PCATransformShim, model, static, meta)

    def _tree_ensemble_to_tpu(self, est, family) -> TpuModel:
        """Fitted sklearn tree ensemble -> packed-arrays TpuModel with a
        compiled level-by-level traversal (convert/tree_infer.py).  The
        packed form is exact — same thresholds on the same raw X — so
        predict/proba parity with sklearn is at float tolerance.  The
        reverse direction is unsupported: the search-internal histogram
        families cache fold predictions, not tree structures."""
        from sklearn.utils.validation import check_is_fitted

        from spark_sklearn_tpu.convert import tree_infer as ti

        check_is_fitted(est)
        if getattr(est, "n_outputs_", 1) > 1:
            # pack_trees keeps one output column; silently dropping the
            # rest would return wrong-shaped predictions
            raise ValueError(
                "Cannot convert a multi-output tree ensemble "
                f"(n_outputs_={est.n_outputs_}); only single-output "
                "ensembles are supported")
        name = family.name
        if name.startswith("random_forest"):
            model = ti.forest_to_model(est)
            shim = (ti.PackedForestClassifier if family.is_classifier
                    else ti.PackedForestRegressor)
        else:
            model = ti.gb_to_model(est)
            shim = (ti.PackedGBClassifier if family.is_classifier
                    else ti.PackedGBRegressor)
        static = dict(est.get_params(deep=False))
        meta: Dict[str, Any] = {"n_features": int(est.n_features_in_)}
        if family.is_classifier:
            classes = np.asarray(est.classes_)
            meta["n_classes"] = len(classes)
            meta["classes"] = classes
        return TpuModel(shim, model, static, meta)

    # -- TPU -> sklearn (reference: toSKLearn) ---------------------------
    def toSKLearn(self, tpu_model: TpuModel):
        from sklearn import linear_model as lm

        family = tpu_model.family
        if family.name in ("svc", "nu_svc") and "sv_X" in tpu_model.model:
            return self._svc_to_sklearn(tpu_model)
        if family.name in ("mlp_classifier", "mlp_regressor"):
            return self._mlp_to_sklearn(tpu_model)
        if family.name in ("knn_brute_classifier", "knn_brute_regressor"):
            return self._knn_to_sklearn(tpu_model)
        if family.name == "pca_transform":
            return self._pca_to_sklearn(tpu_model)
        if family.name == "sk_tree_ensemble":
            raise ValueError(
                "tree-ensemble TpuModels are inference-only (packed "
                "traversal arrays); keep the original sklearn estimator "
                "for the sklearn side")
        attrs = family.sklearn_attrs(
            tpu_model.model, tpu_model.static, tpu_model.meta)
        cls = {
            "logistic_regression": lm.LogisticRegression,
            "ridge": lm.Ridge,
            "linear_regression": lm.LinearRegression,
            "elastic_net": lm.ElasticNet,
        }.get(family.name)
        if cls is None and family.name == "kmeans":
            from sklearn.cluster import KMeans
            cls = KMeans
        if cls is None and family.name in (
                "gaussian_nb", "multinomial_nb", "bernoulli_nb",
                "complement_nb", "categorical_nb"):
            from sklearn import naive_bayes as nb
            cls = {"gaussian_nb": nb.GaussianNB,
                   "multinomial_nb": nb.MultinomialNB,
                   "bernoulli_nb": nb.BernoulliNB,
                   "complement_nb": nb.ComplementNB,
                   "categorical_nb": nb.CategoricalNB}[family.name]
        if cls is None:
            raise ValueError(f"no sklearn counterpart for {family.name}")
        valid = cls().get_params()
        est = cls(**{k: v for k, v in tpu_model.static.items()
                     if k in valid})
        for k, v in attrs.items():
            setattr(est, k, v)
        if family.name == "kmeans":
            # sklearn's KMeans.predict reads the fitted thread plan
            est._n_threads = 1
        return est

    to_sklearn = toSKLearn

    def _svc_to_sklearn(self, tm: TpuModel):
        """Representer-form TpuModel -> a functional sklearn SVC/NuSVC,
        rebuilt by attribute injection (libsvm predicts from stored
        arrays: support vectors, class-grouped dual coefficients,
        intercepts, probA/probB).  Needs the class grouping of the
        support vectors, which toTPU stashes (`_sv_class_starts`)."""
        from sklearn.svm import SVC as SkSVC, NuSVC as SkNuSVC

        starts = getattr(tm, "_sv_class_starts", None)
        if starts is None:
            raise ValueError(
                "toSKLearn for SVC needs the support vectors' class "
                "grouping; convert with toTPU first (round trip) — "
                "export of search-internal SVC models is not supported")
        cls = SkNuSVC if tm.family.name == "nu_svc" else SkSVC
        valid = cls().get_params()
        est = cls(**{k: v for k, v in tm.static.items() if k in valid})
        classes = np.asarray(tm.meta["classes"])
        k = len(classes)
        sv = np.asarray(tm.model["sv_X"], np.float64)
        alphas = np.asarray(tm.model["alphas"], np.float64)   # public
        icpt = np.asarray(tm.model["intercepts"], np.float64)
        m = sv.shape[0]
        pairs = tm.meta["pairs"]
        dual_pub = np.zeros((max(1, k - 1), m))
        for p, (i, j) in enumerate(pairs):
            dual_pub[j - 1, starts[i]:starts[i + 1]] = \
                alphas[p, starts[i]:starts[i + 1]]
            dual_pub[i, starts[j]:starts[j + 1]] = \
                alphas[p, starts[j]:starts[j + 1]]
        flip = -1.0 if k == 2 else 1.0   # sklearn's binary public flip
        est.classes_ = classes
        est.support_vectors_ = sv
        est.support_ = np.arange(m, dtype=np.int32)
        est._n_support = np.diff(starts).astype(np.int32)
        est.dual_coef_ = dual_pub
        est.intercept_ = icpt
        est._dual_coef_ = flip * dual_pub
        est._intercept_ = flip * icpt
        est._probA = np.asarray(tm.model.get("probA", np.empty(0)),
                                np.float64)
        est._probB = np.asarray(tm.model.get("probB", np.empty(0)),
                                np.float64)
        est._gamma = float(tm.meta["resolved_gamma"])
        est._sparse = False
        est.shape_fit_ = (m, sv.shape[1])
        est.fit_status_ = 0
        est.class_weight_ = np.ones(k)
        est.n_features_in_ = sv.shape[1]
        est.n_iter_ = np.zeros(len(pairs), dtype=np.int32)
        return est

    def _knn_to_sklearn(self, tm: TpuModel):
        """Brute-KNN TpuModel -> sklearn KNeighbors estimator by refitting
        on the stored data — for k-NN, fit() IS storing the data, so this
        is an exact reconstruction, not an approximation."""
        from sklearn.neighbors import (KNeighborsClassifier,
                                       KNeighborsRegressor)

        is_clf = tm.family.is_classifier
        cls = KNeighborsClassifier if is_clf else KNeighborsRegressor
        valid = cls().get_params()
        est = cls(**{k: v for k, v in tm.static.items() if k in valid})
        X = np.asarray(tm.model["X"], np.float64)
        y = np.asarray(tm.model["y"])
        if is_clf:
            y = np.asarray(tm.meta["classes"])[y]
        return est.fit(X, y)

    def _pca_to_sklearn(self, tm: TpuModel):
        """PCA TpuModel -> a functional sklearn PCA by attribute
        injection (transform reads components_/mean_/explained_variance_);
        exact when the model came from toTPU (float64 originals ride in
        meta), float32-cast otherwise."""
        from sklearn.decomposition import PCA

        valid = PCA().get_params()
        est = PCA(**{k: v for k, v in tm.static.items() if k in valid})
        meta = tm.meta
        est.components_ = np.asarray(
            meta.get("components64", np.asarray(tm.model["components"])),
            np.float64)
        est.mean_ = np.asarray(
            meta.get("mean64", np.asarray(tm.model["mean"])), np.float64)
        est.explained_variance_ = np.asarray(
            meta.get("explained_variance64",
                     np.asarray(tm.model["var"])), np.float64)
        n_comp, n_feat = est.components_.shape
        est.n_components_ = n_comp
        est.n_features_in_ = n_feat
        est.n_samples_ = int(meta.get("n_samples", 0))
        est.explained_variance_ratio_ = np.asarray(meta.get(
            "explained_variance_ratio", np.full(n_comp, np.nan)))
        est.singular_values_ = np.asarray(meta.get(
            "singular_values", np.full(n_comp, np.nan)))
        est.noise_variance_ = float(meta.get("noise_variance", 0.0))
        return est

    def _mlp_to_sklearn(self, tm: TpuModel):
        """Layers-pytree TpuModel -> a functional sklearn MLP (predict
        runs sklearn's own forward pass from coefs_/intercepts_)."""
        from sklearn.neural_network import MLPClassifier, MLPRegressor
        from sklearn.preprocessing import LabelBinarizer

        is_clf = tm.family.is_classifier
        cls = MLPClassifier if is_clf else MLPRegressor
        valid = cls().get_params()
        est = cls(**{k: v for k, v in tm.static.items() if k in valid})
        coefs = [np.asarray(l["W"], np.float64)
                 for l in tm.model["layers"]]
        icpts = [np.asarray(l["b"], np.float64)
                 for l in tm.model["layers"]]
        if is_clf:
            classes = np.asarray(tm.meta["classes"])
            if len(classes) == 2 and coefs[-1].shape[1] == 2:
                # family head is two softmax logits; sklearn's binary
                # head is ONE logistic logit — z1 - z0 is the exact
                # equivalent (sigmoid(z1-z0) == softmax([z0, z1])[1])
                coefs[-1] = (coefs[-1][:, 1:] - coefs[-1][:, :1])
                icpts[-1] = icpts[-1][1:] - icpts[-1][:1]
            est.classes_ = classes
            est._label_binarizer = LabelBinarizer().fit(classes)
            est.out_activation_ = ("logistic" if len(classes) == 2
                                   else "softmax")
            est.n_outputs_ = coefs[-1].shape[1]
        else:
            est.out_activation_ = "identity"
            est.n_outputs_ = coefs[-1].shape[1]
        est.coefs_ = coefs
        est.intercepts_ = icpts
        est.n_layers_ = len(coefs) + 1
        est.n_features_in_ = coefs[0].shape[0]
        if "n_iter" in tm.model:
            est.n_iter_ = int(tm.model["n_iter"])
        return est

    # -- DataFrame helper (reference: toPandas) --------------------------
    def toPandas(self, df):
        """Convert a pandas DataFrame whose cells may hold jax/numpy arrays
        or CSRMatrix rows into a flat pandas DataFrame of numpy arrays —
        the reference's Vector-column -> numpy behavior without a collect().
        """
        import pandas as pd
        from spark_sklearn_tpu.sparse.csr import CSRMatrix

        def _cell(v):
            if isinstance(v, CSRMatrix):
                return np.asarray(v.to_scipy().toarray()).ravel()
            if hasattr(v, "__array__") and not np.isscalar(v):
                return np.asarray(v)
            return v

        return pd.DataFrame(
            {c: [_cell(v) for v in df[c]] for c in df.columns})
