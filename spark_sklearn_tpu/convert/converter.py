"""Converter — fitted-model interchange.

The reference's Converter (reference: python/spark_sklearn/converter.py)
moves fitted models between sklearn and Spark MLlib's JVM objects via py4j,
supporting exactly LogisticRegression{,Model} and LinearRegression{,Model},
plus `toPandas` for Vector-column DataFrames.  The TPU rebuild has no JVM:
the device-side representation of a fitted model is a **JAX parameter
pytree** (SURVEY §2.3 substrate table, last row).  The Converter therefore
maps:

    sklearn fitted estimator  <->  TpuModel (family + param pytree + meta)

and keeps the reference's method names as aliases (`toSKLearn`, `toTPU` in
place of `toSpark`, `toPandas`).  Families covered (superset of the
reference's two): LogisticRegression, LinearRegression, Ridge,
ElasticNet/Lasso.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from spark_sklearn_tpu.models.base import resolve_family


class TpuModel:
    """A fitted model as the device representation: (family, pytree, meta).

    `predict`/`decision_function` run the family's compiled functions — this
    is what KeyedModel stores per key and what multi-chip inference shards.
    """

    def __init__(self, family, model: Dict[str, Any], static: Dict[str, Any],
                 meta: Dict[str, Any]):
        self.family = family
        self.model = model
        self.static = static
        self.meta = meta

    def predict(self, X):
        import jax.numpy as jnp
        X = jnp.asarray(np.asarray(X))
        pred = self.family.predict(self.model, self.static, X, self.meta)
        pred = np.asarray(pred)
        if self.family.is_classifier:
            return self.meta["classes"][pred]
        return pred

    def decision_function(self, X):
        import jax.numpy as jnp
        X = jnp.asarray(np.asarray(X))
        return np.asarray(self.family.decision(
            self.model, self.static, X, self.meta))

    def __repr__(self):
        return f"TpuModel(family={self.family.name})"


class Converter:
    """Convert fitted models between sklearn and the TPU pytree form.

    API mirrors the reference (converter.py): the ctor takes an optional
    legacy context argument (ignored — kept so `Converter(sc)` still works).

    Examples
    --------
    >>> import numpy as np
    >>> from sklearn.linear_model import LinearRegression
    >>> from spark_sklearn_tpu import Converter
    >>> X = np.array([[0.0], [1.0], [2.0]]); y = np.array([0.0, 2.0, 4.0])
    >>> tm = Converter().toTPU(LinearRegression().fit(X, y))
    >>> np.round(tm.predict(np.array([[3.0]])), 3)
    array([6.], dtype=float32)
    >>> type(Converter().toSKLearn(tm)).__name__
    'LinearRegression'
    """

    def __init__(self, sc=None):
        self._sc = sc  # accepted for reference API compatibility; unused

    # -- sklearn -> TPU (reference: toSpark) -----------------------------
    #: families whose fitted state is representable as (coef, intercept)
    _CONVERTIBLE = {"logistic_regression", "ridge", "linear_regression",
                    "elastic_net"}

    def toTPU(self, sklearn_model) -> TpuModel:
        import jax.numpy as jnp
        family = resolve_family(sklearn_model)
        if family is None or family.name not in self._CONVERTIBLE:
            raise ValueError(
                f"Cannot convert {type(sklearn_model).__name__}: not a "
                f"linear-model family (reference Converter supports "
                f"LogisticRegression/LinearRegression only; this one also "
                f"covers Ridge/ElasticNet/Lasso)")
        if not hasattr(sklearn_model, "coef_"):
            raise ValueError("model must be fitted (missing coef_)")
        static = family.extract_params(sklearn_model)
        coef = np.asarray(sklearn_model.coef_)
        intercept = np.asarray(getattr(sklearn_model, "intercept_", 0.0))
        meta: Dict[str, Any] = {"n_features": int(coef.shape[-1])}
        if family.is_classifier:
            classes = np.asarray(sklearn_model.classes_)
            meta["n_classes"] = len(classes)
            meta["classes"] = classes
            model = {"coef": jnp.asarray(coef, jnp.float32),
                     "intercept": jnp.asarray(
                         np.atleast_1d(intercept), jnp.float32)}
        else:
            model = {"coef": jnp.asarray(coef.ravel(), jnp.float32),
                     "intercept": jnp.asarray(
                         np.asarray(intercept).reshape(()), jnp.float32)}
        return TpuModel(family, model, static, meta)

    # alias keeping the reference's verb ("to the distributed side")
    toSpark = toTPU

    # -- TPU -> sklearn (reference: toSKLearn) ---------------------------
    def toSKLearn(self, tpu_model: TpuModel):
        from sklearn import linear_model as lm

        family = tpu_model.family
        attrs = family.sklearn_attrs(
            tpu_model.model, tpu_model.static, tpu_model.meta)
        cls = {
            "logistic_regression": lm.LogisticRegression,
            "ridge": lm.Ridge,
            "linear_regression": lm.LinearRegression,
            "elastic_net": lm.ElasticNet,
        }.get(family.name)
        if cls is None:
            raise ValueError(f"no sklearn counterpart for {family.name}")
        valid = cls().get_params()
        est = cls(**{k: v for k, v in tpu_model.static.items()
                     if k in valid})
        for k, v in attrs.items():
            setattr(est, k, v)
        return est

    to_sklearn = toSKLearn

    # -- DataFrame helper (reference: toPandas) --------------------------
    def toPandas(self, df):
        """Convert a pandas DataFrame whose cells may hold jax/numpy arrays
        or CSRMatrix rows into a flat pandas DataFrame of numpy arrays —
        the reference's Vector-column -> numpy behavior without a collect().
        """
        import pandas as pd
        from spark_sklearn_tpu.sparse.csr import CSRMatrix

        def _cell(v):
            if isinstance(v, CSRMatrix):
                return np.asarray(v.to_scipy().toarray()).ravel()
            if hasattr(v, "__array__") and not np.isscalar(v):
                return np.asarray(v)
            return v

        return pd.DataFrame(
            {c: [_cell(v) for v in df[c]] for c in df.columns})
