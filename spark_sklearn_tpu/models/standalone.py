"""Standalone native estimators for the non-linear families.

`models/estimators.py` covers the linear natives; these wrap the SVC / MLP
/ tree families with the sklearn estimator contract so the framework is
usable with no sklearn estimator objects at all.  Each `.fit` runs the
family's compiled program with a single all-ones weight vector (one
"task"), mirroring how the search fits the refitted best estimator.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin


class SVC(ClassifierMixin, BaseEstimator):
    """TPU-native kernel SVM (FISTA dual ascent — models/svm.py)."""

    def __init__(self, C=1.0, kernel="rbf", gamma="scale", degree=3,
                 coef0=0.0, max_iter=-1, tol=1e-3, class_weight=None,
                 random_state=None):
        self.C = C
        self.kernel = kernel
        self.gamma = gamma
        self.degree = degree
        self.coef0 = coef0
        self.max_iter = max_iter
        self.tol = tol
        self.class_weight = class_weight
        self.random_state = random_state

    def fit(self, X, y):
        from spark_sklearn_tpu.models.svm import SVCFamily, _resolve_gamma

        X = np.asarray(X, np.float32)
        data, meta = SVCFamily.prepare_data(X, y)
        self._meta = meta
        self._static = dict(self.get_params(deep=False))
        self._X_train = data["X"]
        self._y = data["y"]
        self.classes_ = meta["classes"]
        self.n_features_in_ = meta["n_features"]
        self._gamma_val = _resolve_gamma(
            self._static.get("gamma", "scale"), meta)
        # the fit IS the dual solve; signed alphas + KKT intercepts are
        # the model (the representer form d(x) = sum_i alpha_i y_i
        # K(x_i, x) + b serves training AND new data with one kernel
        # matmul)
        self._alphas, self._intercepts = self._solve_alphas()
        return self

    def _pair_decisions(self, X):
        import jax.numpy as jnp
        from spark_sklearn_tpu.models.svm import _kernel
        K = _kernel(jnp.asarray(np.asarray(X, np.float32)),
                    jnp.asarray(self._X_train), self._static.get(
                        "kernel", "rbf"), self._gamma_val,
                    float(self._static.get("degree", 3)),
                    float(self._static.get("coef0", 0.0)))
        return np.asarray(K @ self._alphas.T) + \
            self._intercepts[None, :]                # (n_new, P)

    def _solve_alphas(self):
        """One dual solve via the family's shared FISTA kernel
        (models/svm.py::fista_dual_ascent — numerics live in one place)."""
        from spark_sklearn_tpu.models.svm import (
            _kernel, _power_step, fista_dual_ascent)
        X = jnp.asarray(self._X_train)
        y = jnp.asarray(self._y)
        n = X.shape[0]
        k = self._meta["n_classes"]
        pairs = jnp.asarray(self._meta["pairs"])
        K = _kernel(X, X, self._static.get("kernel", "rbf"),
                    self._gamma_val, float(self._static.get("degree", 3)),
                    float(self._static.get("coef0", 0.0)))
        ypos = (y[None, :] == pairs[:, 0][:, None])
        yneg = (y[None, :] == pairs[:, 1][:, None])
        yb = ypos.astype(jnp.float32) - yneg.astype(jnp.float32)
        if k == 2:
            yb = -yb
        box = (ypos | yneg).astype(jnp.float32)
        C = float(self._static.get("C", 1.0))
        max_iter = int(self._static.get("max_iter", -1))
        if max_iter in (-1, 0):
            max_iter = 300
        from spark_sklearn_tpu.models.base import class_weight_multiplier
        cw = class_weight_multiplier(
            jnp.ones((n,), jnp.float32), jnp.asarray(self._y),
            self._meta, self._static.get("class_weight"))
        bound = C * box if cw is None else C * box * cw[None, :]
        from spark_sklearn_tpu.models.svm import _tol_or_default
        A, b, _ = fista_dual_ascent(
            K, yb, bound, _power_step(K, n, jnp.float32), max_iter,
            tol=_tol_or_default(self._static))
        return np.asarray(A * yb), np.asarray(b)      # signed alphas + b

    def decision_function(self, X):
        from spark_sklearn_tpu.models.svm import SVCFamily
        dec = jnp.asarray(self._pair_decisions(X))
        if self._meta["n_classes"] == 2:
            return np.asarray(dec[:, 0])
        return np.asarray(SVCFamily._votes(dec, self._meta))

    def predict(self, X):
        from spark_sklearn_tpu.models.svm import SVCFamily
        dec = jnp.asarray(self._pair_decisions(X))
        idx = np.asarray(SVCFamily.predict(
            {"pair_dec": dec}, self._static, None, self._meta))
        return self.classes_[idx]


from spark_sklearn_tpu.models.estimators import _TpuEstimatorBase


class MLPClassifier(ClassifierMixin, _TpuEstimatorBase):
    from spark_sklearn_tpu.models.mlp import MLPClassifierFamily as _family

    def __init__(self, hidden_layer_sizes=(100,), activation="relu",
                 solver="adam", alpha=1e-4, batch_size="auto",
                 learning_rate_init=1e-3, max_iter=200, random_state=None,
                 momentum=0.9, beta_1=0.9, beta_2=0.999, epsilon=1e-8):
        self.hidden_layer_sizes = hidden_layer_sizes
        self.activation = activation
        self.solver = solver
        self.alpha = alpha
        self.batch_size = batch_size
        self.learning_rate_init = learning_rate_init
        self.max_iter = max_iter
        self.random_state = random_state
        self.momentum = momentum
        self.beta_1 = beta_1
        self.beta_2 = beta_2
        self.epsilon = epsilon

    def fit(self, X, y):
        return self._fit_family(X, y)

    def predict(self, X):
        return self.classes_[np.asarray(self._predict_family(X))]

    def predict_proba(self, X):
        return np.asarray(self._family.predict_proba(
            self._model, self._static,
            jnp.asarray(np.asarray(X, np.float32)), self._meta))


class MLPRegressor(RegressorMixin, _TpuEstimatorBase):
    from spark_sklearn_tpu.models.mlp import MLPRegressorFamily as _family

    def __init__(self, hidden_layer_sizes=(100,), activation="relu",
                 solver="adam", alpha=1e-4, batch_size="auto",
                 learning_rate_init=1e-3, max_iter=200, random_state=None):
        self.hidden_layer_sizes = hidden_layer_sizes
        self.activation = activation
        self.solver = solver
        self.alpha = alpha
        self.batch_size = batch_size
        self.learning_rate_init = learning_rate_init
        self.max_iter = max_iter
        self.random_state = random_state

    def fit(self, X, y):
        return self._fit_family(X, y)

    def predict(self, X):
        return np.asarray(self._predict_family(X))
