"""Native TPU estimator classes with the sklearn estimator contract.

These are standalone replacements for the sklearn estimators the compiled
families cover: same constructor params and fitted attributes, but `.fit`
runs the family's jitted JAX program on the TPU.  They subclass sklearn's
BaseEstimator so `clone()`/`get_params`/`set_params` (the contract the
reference relies on everywhere — reference: grid_search.py uses
sklearn.base.clone) work unchanged, and they dispatch to the Tier-A compiled
search path automatically.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np
import jax.numpy as jnp

from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin

from spark_sklearn_tpu.models.linear import (
    ElasticNetFamily,
    LinearRegressionFamily,
    LogisticRegressionFamily,
    RidgeFamily,
)


class _TpuEstimatorBase(BaseEstimator):
    """Single-fit plumbing shared by every native estimator (linear here,
    MLP in standalone.py): prepare -> params from the sklearn contract ->
    one jitted family fit with all-ones weights -> fitted attrs."""

    _family = None

    def _fit_family(self, X, y, sample_weight=None):
        import jax

        family = self._family
        X = np.asarray(X)
        data, meta = family.prepare_data(X, y)
        n = X.shape[0]
        w = (np.ones(n, dtype=np.float32) if sample_weight is None
             else np.asarray(sample_weight, dtype=np.float32))
        params = family.extract_params(self)
        if hasattr(family, "observe_candidates"):
            family.observe_candidates([], params, meta)
        model = jax.jit(
            lambda d, wv: family.fit({}, params, d, wv, meta))(
            {k: jnp.asarray(v) for k, v in data.items()}, jnp.asarray(w))
        self._model = model
        self._meta = meta
        self._static = params
        for k, v in family.sklearn_attrs(model, params, meta).items():
            setattr(self, k, v)
        return self

    def _predict_family(self, X):
        X = jnp.asarray(np.asarray(X), jnp.float32)
        return self._family.predict(self._model, self._static, X, self._meta)


class LogisticRegression(ClassifierMixin, _TpuEstimatorBase):
    """TPU-native logistic regression (lbfgs, L2).  Mirrors
    sklearn.linear_model.LogisticRegression's core surface."""

    _family = LogisticRegressionFamily

    def __init__(self, penalty="l2", C=1.0, tol=1e-4, fit_intercept=True,
                 max_iter=100, random_state=None):
        self.penalty = penalty
        self.C = C
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.max_iter = max_iter
        self.random_state = random_state

    def fit(self, X, y, sample_weight=None):
        return self._fit_family(X, y, sample_weight)

    def predict(self, X):
        idx = np.asarray(self._predict_family(X))
        return self.classes_[idx]

    def decision_function(self, X):
        X = jnp.asarray(np.asarray(X), self._model["coef"].dtype)
        return np.asarray(self._family.decision(
            self._model, self._static, X, self._meta))

    def predict_proba(self, X):
        X = jnp.asarray(np.asarray(X), self._model["coef"].dtype)
        return np.asarray(self._family.predict_proba(
            self._model, self._static, X, self._meta))

    def predict_log_proba(self, X):
        return np.log(self.predict_proba(X))


class _TpuRegressorBase(RegressorMixin, _TpuEstimatorBase):
    def fit(self, X, y, sample_weight=None):
        return self._fit_family(X, y, sample_weight)

    def predict(self, X):
        return np.asarray(self._predict_family(X))


class Ridge(_TpuRegressorBase):
    _family = RidgeFamily

    def __init__(self, alpha=1.0, fit_intercept=True, tol=1e-4,
                 random_state=None):
        self.alpha = alpha
        self.fit_intercept = fit_intercept
        self.tol = tol
        self.random_state = random_state


class LinearRegression(_TpuRegressorBase):
    _family = LinearRegressionFamily

    def __init__(self, fit_intercept=True):
        self.fit_intercept = fit_intercept


class ElasticNet(_TpuRegressorBase):
    _family = ElasticNetFamily

    def __init__(self, alpha=1.0, l1_ratio=0.5, fit_intercept=True,
                 max_iter=1000, tol=1e-4, random_state=None):
        self.alpha = alpha
        self.l1_ratio = l1_ratio
        self.fit_intercept = fit_intercept
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state


class Lasso(ElasticNet):
    _family = ElasticNetFamily

    def __init__(self, alpha=1.0, fit_intercept=True, max_iter=1000,
                 tol=1e-4, random_state=None):
        super().__init__(alpha=alpha, l1_ratio=1.0,
                         fit_intercept=fit_intercept, max_iter=max_iter,
                         tol=tol, random_state=random_state)
