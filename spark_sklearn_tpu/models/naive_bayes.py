"""Naive Bayes families — closed-form fits, the best case for the mesh.

Reference counterpart: sklearn's GaussianNB / MultinomialNB /
BernoulliNB running whole inside Spark tasks (reference: grid_search.py
-> sklearn _fit_and_score).  Every fit is a handful of weighted
reductions over X — no iterations at all — so a (candidate x fold) grid
compiles to a few wide matmuls with the fold masks as weights, and
parity with sklearn is at float tolerance, not accuracy level:

  - GaussianNB: per-class weighted mean/variance + the var_smoothing
    epsilon (sklearn _gaussian: epsilon_ = var_smoothing * max feature
    variance of the UNWEIGHTED train fold);
  - MultinomialNB: smoothed per-class feature count ratios
    (feature_log_prob = log(N_cf + a) - log(N_c + a*d));
  - BernoulliNB: binarized count ratios with the two-sided smoothing
    (p = (N_cf + a) / (N_c + 2a)) and the log(1-p) offset term;
  - ComplementNB: each class weighted by every OTHER class's counts
    (comp_count = feature_all + a - N_cf, negated log ratios, optional
    weight normalisation), prior only in the single-class case;
  - CategoricalNB: per-(feature, category) counts padded to the global
    max category count — one one-hot einsum to count, one to score
    (sklearn's ragged per-feature lists rebuilt on conversion).

The per-class sums are one (k, n) @ (n, d) matmul per task; XLA batches
tasks on the vmap axis.  sample_weight and class priors follow sklearn's
exact formulas (weighted counts everywhere except GaussianNB's epsilon).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, encode_labels, register_family

_EPS = 1e-10


def _prep_classifier_data(X, y, dtype, x_override=None):
    """Shared prepare_data body: encoded labels + one-hot + meta.
    `x_override` supplies a pre-built device array for data["X"]
    (CategoricalNB's int codes) so no dead float copy of X is made."""
    classes, y_enc = encode_labels(y)
    k = len(classes)
    data = {"X": (np.ascontiguousarray(X, dtype=dtype)
                  if x_override is None else x_override),
            "y": y_enc,
            "y1h": np.eye(k, dtype=dtype)[y_enc]}
    meta = {"n_classes": int(k), "classes": classes,
            "n_features": int(X.shape[1])}
    return data, meta


def _prep_classifier_sparse(X, y, dtype):
    """Sparse twin of `_prep_classifier_data`: X is scipy CSR and stays
    a `SparseOperand` — labels/one-hot build exactly as on the dense
    path, X itself is never densified."""
    from spark_sklearn_tpu.sparse.csr import SparseOperand
    classes, y_enc = encode_labels(y)
    k = len(classes)
    op = SparseOperand.from_csr(X, dtype=dtype)
    data = {"X": op,
            "y": y_enc,
            "y1h": np.eye(k, dtype=dtype)[y_enc]}
    # the operand's signature tuple (truthy, hashable): flows through
    # freeze(meta) into ProgramStore keys and fusion keys, so a sparse
    # program can never alias a dense one with the same dense shape
    meta = {"n_classes": int(k), "classes": classes,
            "n_features": int(X.shape[1]), "sparse": op.signature()}
    return data, meta


def _class_sums(y1h, w, X=None):
    """Weighted per-class row sums: counts (k,), the (n, k) weighted
    one-hot used to build them, and, with X, per-class weighted feature
    sums (k, d) as ONE matmul."""
    wy = y1h * w[:, None]                       # (n, k)
    counts = jnp.sum(wy, axis=0)                # (k,)
    if X is None:
        return counts, wy, None
    return counts, wy, wy.T @ X                 # (k, d)


def _log_prior(counts, static, k, dtype):
    """sklearn _BaseDiscreteNB._update_class_log_prior."""
    class_prior = static.get("class_prior")
    if class_prior is not None:
        return jnp.log(jnp.asarray(class_prior, dtype))
    if static.get("fit_prior", True):
        return jnp.log(counts) - jnp.log(jnp.sum(counts))
    return jnp.full((k,), -np.log(k), dtype)


class GaussianNBFamily(Family):
    name = "gaussian_nb"
    is_classifier = True
    dynamic_params = {"var_smoothing": np.float32}
    # stays f32 deliberately: sklearn's GaussianNB preserves a float32
    # X end to end (f32 jll, f32 probas, log_loss clipped at f32 eps),
    # so the f32 engine mode IS the parity mode — an x64 override was
    # tried and made neg_log_loss diverge (f64 probas clip at 2.2e-16
    # where sklearn's f32 probas clip at 1.19e-7)

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        """Host-side, once per search: sklearn's priors validation
        (GaussianNB._partial_fit) — a bad priors array must raise
        sklearn's clear messages, not an XLA broadcast error
        mid-trace."""
        k = meta.get("n_classes")
        seen = {id(None): None}
        for params in [base_params] + list(candidates):
            priors = params.get("priors")
            if priors is None or id(priors) in seen:
                continue
            seen[id(priors)] = priors
            p = np.asarray(priors, np.float64)
            if k is not None and len(p) != k:
                raise ValueError(
                    "Number of priors must match number of classes.")
            if not np.isclose(p.sum(), 1.0):
                raise ValueError("The sum of the priors should be 1.")
            if (p < 0).any():
                raise ValueError("Priors must be non-negative.")

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        return _prep_classifier_data(X, y, dtype)

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        X, y1h = data["X"], data["y1h"]
        vs = jnp.asarray(dynamic.get(
            "var_smoothing", static.get("var_smoothing", 1e-9)), X.dtype)
        priors = static.get("priors")
        # true two-pass variance (sklearn's _update_mean_variance is
        # np.average((X - mu)^2, weights=sw)): residuals are taken about
        # each sample's OWN class mean via a label gather, because ANY
        # one-pass E[x^2]-E[x]^2 form — even shifted by the grand mean —
        # cancels catastrophically in f32 once a class offset dwarfs the
        # within-class spread (measured: var off 8x RELATIVE on digits'
        # near-constant features, which log(var) turns into 0.007 score
        # drift)
        counts, wy, sums = _class_sums(y1h, train_w, X)      # (k,), (k, d)
        cnt = jnp.maximum(counts, _EPS)[:, None]
        theta = sums / cnt                                   # (k, d)
        r = X - theta[data["y"]]                             # (n, d)
        var = (wy.T @ (r * r)) / cnt
        # epsilon_ follows the UNWEIGHTED variance of the train fold
        # (sklearn _gaussian.py: np.var(X, axis=0).max() on the X passed
        # to fit, before sample weights), two-pass about the fold mean.
        # Known deviation: rows whose sample_weight is exactly 0 are
        # indistinguishable from out-of-fold rows here, so they drop out
        # of this variance where sklearn keeps them — an
        # O(var_smoothing) effect.
        ind = (train_w > 0).astype(X.dtype)
        n_ind = jnp.maximum(jnp.sum(ind), 1.0)
        mu0 = (ind @ X) / n_ind                              # (d,)
        r0 = X - mu0[None, :]
        fold_var = (ind @ (r0 * r0)) / n_ind
        eps = vs * jnp.max(fold_var)
        var = var + eps
        if priors is not None:
            prior = jnp.asarray(priors, X.dtype)
        else:
            prior = counts / jnp.maximum(jnp.sum(counts), _EPS)
        return {"theta": theta, "var": var,
                "log_prior": jnp.log(jnp.maximum(prior, 0.0))}

    @classmethod
    def _jll(cls, model, X):
        theta, var = model["theta"], model["var"]            # (k, d)
        ll = -0.5 * jnp.sum(jnp.log(2.0 * np.pi * var), axis=1)  # (k,)
        # sklearn's DIRECT form (_gaussian.py: -0.5*sum((X-theta)^2/var)),
        # not the matmul expansion: with var floored at epsilon the
        # per-feature terms reach ~1/var_smoothing, where the expanded
        # x^2/var - 2x*theta/var + theta^2/var cross terms round
        # differently from the oracle by O(10) in the jll (measured
        # 0.017 proba drift on digits).  XLA fuses this broadcast-reduce
        # without materialising the (n, k, d) intermediate.
        q = 0.5 * jnp.sum(
            (X[:, None, :] - theta[None, :, :]) ** 2 / var[None, :, :],
            axis=2)                                          # (n, k)
        return model["log_prior"][None, :] + ll[None, :] - q

    @classmethod
    def predict(cls, model, static, X, meta):
        return jnp.argmax(cls._jll(model, X), axis=1).astype(jnp.int32)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        return jax.nn.softmax(cls._jll(model, X), axis=1)

    @classmethod
    def decision(cls, model, static, X, meta):
        jll = cls._jll(model, X)
        if meta["n_classes"] == 2:
            return jll[:, 1] - jll[:, 0]
        return jll

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"theta_": np.asarray(model["theta"]),
                "var_": np.asarray(model["var"]),
                "class_prior_": np.exp(np.asarray(model["log_prior"])),
                "classes_": meta["classes"],
                "n_features_in_": meta["n_features"]}


class MultinomialNBFamily(Family):
    name = "multinomial_nb"
    is_classifier = True
    dynamic_params = {"alpha": np.float32}
    # the fit is {counts, feature counts} -> closed form: the count sums
    # are one `wy.T @ X` (operator form, BCOO-legal) and additive over
    # row shards, so both out-of-core tiers apply
    supports_sparse = True
    supports_stream = True

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        """Host-side class_prior length check (sklearn
        _update_class_log_prior) — same rationale as GaussianNB's priors
        validation: sklearn's clear error, not an XLA broadcast error."""
        k = meta.get("n_classes")
        if k is None:
            return
        for params in [base_params] + list(candidates):
            cp = params.get("class_prior")
            if cp is not None and len(np.asarray(cp)) != k:
                raise ValueError(
                    "Number of priors must match number of classes.")

    #: sklearn's check_non_negative names the concrete class
    _sklearn_display = "MultinomialNB"

    @staticmethod
    def _check_finite(Xa):
        """sklearn's check_array contract: NaN (which would pass a
        min()<0 test — NaN comparisons are False) and infinity both
        raise BEFORE any launch, with sklearn's OWN per-case message
        (delegated, so the wording can never drift from the installed
        sklearn), instead of becoming masked failed fits."""
        if not np.issubdtype(Xa.dtype, np.floating):
            return
        from sklearn.utils import assert_all_finite
        assert_all_finite(Xa, input_name="X")

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        Xa = np.asarray(X)
        cls._check_finite(Xa)
        if np.min(Xa) < 0:
            # sklearn's exact complaint; surfaces host-side before any
            # launch (the engine's designed fallback runs sklearn, which
            # raises the same for every candidate)
            raise ValueError(
                f"Negative values in data passed to "
                f"{cls._sklearn_display} (input X)")
        return _prep_classifier_data(X, y, dtype)

    @classmethod
    def prepare_data_sparse(cls, X, y, dtype=np.float32):
        # the sign/finiteness contract runs on the stored values only —
        # implicit zeros are non-negative and finite by construction
        Xd = np.asarray(X.data)
        cls._check_finite(Xd)
        if Xd.size and np.min(Xd) < 0:
            raise ValueError(
                f"Negative values in data passed to "
                f"{cls._sklearn_display} (input X)")
        return _prep_classifier_sparse(X, y, dtype)

    @classmethod
    def _alpha(cls, dynamic, static, dtype):
        a = jnp.asarray(dynamic.get("alpha", static.get("alpha", 1.0)),
                        dtype)
        if not static.get("force_alpha", True):
            a = jnp.maximum(a, 1e-10)   # sklearn's _check_alpha clamp
        return a

    @classmethod
    def _fit_X(cls, static, X):
        """The matrix the count sums run over (Bernoulli binarizes)."""
        return X

    @classmethod
    def _model_from_sums(cls, dynamic, static, counts, fc, meta, dtype):
        """Closed-form model from the sufficient statistics
        (class counts (k,), per-class feature sums (k, d)) — the shared
        tail of `fit` and `stream_fit_finalize`, so the streamed fit is
        the in-core fit by construction."""
        k = meta["n_classes"]
        a = cls._alpha(dynamic, static, dtype)
        smoothed = fc + a
        flp = jnp.log(smoothed) \
            - jnp.log(jnp.sum(smoothed, axis=1))[:, None]
        return {"feature_log_prob": flp,
                "class_log_prior": _log_prior(counts, static, k, dtype),
                "class_count": counts}

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        X = cls._fit_X(static, data["X"])
        counts, _wy, fc = _class_sums(data["y1h"], train_w, X)
        return cls._model_from_sums(dynamic, static, counts, fc, meta,
                                    X.dtype)

    # --- streaming-fold protocol -----------------------------------------
    @classmethod
    def stream_fit_partial(cls, static, data, fit_w, meta):
        X = cls._fit_X(static, data["X"])
        y1h = data["y1h"]

        def one_fold(w):
            counts, _wy, fc = _class_sums(y1h, w, X)
            return {"count": counts, "fc": fc}

        return jax.vmap(one_fold)(fit_w)        # leaves: (F, ...) sums

    @classmethod
    def stream_fit_finalize(cls, dynamic, static, stats, meta):
        return cls._model_from_sums(dynamic, static, stats["count"],
                                    stats["fc"], meta,
                                    stats["fc"].dtype)

    @classmethod
    def _jll(cls, model, X):
        return X @ model["feature_log_prob"].T \
            + model["class_log_prior"][None, :]

    predict = classmethod(GaussianNBFamily.predict.__func__)
    predict_proba = classmethod(GaussianNBFamily.predict_proba.__func__)
    decision = classmethod(GaussianNBFamily.decision.__func__)

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"feature_log_prob_": np.asarray(
                    model["feature_log_prob"]),
                "class_log_prior_": np.asarray(model["class_log_prior"]),
                "class_count_": np.asarray(model["class_count"]),
                "classes_": meta["classes"],
                "n_features_in_": meta["n_features"]}


class ComplementNBFamily(MultinomialNBFamily):
    """Complement NB (Rennie et al. 2003, sklearn ComplementNB): each
    class's weights come from the counts of every OTHER class —
    comp_count = feature_all + alpha - feature_count — so imbalanced
    text corpora don't drown minority classes.  The class prior only
    enters the degenerate single-class case, exactly like sklearn."""

    name = "complement_nb"
    _sklearn_display = "ComplementNB"

    @classmethod
    def _model_from_sums(cls, dynamic, static, counts, fc, meta, dtype):
        k = meta["n_classes"]
        a = cls._alpha(dynamic, static, dtype)
        comp = jnp.sum(fc, axis=0)[None, :] + a - fc          # (k, d)
        logged = jnp.log(comp / jnp.sum(comp, axis=1, keepdims=True))
        if static.get("norm", False):
            flp = logged / jnp.sum(logged, axis=1, keepdims=True)
        else:
            flp = -logged
        return {"feature_log_prob": flp,
                "class_log_prior": _log_prior(counts, static, k, dtype),
                "class_count": counts}

    @classmethod
    def _jll(cls, model, X):
        jll = X @ model["feature_log_prob"].T
        # sklearn adds the prior only in the single-class degenerate case
        if model["class_log_prior"].shape[0] == 1:
            jll = jll + model["class_log_prior"][None, :]
        return jll


class BernoulliNBFamily(MultinomialNBFamily):
    name = "bernoulli_nb"

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        # negative X is fine here (binarize thresholds it), but the
        # finiteness contract still applies: NaN > threshold is False,
        # so without the guard a NaN X would silently binarize to 0
        # where sklearn raises
        cls._check_finite(np.asarray(X))
        return _prep_classifier_data(X, y, dtype)

    @classmethod
    def prepare_data_sparse(cls, X, y, dtype=np.float32):
        cls._check_finite(np.asarray(X.data))
        return _prep_classifier_sparse(X, y, dtype)

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        super().observe_candidates(candidates, base_params, meta)
        if not meta.get("sparse"):
            return
        # binarize < 0 turns every implicit zero into a 1 — a DENSE
        # matrix in BCOO clothing; refuse host-side rather than emit a
        # silently-wrong sparse program
        b0 = base_params.get("binarize", 0.0)
        for params in [base_params] + list(candidates):
            b = params.get("binarize", b0)
            if b is not None and float(b) < 0:
                raise ValueError(
                    "binarize < 0 densifies a sparse X (implicit zeros "
                    "binarize to 1); use data_mode='device'")

    @classmethod
    def _binarized(cls, static, X):
        b = static.get("binarize", 0.0)
        if b is None:
            return X
        from jax.experimental import sparse as jsparse
        if isinstance(X, jsparse.BCOO):
            # threshold the stored values in place; implicit zeros stay
            # zero (b >= 0 is enforced host-side on the sparse path)
            return jsparse.BCOO(
                ((X.data > b).astype(X.data.dtype), X.indices),
                shape=X.shape, indices_sorted=X.indices_sorted,
                unique_indices=X.unique_indices)
        return (X > b).astype(X.dtype)

    @classmethod
    def _fit_X(cls, static, X):
        return cls._binarized(static, X)

    @classmethod
    def _model_from_sums(cls, dynamic, static, counts, fc, meta, dtype):
        k = meta["n_classes"]
        a = cls._alpha(dynamic, static, dtype)
        # two-sided smoothing: p_cf = (N_cf + a) / (N_c + 2a)
        log_p = jnp.log(fc + a) - jnp.log(counts + 2.0 * a)[:, None]
        log_1mp = jnp.log(counts[:, None] - fc + a) \
            - jnp.log(counts + 2.0 * a)[:, None]
        return {"feature_log_prob": log_p, "log_neg_prob": log_1mp,
                "class_log_prior": _log_prior(counts, static, k, dtype),
                "class_count": counts}

    @classmethod
    def _jll(cls, model, X_raw):
        # caller passes raw X; the threshold lives in static, which _jll
        # doesn't receive — so the view entry points re-binarize below
        raise NotImplementedError

    @classmethod
    def _jll_static(cls, model, static, X):
        Xb = cls._binarized(static, X)
        flp, lnp = model["feature_log_prob"], model["log_neg_prob"]
        return Xb @ (flp - lnp).T \
            + jnp.sum(lnp, axis=1)[None, :] \
            + model["class_log_prior"][None, :]

    @classmethod
    def predict(cls, model, static, X, meta):
        return jnp.argmax(cls._jll_static(model, static, X),
                          axis=1).astype(jnp.int32)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        return jax.nn.softmax(cls._jll_static(model, static, X), axis=1)

    @classmethod
    def decision(cls, model, static, X, meta):
        jll = cls._jll_static(model, static, X)
        if meta["n_classes"] == 2:
            return jll[:, 1] - jll[:, 0]
        return jll


class CategoricalNBFamily(MultinomialNBFamily):
    """Categorical NB: per-(feature, category) counts.  sklearn keeps a
    ragged list of (k, n_categories_i) arrays; the compiled form pads to
    the global max category count — counts are ONE
    einsum('nk,ndc->kdc') over the one-hot codes, and the jll is ONE
    einsum('ndc,kdc->nk') contraction per task.

    Documented deviation: n_categories_ is resolved from the FULL X of
    the search (static shapes), where sklearn's per-fit resolution uses
    only the train fold — in CV that makes sklearn RAISE at score time
    when a test fold holds a category its train fold never saw; the
    compiled path behaves as if `min_categories` covered the full data,
    which is sklearn's own documented fix for that crash."""

    name = "categorical_nb"
    _sklearn_display = "CategoricalNB"
    # int codes + one-hot einsums: neither the BCOO operator forms nor
    # the additive-sums streaming protocol apply — undo the inherited
    # Multinomial capabilities
    supports_sparse = False
    supports_stream = False
    #: consumes int codes + search-resolved n_categories meta, which the
    #: keyed fleet's generic build_fit_data cannot synthesise (same
    #: opt-out as the binned tree families) — keyed CategoricalNB runs
    #: per-key sklearn on the host instead of silently mis-smoothing
    keyed_compatible = False

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        Xa = np.asarray(X)
        if np.issubdtype(Xa.dtype, np.floating) and \
                not np.isfinite(Xa).all():
            # NaN passes a min()<0 test (NaN comparisons are False) and
            # astype(int32) would turn it into garbage codes
            raise ValueError("Input X contains NaN.")
        if np.min(Xa) < 0:
            raise ValueError(
                "Negative values in data passed to CategoricalNB "
                "(input X)")
        codes = np.ascontiguousarray(Xa, dtype=np.int32)
        data, meta = _prep_classifier_data(codes, y, dtype,
                                           x_override=codes)
        meta["n_categories"] = (codes.max(axis=0) + 1).astype(np.int64)
        return data, meta

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        """Resolve min_categories into the padded category counts
        (sklearn _validate_n_categories, host-side)."""
        super().observe_candidates(candidates, base_params, meta)
        mc = base_params.get("min_categories")
        if any(c.get("min_categories", mc) is not mc for c in candidates):
            raise ValueError(
                "min_categories changes the compiled shapes; grid it "
                "with backend='host'")
        if mc is not None and "n_categories" in meta:
            mc_arr = np.asarray(mc)
            if not np.issubdtype(mc_arr.dtype, np.signedinteger):
                raise ValueError(
                    "'min_categories' should have integral type. Got "
                    f"{mc_arr.dtype} instead.")
            d = len(meta["n_categories"])
            # shape check BEFORE np.maximum: a (2,) array must get
            # sklearn's message, not a raw broadcast error, and a
            # broadcastable-but-wrong (1,) must not slip through
            if mc_arr.ndim > 0 and mc_arr.shape != (d,):
                raise ValueError(
                    f"'min_categories' should have shape ({d},) when "
                    f"an array-like is provided. Got {mc_arr.shape} "
                    f"instead.")
            meta["n_categories"] = np.maximum(
                meta["n_categories"], mc_arr).astype(np.int64)

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        codes, y1h = data["X"], data["y1h"]
        k = meta["n_classes"]
        ncat = jnp.asarray(meta["n_categories"])             # (d,)
        C = int(np.max(meta["n_categories"]))
        a = cls._alpha(dynamic, static, y1h.dtype)
        wy = y1h * train_w[:, None]                          # (n, k)
        counts = jnp.sum(wy, axis=0)                         # (k,)
        oh = jax.nn.one_hot(codes, C, dtype=y1h.dtype)       # (n, d, C)
        cat = jnp.einsum("nk,ndc->kdc", wy, oh)              # (k, d, C)
        # per-feature denominator: total + alpha * n_categories_i
        # (padded columns beyond a feature's category count hold zero
        # counts and are never gathered — codes stay < n_categories_i)
        denom = jnp.sum(cat, axis=2) + a * ncat[None, :].astype(y1h.dtype)
        flp = jnp.log(cat + a) - jnp.log(denom)[:, :, None]
        return {"feature_log_prob": flp,                     # (k, d, C)
                "class_log_prior": _log_prior(counts, static, k,
                                              y1h.dtype),
                "class_count": counts}

    @classmethod
    def _jll(cls, model, X):
        flp = model["feature_log_prob"]                      # (k, d, C)
        oh = jax.nn.one_hot(X.astype(jnp.int32), flp.shape[2],
                            dtype=flp.dtype)                 # (n, d, C)
        return jnp.einsum("ndc,kdc->nk", oh, flp) \
            + model["class_log_prior"][None, :]

    @classmethod
    def check_predict_X(cls, X, meta):
        """Host-side predict-input guard (TpuModel calls this): sklearn
        raises IndexError for a category the model never allocated —
        one_hot would silently zero it instead."""
        ncat = np.asarray(meta["n_categories"])
        codes = np.asarray(X)
        bad = codes >= ncat[None, :]
        if bad.any():
            i, j = np.argwhere(bad)[0]
            raise IndexError(
                f"index {int(codes[i, j])} is out of bounds for feature "
                f"{int(j)} with {int(ncat[j])} categories")

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        flp = np.asarray(model["feature_log_prob"])
        ncat = np.asarray(meta["n_categories"])
        return {"feature_log_prob_": [flp[:, i, :ncat[i]]
                                      for i in range(flp.shape[1])],
                "class_log_prior_": np.asarray(model["class_log_prior"]),
                "class_count_": np.asarray(model["class_count"]),
                "n_categories_": ncat,
                "classes_": meta["classes"],
                "n_features_in_": meta["n_features"]}


register_family(
    CategoricalNBFamily,
    "sklearn.naive_bayes.CategoricalNB",
)
register_family(
    GaussianNBFamily,
    "sklearn.naive_bayes.GaussianNB",
)
register_family(
    MultinomialNBFamily,
    "sklearn.naive_bayes.MultinomialNB",
)
register_family(
    ComplementNBFamily,
    "sklearn.naive_bayes.ComplementNB",
)
register_family(
    BernoulliNBFamily,
    "sklearn.naive_bayes.BernoulliNB",
)
