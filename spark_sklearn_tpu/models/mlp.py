"""MLP classifier/regressor families — jit-compiled minibatch training.

Reference counterpart: sklearn's MLPClassifier running unchanged inside a
Spark task (BASELINE config #5 exercises Pipeline(StandardScaler + MLP)).
Here the whole training loop is one XLA program: `lax.scan` over epochs, an
inner `lax.scan` over minibatches, adam/sgd updates inline — and `vmap`
lifts it over hyperparameter candidates so the MXU sees (candidates x batch)
matmuls instead of Python-loop epochs.

Numeric conventions follow sklearn's MLP (_multilayer_perceptron.py):
Glorot-uniform init, softmax/logistic output, mean cross-entropy (or 0.5*MSE
for regression) plus alpha*0.5*||W||^2/batch_n regularisation, default
batch_size=min(200, n), constant learning rate.  Early stopping and
adaptive/invscaling schedules are not compiled (they raise -> the search
falls back to the host path).  Training runs the full `max_iter` epochs —
inside one fused program that is cheaper than dynamic stopping would be.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, encode_labels, register_family

EPS = 1e-8


def _activation(name):
    return {
        "relu": jax.nn.relu,
        "tanh": jnp.tanh,
        "logistic": jax.nn.sigmoid,
        "identity": lambda x: x,
    }[name]


def _init_params(key, layer_sizes, dtype):
    """Glorot-uniform like sklearn's _init_coef."""
    params = []
    keys = jax.random.split(key, len(layer_sizes) - 1)
    for k, (fan_in, fan_out) in zip(keys, zip(layer_sizes[:-1],
                                              layer_sizes[1:])):
        bound = jnp.sqrt(6.0 / (fan_in + fan_out)).astype(dtype)
        kw, kb = jax.random.split(k)
        W = jax.random.uniform(kw, (fan_in, fan_out), dtype,
                               -bound, bound)
        b = jax.random.uniform(kb, (fan_out,), dtype, -bound, bound)
        params.append({"W": W, "b": b})
    return params


def _forward(params, X, act):
    h = X
    for layer in params[:-1]:
        h = act(h @ layer["W"] + layer["b"])
    return h @ params[-1]["W"] + params[-1]["b"]


def _check_supported(static):
    if static.get("early_stopping", False):
        raise ValueError("early_stopping is not compiled; use backend='host'")
    if static.get("learning_rate", "constant") != "constant":
        raise ValueError(
            "learning_rate schedules are not compiled; use backend='host'")
    solver = static.get("solver", "adam")
    if solver not in ("adam", "sgd"):
        raise ValueError(f"solver={solver!r} is not compiled")


class MLPClassifierFamily(Family):
    name = "mlp_classifier"
    is_classifier = True
    dynamic_params = {"alpha": np.float32,
                      "learning_rate_init": np.float32}

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        classes, y_enc = encode_labels(y)
        data = {
            "X": np.ascontiguousarray(X, dtype=dtype),
            "y": y_enc,
            "y1h": np.eye(len(classes), dtype=dtype)[y_enc],
        }
        meta = {"n_classes": int(len(classes)), "classes": classes,
                "n_features": int(X.shape[1])}
        return data, meta

    @classmethod
    def _out_dim(cls, meta):
        return meta["n_classes"]

    @classmethod
    def _loss_terms(cls, logits, data_slice, w):
        logp = jax.nn.log_softmax(logits, axis=1)
        per = -jnp.sum(data_slice["y1h"] * logp, axis=1)
        return jnp.sum(w * per)

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        _check_supported(static)
        X = data["X"]
        n, d = X.shape
        dtype = X.dtype
        out_dim = cls._out_dim(meta)
        hidden = static.get("hidden_layer_sizes", (100,))
        if isinstance(hidden, int):
            hidden = (hidden,)
        layer_sizes = (d, *[int(h) for h in hidden], out_dim)
        act = _activation(static.get("activation", "relu"))
        solver = static.get("solver", "adam")
        alpha = jnp.asarray(
            dynamic.get("alpha", static.get("alpha", 1e-4)), dtype)
        lr = jnp.asarray(
            dynamic.get("learning_rate_init",
                        static.get("learning_rate_init", 1e-3)), dtype)
        max_iter = int(static.get("max_iter", 200))
        batch_size = static.get("batch_size", "auto")
        if batch_size == "auto":
            batch_size = min(200, n)
        batch_size = int(min(batch_size, n))
        n_batches = (n + batch_size - 1) // batch_size
        n_pad = n_batches * batch_size
        seed = static.get("random_state")
        seed = 0 if seed is None else int(seed)
        momentum = float(static.get("momentum", 0.9))
        b1 = float(static.get("beta_1", 0.9))
        b2 = float(static.get("beta_2", 0.999))
        eps_adam = float(static.get("epsilon", 1e-8))

        key = jax.random.PRNGKey(seed)
        key, init_key = jax.random.split(key)
        params = _init_params(init_key, layer_sizes, dtype)

        # per-batch targets gathered by index; pad with index 0, weight 0
        y_all = {k: data[k] for k in ("y1h",) if k in data}
        if "y_target" in data:
            y_all["y_target"] = data["y_target"]

        def batch_loss(p, idx, w_idx, a):
            Xb = X[idx]
            slice_ = {k: v[idx] for k, v in y_all.items()}
            logits = _forward(p, Xb, act)
            # clamp at 1 so a minibatch with zero training-fold rows makes a
            # harmless small step instead of a 1/EPS-exploded penalty grad
            wsum = jnp.maximum(jnp.sum(w_idx), 1.0)
            data_loss = cls._loss_terms(logits, slice_, w_idx) / wsum
            l2 = sum(jnp.sum(layer["W"] ** 2) for layer in p)
            return data_loss + 0.5 * a * l2 / wsum

        grad_fn = jax.grad(batch_loss)

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        if solver == "adam":
            opt_state = {"m": zeros, "v": zeros,
                         "t": jnp.asarray(0.0, dtype)}

            def update(p, g, st):
                t = st["t"] + 1.0
                m = jax.tree_util.tree_map(
                    lambda m_, g_: b1 * m_ + (1 - b1) * g_, st["m"], g)
                v = jax.tree_util.tree_map(
                    lambda v_, g_: b2 * v_ + (1 - b2) * g_ * g_, st["v"], g)
                mhat = jax.tree_util.tree_map(
                    lambda m_: m_ / (1 - b1 ** t), m)
                vhat = jax.tree_util.tree_map(
                    lambda v_: v_ / (1 - b2 ** t), v)
                p_new = jax.tree_util.tree_map(
                    lambda p_, mh, vh: p_ - lr * mh /
                    (jnp.sqrt(vh) + eps_adam), p, mhat, vhat)
                return p_new, {"m": m, "v": v, "t": t}
        else:  # sgd with momentum
            opt_state = {"vel": zeros}

            def update(p, g, st):
                vel = jax.tree_util.tree_map(
                    lambda v_, g_: momentum * v_ - lr * g_, st["vel"], g)
                p_new = jax.tree_util.tree_map(
                    lambda p_, v_: p_ + v_, p, vel)
                return p_new, {"vel": vel}

        def epoch(carry, ek):
            p, st = carry
            # pad with index 0 at ZERO weight (a modulo wrap would silently
            # double-count wrapped samples at full weight)
            perm = jax.random.permutation(ek, n)
            idx_pad = jnp.concatenate(
                [perm, jnp.zeros((n_pad - n,), perm.dtype)])
            wmul = jnp.concatenate(
                [jnp.ones((n,), dtype), jnp.zeros((n_pad - n,), dtype)])
            batches = idx_pad.reshape(n_batches, batch_size)
            wmuls = wmul.reshape(n_batches, batch_size)

            def one_batch(c, inp):
                p_, st_ = c
                idx, wm = inp
                w_idx = train_w[idx] * wm
                g = grad_fn(p_, idx, w_idx, alpha)
                p_, st_ = update(p_, g, st_)
                return (p_, st_), None

            (p, st), _ = jax.lax.scan(one_batch, (p, st), (batches, wmuls))
            return (p, st), None

        epoch_keys = jax.random.split(key, max_iter)
        (params, _), _ = jax.lax.scan(epoch, (params, opt_state), epoch_keys)
        return {"layers": params}

    @classmethod
    def _logits(cls, model, static, X, meta):
        act = _activation(static.get("activation", "relu"))
        return _forward(model["layers"], X, act)

    @classmethod
    def decision(cls, model, static, X, meta):
        Z = cls._logits(model, static, X, meta)
        if meta.get("n_classes") == 2:
            # scorer contract: binary decision is a 1-D margin
            return Z[:, 1] - Z[:, 0]
        return Z

    @classmethod
    def predict(cls, model, static, X, meta):
        return jnp.argmax(cls._logits(model, static, X, meta),
                          axis=1).astype(jnp.int32)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        return jax.nn.softmax(cls._logits(model, static, X, meta), axis=1)

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        layers = model["layers"]
        return {
            "coefs_": [np.asarray(l["W"]) for l in layers],
            "intercepts_": [np.asarray(l["b"]) for l in layers],
            "classes_": meta.get("classes"),
            "n_features_in_": meta["n_features"],
            "n_layers_": len(layers) + 1,
        }


class MLPRegressorFamily(MLPClassifierFamily):
    name = "mlp_regressor"
    is_classifier = False

    @classmethod
    def build_fit_data(cls, Xg, yg, meta):
        yt = yg.astype(Xg.dtype)
        # the loss consumes "y_target" in (n, n_targets) layout; keyed
        # fleets carry a single y column -> (n, 1)
        return {"X": Xg, "y": yt, "y_target": yt[:, None]}

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        y = np.asarray(y, dtype=dtype)
        data = {
            "X": np.ascontiguousarray(X, dtype=dtype),
            "y": y,
            "y_target": y.reshape(len(y), -1),
        }
        meta = {"n_features": int(X.shape[1]),
                "n_targets": int(data["y_target"].shape[1])}
        return data, meta

    @classmethod
    def _out_dim(cls, meta):
        return meta["n_targets"]

    @classmethod
    def _loss_terms(cls, preds, data_slice, w):
        se = jnp.sum((preds - data_slice["y_target"]) ** 2, axis=1)
        return 0.5 * jnp.sum(w * se)

    @classmethod
    def predict(cls, model, static, X, meta):
        out = cls._logits(model, static, X, meta)
        return out[:, 0] if meta["n_targets"] == 1 else out

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        attrs = MLPClassifierFamily.sklearn_attrs.__func__(
            cls, model, static, meta)
        attrs.pop("classes_", None)
        return attrs


register_family(
    MLPClassifierFamily,
    "sklearn.neural_network._multilayer_perceptron.MLPClassifier",
    "sklearn.neural_network.MLPClassifier",
)
register_family(
    MLPRegressorFamily,
    "sklearn.neural_network._multilayer_perceptron.MLPRegressor",
    "sklearn.neural_network.MLPRegressor",
)
