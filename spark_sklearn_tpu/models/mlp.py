"""MLP classifier/regressor families — jit-compiled minibatch training.

Reference counterpart: sklearn's MLPClassifier running unchanged inside a
Spark task (BASELINE config #5 exercises Pipeline(StandardScaler + MLP)).
Here the whole training loop is one XLA program: `lax.scan` over epochs, an
inner `lax.scan` over minibatches, adam/sgd updates inline — and `vmap`
lifts it over hyperparameter candidates so the MXU sees (candidates x batch)
matmuls instead of Python-loop epochs.

Numeric conventions follow sklearn's MLP (_multilayer_perceptron.py):
Glorot-uniform init, softmax/logistic output, mean cross-entropy (or 0.5*MSE
for regression) plus alpha*0.5*||W||^2/batch_n regularisation, default
batch_size=min(200, n), and sklearn's stopping rules compiled into a
`lax.while_loop` over epochs: training-loss plateau (`tol` /
`n_iter_no_change`), validation-score early stopping with best-weight
restore (`early_stopping=True` holds out `validation_fraction` of the
train-fold rows via a PRNG-derived held-out mask — same semantics as
sklearn's train_test_split, not the same row indices), and the sgd
`invscaling` / `adaptive` learning-rate schedules.  Under `vmap` the
while_loop runs until every candidate lane has stopped.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, encode_labels, register_family

EPS = 1e-8


def _activation(name):
    return {
        "relu": jax.nn.relu,
        "tanh": jnp.tanh,
        "logistic": jax.nn.sigmoid,
        "identity": lambda x: x,
    }[name]


def _init_params(key, layer_sizes, dtype):
    """Glorot-uniform like sklearn's _init_coef."""
    params = []
    keys = jax.random.split(key, len(layer_sizes) - 1)
    for k, (fan_in, fan_out) in zip(keys, zip(layer_sizes[:-1],
                                              layer_sizes[1:])):
        bound = jnp.sqrt(6.0 / (fan_in + fan_out)).astype(dtype)
        kw, kb = jax.random.split(k)
        W = jax.random.uniform(kw, (fan_in, fan_out), dtype,
                               -bound, bound)
        b = jax.random.uniform(kb, (fan_out,), dtype, -bound, bound)
        params.append({"W": W, "b": b})
    return params


def _forward(params, X, act):
    h = X
    for layer in params[:-1]:
        h = act(h @ layer["W"] + layer["b"])
    return h @ params[-1]["W"] + params[-1]["b"]


def _check_supported(static):
    solver = static.get("solver", "adam")
    if solver not in ("adam", "sgd"):
        raise ValueError(f"solver={solver!r} is not compiled")
    if static.get("learning_rate", "constant") not in (
            "constant", "invscaling", "adaptive"):
        raise ValueError(
            f"learning_rate={static.get('learning_rate')!r} is not compiled")


class MLPClassifierFamily(Family):
    name = "mlp_classifier"
    is_classifier = True
    dynamic_params = {"alpha": np.float32,
                      "learning_rate_init": np.float32}
    #: sklearn's MLP keeps the user's X dtype all the way to the proba
    #: output (one of the two classifiers on this sklearn that do —
    #: everything else upcasts to f64; see grid.py's log_loss clip)
    proba_dtype_rule = "input"

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        classes, y_enc = encode_labels(y)
        data = {
            "X": np.ascontiguousarray(X, dtype=dtype),
            "y": y_enc,
            "y1h": np.eye(len(classes), dtype=dtype)[y_enc],
        }
        meta = {"n_classes": int(len(classes)), "classes": classes,
                "n_features": int(X.shape[1])}
        return data, meta

    @classmethod
    def _out_dim(cls, meta):
        return meta["n_classes"]

    @classmethod
    def _loss_terms(cls, logits, data_slice, w):
        logp = jax.nn.log_softmax(logits, axis=1)
        per = -jnp.sum(data_slice["y1h"] * logp, axis=1)
        return jnp.sum(w * per)

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        _check_supported(static)
        # device arrays throughout: minibatch rows are gathered by TRACED
        # permutation indices, which numpy inputs (a direct family.fit
        # call outside the engine) cannot serve
        data = {k: jnp.asarray(v) for k, v in data.items()}
        train_w = jnp.asarray(train_w)
        X = data["X"]
        n, d = X.shape
        dtype = X.dtype
        out_dim = cls._out_dim(meta)
        hidden = static.get("hidden_layer_sizes", (100,))
        if isinstance(hidden, int):
            hidden = (hidden,)
        layer_sizes = (d, *[int(h) for h in hidden], out_dim)
        act = _activation(static.get("activation", "relu"))
        solver = static.get("solver", "adam")
        alpha = jnp.asarray(
            dynamic.get("alpha", static.get("alpha", 1e-4)), dtype)
        lr = jnp.asarray(
            dynamic.get("learning_rate_init",
                        static.get("learning_rate_init", 1e-3)), dtype)
        max_iter = int(static.get("max_iter", 200))
        batch_size = static.get("batch_size", "auto")
        if batch_size == "auto":
            batch_size = min(200, n)
        batch_size = int(min(batch_size, n))
        n_batches = (n + batch_size - 1) // batch_size
        n_pad = n_batches * batch_size
        seed = static.get("random_state")
        seed = 0 if seed is None else int(seed)
        momentum = float(static.get("momentum", 0.9))
        b1 = float(static.get("beta_1", 0.9))
        b2 = float(static.get("beta_2", 0.999))
        eps_adam = float(static.get("epsilon", 1e-8))

        key = jax.random.PRNGKey(seed)
        key, init_key = jax.random.split(key)
        params = _init_params(init_key, layer_sizes, dtype)

        # per-batch targets gathered by index; pad with index 0, weight 0
        y_all = {k: data[k] for k in ("y1h",) if k in data}
        if "y_target" in data:
            y_all["y_target"] = data["y_target"]

        def batch_loss(p, idx, w_idx, a):
            Xb = X[idx]
            slice_ = {k: v[idx] for k, v in y_all.items()}
            logits = _forward(p, Xb, act)
            # clamp at 1 so a minibatch with zero training-fold rows makes a
            # harmless small step instead of a 1/EPS-exploded penalty grad
            wsum = jnp.maximum(jnp.sum(w_idx), 1.0)
            data_loss = cls._loss_terms(logits, slice_, w_idx) / wsum
            l2 = sum(jnp.sum(layer["W"] ** 2) for layer in p)
            return data_loss + 0.5 * a * l2 / wsum

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        if solver == "adam":
            opt_state = {"m": zeros, "v": zeros,
                         "t": jnp.asarray(0.0, dtype)}

            def update(p, g, st, lr_eff):
                t = st["t"] + 1.0
                m = jax.tree_util.tree_map(
                    lambda m_, g_: b1 * m_ + (1 - b1) * g_, st["m"], g)
                v = jax.tree_util.tree_map(
                    lambda v_, g_: b2 * v_ + (1 - b2) * g_ * g_, st["v"], g)
                mhat = jax.tree_util.tree_map(
                    lambda m_: m_ / (1 - b1 ** t), m)
                vhat = jax.tree_util.tree_map(
                    lambda v_: v_ / (1 - b2 ** t), v)
                p_new = jax.tree_util.tree_map(
                    lambda p_, mh, vh: p_ - lr_eff * mh /
                    (jnp.sqrt(vh) + eps_adam), p, mhat, vhat)
                return p_new, {"m": m, "v": v, "t": t}
        else:  # sgd with momentum
            opt_state = {"vel": zeros}

            def update(p, g, st, lr_eff):
                vel = jax.tree_util.tree_map(
                    lambda v_, g_: momentum * v_ - lr_eff * g_, st["vel"], g)
                p_new = jax.tree_util.tree_map(
                    lambda p_, v_: p_ + v_, p, vel)
                return p_new, {"vel": vel}

        # ---- sklearn stopping semantics (while_loop over epochs) ---------
        tol = float(static.get("tol", 1e-4))
        n_iter_no_change = int(static.get("n_iter_no_change", 10))
        early_stopping = bool(static.get("early_stopping", False))
        lr_schedule = static.get("learning_rate", "constant")
        power_t = float(static.get("power_t", 0.5))
        val_frac = float(static.get("validation_fraction", 0.1))

        if early_stopping:
            # hold out ~validation_fraction of the TRAIN-FOLD rows with a
            # PRNG mask: same semantics as sklearn's train_test_split
            # (score a held-out slice each epoch, restore best weights),
            # independent of the fold mask so every task shares one split
            key, vkey = jax.random.split(key)
            val_sel = (jax.random.uniform(vkey, (n,)) < val_frac).astype(
                dtype)
            fit_w = train_w * (1.0 - val_sel)
            val_w = train_w * val_sel
        else:
            fit_w = train_w
            val_w = None

        # sklearn advances its invscaling clock by the number of rows the
        # net actually trains on per epoch — the train-fold subset, minus
        # the early-stopping validation hold-out — not the full dataset
        n_fit_rows = jnp.sum((fit_w > 0).astype(dtype))

        def epoch_lr(it):
            """sklearn's SGDOptimizer.iteration_ends: lr fixed within an
            epoch, rescaled from the count of samples seen (invscaling);
            adam ignores schedules like sklearn's AdamOptimizer."""
            if solver != "sgd" or lr_schedule != "invscaling":
                return lr
            # epoch 0 runs at lr_init (sklearn decays AFTER each epoch,
            # from the count of samples seen so far)
            t_seen = it.astype(dtype) * n_fit_rows
            return lr / (t_seen + 1.0) ** power_t

        def run_epoch(p, st, ek, lr_eff):
            perm = jax.random.permutation(ek, n)
            # pad with index 0 at ZERO weight (a modulo wrap would silently
            # double-count wrapped samples at full weight)
            idx_pad = jnp.concatenate(
                [perm, jnp.zeros((n_pad - n,), perm.dtype)])
            wmul = jnp.concatenate(
                [jnp.ones((n,), dtype), jnp.zeros((n_pad - n,), dtype)])
            batches = idx_pad.reshape(n_batches, batch_size)
            wmuls = wmul.reshape(n_batches, batch_size)

            def one_batch(c, inp):
                p_, st_, acc = c
                idx, wm = inp
                w_idx = fit_w[idx] * wm
                loss, g = jax.value_and_grad(batch_loss)(
                    p_, idx, w_idx, alpha)
                wsum = jnp.maximum(jnp.sum(w_idx), 1.0)
                p_, st_ = update(p_, g, st_, lr_eff)
                # sklearn accumulates batch_loss * batch_size / n_total
                return (p_, st_, acc + loss * wsum), None

            (p, st, acc), _ = jax.lax.scan(
                one_batch, (p, st, jnp.asarray(0.0, dtype)),
                (batches, wmuls))
            wtot = jnp.maximum(jnp.sum(fit_w), 1.0)
            return p, st, acc / wtot

        def val_score(p):
            out = _forward(p, X, act)
            wsum = jnp.maximum(jnp.sum(val_w), jnp.asarray(1e-12, dtype))
            if cls.is_classifier:
                pred = jnp.argmax(out, axis=1)
                return jnp.sum(val_w * (pred == data["y"])) / wsum
            yt = data["y_target"]
            err = jnp.sum((out - yt) ** 2, axis=1)
            resid = jnp.sum(val_w * err) / wsum
            ym = jnp.sum(val_w[:, None] * yt, axis=0) / wsum
            tot = jnp.sum(val_w * jnp.sum((yt - ym[None, :]) ** 2,
                                          axis=1)) / wsum
            return 1.0 - resid / jnp.maximum(tot,
                                             jnp.asarray(1e-12, dtype))

        big = jnp.asarray(np.finfo(np.float32).max, dtype)
        state = dict(
            p=params, opt=opt_state, key=key,
            it=jnp.asarray(0, jnp.int32),
            stop=jnp.asarray(False),
            # best validation score (early stopping) / best loss (plateau)
            best_score=-big, best_loss=big,
            no_improve=jnp.asarray(0, jnp.int32),
            lr_div=jnp.asarray(1.0, dtype),      # adaptive: lr /= 5 steps
            best_p=params,
        )

        def cond(s):
            return jnp.logical_and(s["it"] < max_iter,
                                   jnp.logical_not(s["stop"]))

        def body(s):
            key, ek = jax.random.split(s["key"])
            lr_eff = epoch_lr(s["it"]) / s["lr_div"]
            p, opt, loss = run_epoch(s["p"], s["opt"], ek, lr_eff)
            if early_stopping:
                score = val_score(p)
                improved_tol = score >= s["best_score"] + tol
                is_best = score > s["best_score"]
                best_score = jnp.where(is_best, score, s["best_score"])
                best_p = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(is_best, new, old),
                    p, s["best_p"])
                best_loss = s["best_loss"]
            else:
                improved_tol = loss <= s["best_loss"] - tol
                best_loss = jnp.minimum(loss, s["best_loss"])
                best_score = s["best_score"]
                best_p = s["best_p"]
            no_improve = jnp.where(improved_tol, 0, s["no_improve"] + 1)
            trigger = no_improve > n_iter_no_change
            if solver == "sgd" and lr_schedule == "adaptive":
                # sklearn SGDOptimizer.trigger_stopping: while the CURRENT
                # lr is above 1e-6, divide by 5 and keep going; only stop
                # when the current lr has already decayed to <= 1e-6 (one
                # more decay round than gating on lr/5)
                can_decay = lr_eff > 1e-6
                lr_div = jnp.where(jnp.logical_and(trigger, can_decay),
                                   s["lr_div"] * 5.0, s["lr_div"])
                stop = jnp.logical_and(trigger,
                                       jnp.logical_not(can_decay))
                no_improve = jnp.where(trigger, 0, no_improve)
            else:
                lr_div = s["lr_div"]
                stop = trigger
            return dict(p=p, opt=opt, key=key, it=s["it"] + 1, stop=stop,
                        best_score=best_score, best_loss=best_loss,
                        no_improve=no_improve, lr_div=lr_div, best_p=best_p)

        s = jax.lax.while_loop(cond, body, state)
        final_p = s["best_p"] if early_stopping else s["p"]
        return {"layers": final_p, "n_iter": s["it"]}

    @classmethod
    def _logits(cls, model, static, X, meta):
        act = _activation(static.get("activation", "relu"))
        return _forward(model["layers"], X, act)

    @classmethod
    def decision(cls, model, static, X, meta):
        Z = cls._logits(model, static, X, meta)
        if meta.get("n_classes") == 2:
            # scorer contract: binary decision is a 1-D margin
            return Z[:, 1] - Z[:, 0]
        return Z

    @classmethod
    def predict(cls, model, static, X, meta):
        return jnp.argmax(cls._logits(model, static, X, meta),
                          axis=1).astype(jnp.int32)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        return jax.nn.softmax(cls._logits(model, static, X, meta), axis=1)

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        layers = model["layers"]
        attrs = {
            "coefs_": [np.asarray(l["W"]) for l in layers],
            "intercepts_": [np.asarray(l["b"]) for l in layers],
            "classes_": meta.get("classes"),
            "n_features_in_": meta["n_features"],
            "n_layers_": len(layers) + 1,
        }
        if "n_iter" in model:
            attrs["n_iter_"] = int(model["n_iter"])
        return attrs


class MLPRegressorFamily(MLPClassifierFamily):
    name = "mlp_regressor"
    is_classifier = False

    @classmethod
    def build_fit_data(cls, Xg, yg, meta):
        yt = yg.astype(Xg.dtype)
        # the loss consumes "y_target" in (n, n_targets) layout; keyed
        # fleets carry a single y column -> (n, 1)
        return {"X": Xg, "y": yt, "y_target": yt[:, None]}

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        y = np.asarray(y, dtype=dtype)
        data = {
            "X": np.ascontiguousarray(X, dtype=dtype),
            "y": y,
            "y_target": y.reshape(len(y), -1),
        }
        meta = {"n_features": int(X.shape[1]),
                "n_targets": int(data["y_target"].shape[1])}
        return data, meta

    @classmethod
    def _out_dim(cls, meta):
        return meta["n_targets"]

    @classmethod
    def _loss_terms(cls, preds, data_slice, w):
        se = jnp.sum((preds - data_slice["y_target"]) ** 2, axis=1)
        return 0.5 * jnp.sum(w * se)

    @classmethod
    def predict(cls, model, static, X, meta):
        out = cls._logits(model, static, X, meta)
        return out[:, 0] if meta["n_targets"] == 1 else out

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        attrs = MLPClassifierFamily.sklearn_attrs.__func__(
            cls, model, static, meta)
        attrs.pop("classes_", None)
        return attrs


register_family(
    MLPClassifierFamily,
    "sklearn.neural_network._multilayer_perceptron.MLPClassifier",
    "sklearn.neural_network.MLPClassifier",
)
register_family(
    MLPRegressorFamily,
    "sklearn.neural_network._multilayer_perceptron.MLPRegressor",
    "sklearn.neural_network.MLPRegressor",
)
