"""Linear model families: logistic regression, ridge/OLS, elastic-net.

Reference counterpart: spark-sklearn's Converter supports exactly
LogisticRegression and LinearRegression (reference: converter.py), and its
GridSearchCV runs any sklearn estimator on CPU executors.  Here the linear
families are first-class compiled citizens: one jitted program per compile
group, `vmap` over the candidate axis, masked sample weights over the fold
axis, MXU-friendly dense matmuls.

Numeric conventions follow sklearn so the vendored oracle tests pass:
  - LogisticRegression: minimise sum-logloss + 0.5/C * ||coef||^2 (intercept
    unpenalised), lbfgs, tol on max|grad|.
  - Ridge: weighted normal equations with unpenalised intercept.
  - LinearRegression: lstsq on weighted-centred data.
  - ElasticNet/Lasso: FISTA on 1/(2n) LSQ + alpha*(l1_ratio*L1 + (1-l1_ratio)
    /2*L2), centred intercept.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, encode_labels, register_family
from spark_sklearn_tpu.ops.solvers import lbfgs


def _is_bcoo(X) -> bool:
    """True when X is a device BCOO operand (the sparse Tier-A path).
    jnp.matmul/einsum reject BCOO, so the matmul sites below switch to
    the equivalent `@`-operator forms when this holds."""
    try:
        from jax.experimental import sparse as jsparse
    except ImportError:       # pragma: no cover - jax always ships it
        return False
    return isinstance(X, jsparse.BCOO)


# ----------------------------------------------------------------------------
# Logistic regression
# ----------------------------------------------------------------------------

class LogisticRegressionFamily(Family):
    name = "logistic_regression"
    is_classifier = True
    dynamic_params = {"C": np.float32, "tol": np.float32}
    #: the GLM solvers only touch X through Ax/AT, both expressible as
    #: BCOO-legal operator-form matmuls
    supports_sparse = True

    #: sorted chunking needs enough candidates to amortise the extra
    #: dispatches on the GLM solvers (policy applied by the engine)
    min_sort_candidates = 32

    @classmethod
    def convergence_proxy(cls, dynamic_params, static):
        """Ascending-difficulty proxy for sorted chunking: larger C =
        weaker regularisation = slower L-BFGS/FISTA convergence.  None
        when C is not in the grid (nothing to grade by); the engine
        applies the size threshold and constant-proxy guard."""
        return dynamic_params.get("C")

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        classes, y_enc = encode_labels(y)
        data = {
            "X": np.ascontiguousarray(X, dtype=dtype),
            "y": y_enc,
            "y1h": np.eye(len(classes), dtype=dtype)[y_enc],
        }
        meta = {"n_classes": int(len(classes)), "classes": classes,
                "n_features": int(X.shape[1])}
        return data, meta

    @classmethod
    def prepare_data_sparse(cls, X, y, dtype=np.float32):
        from spark_sklearn_tpu.sparse.csr import SparseOperand
        classes, y_enc = encode_labels(y)
        op = SparseOperand.from_csr(X, dtype=dtype)
        data = {"X": op,
                "y": y_enc,
                "y1h": np.eye(len(classes), dtype=dtype)[y_enc]}
        # signature tuple (truthy, hashable) -> program-store/fusion
        # keys via freeze(meta); see naive_bayes._prep_classifier_sparse
        meta = {"n_classes": int(len(classes)), "classes": classes,
                "n_features": int(X.shape[1]), "sparse": op.signature()}
        return data, meta

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        X = data["X"]
        n, d = X.shape
        k = meta["n_classes"]
        C = jnp.asarray(dynamic.get("C", static.get("C", 1.0)), X.dtype)
        tol = dynamic.get("tol", static.get("tol", 1e-4))
        max_iter = int(static.get("max_iter", 100))
        fit_intercept = bool(static.get("fit_intercept", True))
        penalty = static.get("penalty", "l2")
        l1_ratio = static.get("l1_ratio", 0.0)
        if penalty == "deprecated":
            # sklearn >=1.8 sentinel: regularisation is l2 unless l1_ratio
            # mixes in an l1 term
            penalty = "l2" if not l1_ratio else "elasticnet"
        if penalty in ("l1", "elasticnet"):
            if penalty == "l1" or l1_ratio:
                # one-task view of the batched FISTA path (refit and keyed
                # fleets share the exact numerics of the search sweep)
                model = cls.fit_task_batched(
                    {k_: jnp.asarray(v)[None]
                     for k_, v in dynamic.items()},
                    static, data, train_w[None, :], meta)
                return jax.tree_util.tree_map(lambda a: a[0], model)
            penalty = "l2"   # elasticnet with l1_ratio == 0
        if penalty not in ("l2", None, "none"):
            raise ValueError(
                f"penalty={penalty!r} is not compiled; use the host backend")
        from spark_sklearn_tpu.models.base import apply_class_weight
        train_w = apply_class_weight(
            train_w, data["y"], meta, static.get("class_weight"))
        l2 = (0.5 / C) if penalty == "l2" else 0.0

        if k == 2:
            yb = data["y"].astype(X.dtype)

            def loss(w_flat):
                w, b = w_flat[:d], w_flat[d]
                z = X @ w + (b if fit_intercept else 0.0)
                per = jnp.logaddexp(0.0, z) - yb * z
                pen = l2 * jnp.dot(w, w)
                return jnp.sum(train_w * per) + pen

            res = lbfgs(loss, jnp.zeros(d + 1, X.dtype),
                        max_iter=max_iter, tol=tol)
            w = res.x
            return {"coef": w[:d][None, :], "intercept": w[d:d + 1],
                    "converged": res.converged, "n_iter": res.n_iter}
        else:
            y1h = data["y1h"]

            def loss(w_flat):
                W = w_flat[: k * d].reshape(k, d)
                b = w_flat[k * d:]
                Z = X @ W.T + (b if fit_intercept else 0.0)
                lse = jax.scipy.special.logsumexp(Z, axis=1)
                per = lse - jnp.sum(Z * y1h, axis=1)
                pen = l2 * jnp.sum(W * W)
                return jnp.sum(train_w * per) + pen

            res = lbfgs(loss, jnp.zeros(k * d + k, X.dtype),
                        max_iter=max_iter, tol=tol)
            W = res.x[: k * d].reshape(k, d)
            b = res.x[k * d:]
            if not fit_intercept:
                b = jnp.zeros_like(b)
            return {"coef": W, "intercept": b,
                    "converged": res.converged, "n_iter": res.n_iter}

    @classmethod
    def fit_task_batched(cls, dynamic, static, data, train_w, meta):
        """All (candidate x fold) tasks as ONE wide-matmul program.

        `dynamic` leaves and `train_w` carry a leading task axis B; the
        logits for every task come from a single `X @ W_all` contraction of
        width B*k, which keeps the MXU tiles full (a vmap of per-task fits
        leaves them mostly empty for small k).  Returns model pytrees with
        leading axis B.
        """
        from spark_sklearn_tpu.ops.solvers import glm_lbfgs_batched

        X = data["X"]
        n, d = X.shape
        k = meta["n_classes"]
        B = train_w.shape[0]
        C = jnp.asarray(dynamic.get("C", static.get("C", 1.0)), X.dtype)
        C = jnp.broadcast_to(C, (B,))
        tol = jnp.broadcast_to(jnp.asarray(
            dynamic.get("tol", static.get("tol", 1e-4)), X.dtype), (B,))
        max_iter = int(static.get("max_iter", 100))
        fit_intercept = bool(static.get("fit_intercept", True))
        penalty = static.get("penalty", "l2")
        l1_ratio = static.get("l1_ratio", 0.0) or 0.0
        if penalty == "deprecated":
            penalty = "l2" if not l1_ratio else "elasticnet"
        if penalty == "l1":
            penalty, l1_ratio = "elasticnet", 1.0
        if penalty == "elasticnet" and not l1_ratio:
            penalty = "l2"   # pure-l2 config: quasi-Newton is ~10x cheaper
        if penalty not in ("l2", "elasticnet", None, "none"):
            raise ValueError(
                f"penalty={penalty!r} is not compiled; use the host backend")
        from spark_sklearn_tpu.models.base import apply_class_weight
        train_w = apply_class_weight(
            train_w, data["y"], meta, static.get("class_weight"))
        use_fista = penalty == "elasticnet"
        inv_C_raw = 1.0 / C
        inv_C = inv_C_raw if penalty == "l2" else jnp.zeros_like(C)
        wT = train_w.T                                        # (n, B)
        # MXU-native precision: cast matmul OPERANDS to bf16, accumulate
        # fp32; everything else (losses, solver state) stays fp32.  A
        # BCOO X stays in its own dtype (f32) — the sparse matmuls run
        # as gather/scatter, where a bf16 downcast buys nothing
        sparse_X = _is_bcoo(X)
        bf16 = bool(static.get("__bf16__", False)) and not sparse_X
        mm_dtype = jnp.bfloat16 if bf16 else X.dtype
        Xm = X if sparse_X else X.astype(mm_dtype)

        if k == 2:
            yb = data["y"].astype(X.dtype)                    # (n,)

            def Ax(x):                                        # -> Z (n, B)
                if sparse_X:
                    Z = Xm @ x[:, :d].T
                else:
                    Z = jnp.matmul(Xm, x[:, :d].astype(mm_dtype).T,
                                   preferred_element_type=X.dtype)
                return Z + x[None, :, d] if fit_intercept else Z

            def data_loss(Z):
                per = jnp.logaddexp(0.0, Z) - yb[:, None] * Z
                return jnp.sum(wT * per, axis=0)

            def data_grad(Z):                                 # dL/dZ (n, B)
                return wT * (jax.nn.sigmoid(Z) - yb[:, None])

            def AT(G):                                        # -> (B, d+1)
                if sparse_X:
                    gW = G.T @ Xm
                else:
                    gW = jnp.matmul(G.astype(mm_dtype).T, Xm,
                                    preferred_element_type=X.dtype)
                gb = jnp.sum(G, axis=0) if fit_intercept else \
                    jnp.zeros((B,), X.dtype)
                return jnp.concatenate([gW, gb[:, None]], axis=1)

            def reg_loss(x):
                return 0.5 * inv_C * jnp.sum(x[:, :d] ** 2, axis=1)

            def reg_grad(x):
                g = inv_C[:, None] * x[:, :d]
                return jnp.concatenate(
                    [g, jnp.zeros((B, 1), X.dtype)], axis=1)

            if use_fista:
                res, n_exec = _fista_elasticnet(
                    Ax, data_loss, data_grad, AT, inv_C_raw, l1_ratio,
                    B, d + 1, d, X.dtype, max_iter, tol)
            else:
                res = glm_lbfgs_batched(
                    Ax, data_loss, data_grad, AT, reg_loss, reg_grad,
                    jnp.zeros((B, d + 1), X.dtype), max_iter=max_iter,
                    tol=tol)
                n_exec = res.n_iter
            W = res.x[:, :d]
            b = res.x[:, d]
            if not fit_intercept:
                b = jnp.zeros_like(b)
            return {"coef": W[:, None, :], "intercept": b[:, None],
                    "converged": res.converged, "n_iter": res.n_iter,
                    "n_iter_exec": n_exec}

        y1h = data["y1h"]                                     # (n, k)
        kd = k * d

        def Ax(x):                                            # -> Z (n,B,k)
            W = x[:, :kd].reshape(B, k, d)
            if sparse_X:
                # einsum rejects BCOO; the reshape-matmul form is the
                # identical contraction
                Z = (Xm @ W.reshape(B * k, d).T).reshape(n, B, k)
            else:
                Z = jnp.einsum("nd,bkd->nbk", Xm,             # ONE matmul
                               W.astype(mm_dtype),
                               preferred_element_type=X.dtype)
            return Z + x[None, :, kd:] if fit_intercept else Z

        def data_loss(Z):
            lse = jax.scipy.special.logsumexp(Z, axis=2)      # (n, B)
            fit_term = lse - jnp.einsum("nbk,nk->nb", Z, y1h)
            return jnp.sum(wT * fit_term, axis=0)

        def data_grad(Z):                                     # (n, B, k)
            P = jax.nn.softmax(Z, axis=2)
            return wT[:, :, None] * (P - y1h[:, None, :])

        def AT(G):                                            # -> (B, D)
            if sparse_X:
                gW = (G.reshape(n, B * k).T @ Xm).reshape(B, k, d)
            else:
                gW = jnp.einsum("nbk,nd->bkd", G.astype(mm_dtype), Xm,
                                preferred_element_type=X.dtype)
            gW = gW.reshape(B, kd)
            gb = jnp.sum(G, axis=0) if fit_intercept else \
                jnp.zeros((B, k), X.dtype)
            return jnp.concatenate([gW, gb], axis=1)

        def reg_loss(x):
            return 0.5 * inv_C * jnp.sum(x[:, :kd] ** 2, axis=1)

        def reg_grad(x):
            g = inv_C[:, None] * x[:, :kd]
            return jnp.concatenate(
                [g, jnp.zeros((B, k), X.dtype)], axis=1)

        if use_fista:
            res, n_exec = _fista_elasticnet(
                Ax, data_loss, data_grad, AT, inv_C_raw, l1_ratio,
                B, kd + k, kd, X.dtype, max_iter, tol, curvature=0.5)
        else:
            res = glm_lbfgs_batched(
                Ax, data_loss, data_grad, AT, reg_loss, reg_grad,
                jnp.zeros((B, kd + k), X.dtype), max_iter=max_iter, tol=tol)
            n_exec = res.n_iter
        W = res.x[:, :kd].reshape(B, k, d)
        b = res.x[:, kd:]
        if not fit_intercept:
            b = jnp.zeros_like(b)
        return {"coef": W, "intercept": b,
                "converged": res.converged, "n_iter": res.n_iter,
                "n_iter_exec": n_exec}

    @classmethod
    def decision(cls, model, static, X, meta):
        Z = X @ model["coef"].T + model["intercept"]
        if meta["n_classes"] == 2:
            return Z[:, 0]
        return Z

    @classmethod
    def views_task_batched(cls, models, static, data, meta, needed):
        """Scorer views for ALL tasks from ONE wide matmul.

        `models` carries a flat leading task axis T (coef (T, k, d),
        intercept (T, k)); the logits for every task come from a single
        `X @ W_all^T` contraction of width T*k — the scoring twin of
        `fit_task_batched`'s wide-matmul layout (a vmap of per-task
        matvecs leaves the MXU tiles mostly empty for small k)."""
        X = data["X"]
        n = X.shape[0]
        W = models["coef"]                                 # (T, k, d)
        b = models["intercept"]                            # (T, k)
        T, k, d = W.shape
        if _is_bcoo(X):
            Z = X @ W.reshape(T * k, d).T                  # ONE matmul
        else:
            Z = jnp.matmul(X, W.reshape(T * k, d).T,       # ONE matmul
                           preferred_element_type=X.dtype)
        Z = Z.reshape(n, T, k) + b[None]
        Z = jnp.moveaxis(Z, 0, 1)                          # (T, n, k)
        views = {}
        if meta["n_classes"] == 2:
            z = Z[:, :, 0]                                 # (T, n)
            if "decision" in needed:
                views["decision"] = z
            if "pred" in needed:
                views["pred"] = (z > 0).astype(jnp.int32)
            if "proba" in needed:
                p1 = jax.nn.sigmoid(z)
                views["proba"] = jnp.stack([1.0 - p1, p1], axis=-1)
        else:
            if "decision" in needed:
                views["decision"] = Z
            if "pred" in needed:
                views["pred"] = jnp.argmax(Z, axis=-1).astype(jnp.int32)
            if "proba" in needed:
                views["proba"] = jax.nn.softmax(Z, axis=-1)
        return views

    @classmethod
    def predict(cls, model, static, X, meta):
        Z = cls.decision(model, static, X, meta)
        if meta["n_classes"] == 2:
            return (Z > 0).astype(jnp.int32)
        return jnp.argmax(Z, axis=1).astype(jnp.int32)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        Z = cls.decision(model, static, X, meta)
        if meta["n_classes"] == 2:
            p1 = jax.nn.sigmoid(Z)
            return jnp.stack([1.0 - p1, p1], axis=1)
        return jax.nn.softmax(Z, axis=1)

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        attrs = {
            "coef_": np.asarray(model["coef"]),
            "intercept_": np.asarray(model["intercept"]),
            "classes_": meta["classes"],
            "n_features_in_": meta["n_features"],
        }
        if "n_iter" in model:  # absent on Converter.toTPU-built models
            attrs["n_iter_"] = np.asarray([int(model["n_iter"])])
        return attrs


def _fista_elasticnet(Ax, data_loss, data_grad, AT, inv_C, l1_ratio,
                      B, D, n_pen, dtype, max_iter, tol,
                      curvature=0.25):
    """Elastic-net logistic via proximal FISTA: per-coefficient l1/l2
    weights cover the first n_pen entries (coefficients); the remaining
    intercept entries stay unpenalised, matching sklearn's convention."""
    from spark_sklearn_tpu.ops.solvers import glm_fista_batched

    l1r = jnp.asarray(l1_ratio, dtype)
    lam1 = (inv_C * l1r)[:, None]
    lam2 = (inv_C * (1.0 - l1r))[:, None]
    pen_mask = jnp.concatenate(
        [jnp.ones((B, n_pen), dtype), jnp.zeros((B, D - n_pen), dtype)],
        axis=1)
    # sklearn caps saga's EPOCHS at max_iter; FISTA steps are cheaper so
    # the internal budget is larger, but the reported n_iter is rescaled
    # onto the caller's max_iter axis so sklearn's "n_iter_ >= max_iter
    # means unconverged" idiom holds
    res = glm_fista_batched(
        Ax, data_loss, data_grad, AT,
        l1=lam1 * pen_mask, l2=lam2 * pen_mask,
        x0=jnp.zeros((B, D), dtype),
        max_iter=max(10 * max_iter, 1000), tol=tol, curvature=curvature)
    n_rep = jnp.where(res.converged,
                      jnp.minimum(res.n_iter, max_iter - 1), max_iter)
    # (rescaled-for-sklearn, actually-executed): FLOP/MFU accounting must
    # see the internal budget's true count, not the max_iter-axis rescale
    return res._replace(n_iter=n_rep), res.n_iter


# ----------------------------------------------------------------------------
# Ridge / LinearRegression
# ----------------------------------------------------------------------------

def _weighted_center(X, y, w):
    wsum = jnp.sum(w) + jnp.finfo(X.dtype).eps
    xm = (w @ X) / wsum
    ym = jnp.sum(w * y) / wsum
    return X - xm, y - ym, xm, ym


def _centered_problem(static, X, y, train_w):
    """Shared OLS/Ridge preamble: positive= guard + optional weighted
    centering.  Returns (Xc, yc, xm, ym)."""
    if static.get("positive", False):
        raise ValueError(
            "positive=True is not compiled; use the host backend")
    if bool(static.get("fit_intercept", True)):
        return _weighted_center(X, y, train_w)
    d = X.shape[1]
    return X, y, jnp.zeros((d,), X.dtype), jnp.asarray(0.0, X.dtype)


class RidgeFamily(Family):
    name = "ridge"
    is_classifier = False
    dynamic_params = {"alpha": np.float32}
    # closed-form normal equations: the Gram's conditioning amplifies f32
    # rounding ~1e-4 past sklearn's f64 answers, so the search engine runs
    # this family under x64 (tiny d x d solves — negligible cost)
    wants_float64 = True
    #: the fit is a function of raw second moments {sum w, w@X, sum wy,
    #: X'WX, X'Wy} — additive over row shards; finalize re-centres them
    #: (x64, so the moment expansion stays at solver tolerance)
    supports_stream = True

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        data = {"X": np.ascontiguousarray(X, dtype=dtype),
                "y": np.ascontiguousarray(y, dtype=dtype)}
        meta = {"n_features": int(X.shape[1])}
        return data, meta

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        X, y = data["X"], data["y"]
        d = X.shape[1]
        alpha = jnp.asarray(dynamic.get("alpha", static.get("alpha", 1.0)),
                            X.dtype)
        Xc, yc, xm, ym = _centered_problem(static, X, y, train_w)
        Xw = Xc * train_w[:, None]
        A = Xw.T @ Xc + alpha * jnp.eye(d, dtype=X.dtype)
        b = Xw.T @ yc
        w = jax.scipy.linalg.solve(A, b, assume_a="pos")
        intercept = ym - jnp.dot(xm, w)
        return {"coef": w, "intercept": intercept}

    # --- streaming-fold protocol -----------------------------------------
    @classmethod
    def stream_fit_partial(cls, static, data, fit_w, meta):
        if static.get("positive", False):
            raise ValueError(
                "positive=True is not compiled; use the host backend")
        X, y = data["X"], data["y"]

        def one_fold(w):
            Xw = X * w[:, None]
            return {"wsum": jnp.sum(w), "s": w @ X,
                    "ys": jnp.sum(w * y),
                    "G": Xw.T @ X, "c": Xw.T @ y}

        return jax.vmap(one_fold)(fit_w)

    @classmethod
    def stream_fit_finalize(cls, dynamic, static, stats, meta):
        if static.get("positive", False):
            raise ValueError(
                "positive=True is not compiled; use the host backend")
        G, s, c = stats["G"], stats["s"], stats["c"]
        dt = G.dtype
        d = s.shape[0]
        alpha = jnp.asarray(dynamic.get("alpha", static.get("alpha", 1.0)),
                            dt)
        if bool(static.get("fit_intercept", True)):
            # centred normal equations from raw moments:
            #   A = X'WX - s xm' - xm s' + (sum w) xm xm'
            #   b = X'Wy - ym s - ys xm + (sum w) xm ym
            # (xm, ym use the same eps-guarded weight sum as
            # _weighted_center)
            wsum = stats["wsum"] + jnp.finfo(dt).eps
            xm = s / wsum
            ym = stats["ys"] / wsum
            A = G - jnp.outer(s, xm) - jnp.outer(xm, s) \
                + stats["wsum"] * jnp.outer(xm, xm)
            b = c - ym * s - stats["ys"] * xm + stats["wsum"] * xm * ym
        else:
            A, b = G, c
            xm = jnp.zeros((d,), dt)
            ym = jnp.asarray(0.0, dt)
        A = A + alpha * jnp.eye(d, dtype=dt)
        w = jax.scipy.linalg.solve(A, b, assume_a="pos")
        return {"coef": w, "intercept": ym - jnp.dot(xm, w)}

    @classmethod
    def predict(cls, model, static, X, meta):
        return X @ model["coef"] + model["intercept"]

    @classmethod
    def views_task_batched(cls, models, static, data, meta, needed):
        """All T tasks' predictions as ONE (n, d) @ (d, T) matmul."""
        if "pred" not in needed:
            return {}
        X = data["X"]
        pred = jnp.matmul(X, models["coef"].T,
                          preferred_element_type=X.dtype)   # (n, T)
        return {"pred": (pred + models["intercept"][None]).T}

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"coef_": np.asarray(model["coef"]),
                "intercept_": float(model["intercept"]),
                "n_features_in_": meta["n_features"]}


class LinearRegressionFamily(RidgeFamily):
    name = "linear_regression"
    # lstsq's minimum-norm answer on rank-deficient X is NOT a function
    # of the normal-equation moments — undo the inherited capability
    supports_stream = False

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        """Weighted OLS as minimum-norm lstsq (SVD), matching sklearn's
        scipy.linalg.lstsq path: on rank-deficient X the solution is the
        minimum-norm one, where a ridge-with-tiny-alpha stand-in (the
        round-1 implementation) diverges from sklearn."""
        X, y = data["X"], data["y"]
        Xc, yc, xm, ym = _centered_problem(static, X, y, train_w)
        sw = jnp.sqrt(train_w)
        w, *_ = jnp.linalg.lstsq(Xc * sw[:, None], yc * sw)
        intercept = ym - jnp.dot(xm, w)
        return {"coef": w, "intercept": intercept}


# ----------------------------------------------------------------------------
# ElasticNet / Lasso (FISTA)
# ----------------------------------------------------------------------------

class ElasticNetFamily(Family):
    name = "elastic_net"
    is_classifier = False
    dynamic_params = {"alpha": np.float32, "l1_ratio": np.float32}

    prepare_data = RidgeFamily.prepare_data

    min_sort_candidates = 32

    @classmethod
    def convergence_proxy(cls, dynamic_params, static):
        """Smaller alpha = weaker penalty = slower FISTA convergence,
        so ascending difficulty = DESCENDING alpha (negated proxy)."""
        alpha = dynamic_params.get("alpha")
        return None if alpha is None else -np.asarray(alpha)

    @classmethod
    def extract_params(cls, estimator):
        params = dict(estimator.get_params(deep=False))
        if type(estimator).__name__ == "Lasso":
            params["l1_ratio"] = 1.0
        return params

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        X, y = data["X"], data["y"]
        d = X.shape[1]
        alpha = jnp.asarray(dynamic.get("alpha", static.get("alpha", 1.0)),
                            X.dtype)
        l1r = jnp.asarray(
            dynamic.get("l1_ratio", static.get("l1_ratio", 0.5)), X.dtype)
        max_iter = int(static.get("max_iter", 1000))
        n_eff = jnp.sum(train_w) + jnp.finfo(X.dtype).eps
        Xc, yc, xm, ym = _centered_problem(static, X, y, train_w)
        Xw = Xc * train_w[:, None]
        # Lipschitz constant of (1/n) X^T W X via power iteration
        G = Xw.T @ Xc / n_eff
        v = jnp.ones((d,), X.dtype) / jnp.sqrt(d)

        def power(i, v):
            v = G @ v
            return v / (jnp.linalg.norm(v) + jnp.finfo(X.dtype).eps)

        v = jax.lax.fori_loop(0, 30, power, v)
        L = jnp.dot(v, G @ v) + alpha * (1.0 - l1r) + 1e-6
        lam1 = alpha * l1r
        lam2 = alpha * (1.0 - l1r)

        def grad(w):
            r = Xc @ w - yc
            return (Xw.T @ r) / n_eff + lam2 * w

        def soft(u, t):
            return jnp.sign(u) * jnp.maximum(jnp.abs(u) - t, 0.0)

        def body(carry, _):
            w, z, t = carry
            w_new = soft(z - grad(z) / L, lam1 / L)
            t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
            z_new = w_new + (t - 1.0) / t_new * (w_new - w)
            return (w_new, z_new, t_new), None

        w0 = jnp.zeros((d,), X.dtype)
        (w, _, _), _ = jax.lax.scan(
            body, (w0, w0, jnp.asarray(1.0, X.dtype)), None, length=max_iter)
        intercept = ym - jnp.dot(xm, w)
        return {"coef": w, "intercept": intercept}

    predict = RidgeFamily.predict
    views_task_batched = RidgeFamily.views_task_batched
    sklearn_attrs = RidgeFamily.sklearn_attrs


register_family(
    LogisticRegressionFamily,
    "sklearn.linear_model._logistic.LogisticRegression",
    "sklearn.linear_model.LogisticRegression",
    "spark_sklearn_tpu.models.estimators.LogisticRegression",
)
register_family(
    RidgeFamily,
    "sklearn.linear_model._ridge.Ridge",
    "sklearn.linear_model.Ridge",
    "spark_sklearn_tpu.models.estimators.Ridge",
)
register_family(
    LinearRegressionFamily,
    "sklearn.linear_model._base.LinearRegression",
    "sklearn.linear_model.LinearRegression",
    "spark_sklearn_tpu.models.estimators.LinearRegression",
)
register_family(
    ElasticNetFamily,
    "sklearn.linear_model._coordinate_descent.ElasticNet",
    "sklearn.linear_model.ElasticNet",
    "sklearn.linear_model._coordinate_descent.Lasso",
    "sklearn.linear_model.Lasso",
    "spark_sklearn_tpu.models.estimators.ElasticNet",
    "spark_sklearn_tpu.models.estimators.Lasso",
)
