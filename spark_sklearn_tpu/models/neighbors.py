"""Compiled k-nearest-neighbors families — a TPU-first redesign.

Reference behavior: KNeighborsClassifier/Regressor run as arbitrary
sklearn estimators inside Spark tasks (reference: grid_search.py ->
sklearn _fit_and_score), so every (candidate, fold) task recomputes the
FULL pairwise-distance problem from scratch on a CPU executor.

The TPU-first shape inverts that cost model completely:

  - ONE squared-distance Gram `||xi||^2 + ||xj||^2 - 2 X X^T` for the
    whole search — a single (n, d) @ (d, n) MXU matmul shared by every
    candidate and every fold.
  - Per FOLD (not per task): mask non-train columns to +inf, one
    `lax.top_k` of the grid-wide max n_neighbors, then a cumulative
    weighted one-hot vote over the sorted neighbors.
  - Per CANDIDATE: k is just an INDEX into the cumulative votes — O(1)
    per (candidate, fold) task after the shared preamble.

A 20-candidate x 5-fold KNN grid therefore costs ~one matmul + 5 top_k
calls total, where the reference pays 100 full distance computations.

sklearn-semantics notes:
  - brute-force euclidean only (metric minkowski with p=2 / euclidean);
    other metrics raise -> Tier B host path.
  - weights in {"uniform", "distance"}; distance weights use 1/d with
    d clamped at 1e-12, so an exact-duplicate neighbor dominates the
    vote (sklearn's exact rule: zero-distance neighbors take the whole
    vote; the clamp reproduces it to float precision).
  - predict on rows that belong to the train fold sees the row itself
    as a zero-distance neighbor, exactly like sklearn's
    `KNeighborsClassifier.fit(Xtr).predict(Xtr)`.
  - KNN fit takes no sample_weight in sklearn -> weighted searches take
    the host tier (accepts_sample_weight = False).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_sklearn_tpu.models.base import Family, encode_labels, register_family

_EPS_DIST = 1e-12


def _check_metric(static):
    metric = static.get("metric", "minkowski")
    p = static.get("p", 2)
    if metric not in ("minkowski", "euclidean") or \
            (metric == "minkowski" and p not in (2, 2.0)):
        raise ValueError(
            f"metric={metric!r}/p={p!r} is not compiled (brute euclidean "
            "only); use backend='host'")
    weights = static.get("weights", "uniform")
    if weights not in ("uniform", "distance") and not callable(weights):
        raise ValueError(f"weights={weights!r} is not compiled")
    if callable(weights):
        raise ValueError("callable weights are not compiled; use "
                         "backend='host'")


def _sq_dists(X):
    """Squared euclidean Gram via ONE wide matmul."""
    sq = jnp.sum(X * X, axis=1)
    D = sq[:, None] + sq[None, :] - 2.0 * (X @ X.T)
    return jnp.maximum(D, 0.0)


def _fold_neighbors(D, train_ind, maxk):
    """Per-fold sorted neighbors: (vals, idx) of the maxk nearest TRAIN
    columns for every row; excluded columns sit at +inf so `valid`
    masks lanes beyond the fold's train count."""
    Dm = jnp.where(train_ind[None, :] > 0, D, jnp.inf)
    negv, idx = lax.top_k(-Dm, maxk)            # (n, maxk)
    d2 = -negv
    valid = jnp.isfinite(d2)
    return d2, idx, valid


def _neighbor_weights(d2, valid, weights, dtype):
    if weights == "distance":
        w = 1.0 / jnp.maximum(jnp.sqrt(d2), _EPS_DIST)
    else:
        w = jnp.ones_like(d2)
    return jnp.where(valid, w, jnp.zeros((), dtype))


class KNeighborsClassifierFamily(Family):
    name = "kneighbors_classifier"
    is_classifier = True
    dynamic_params = {"n_neighbors": np.int32}
    #: sklearn's vote tables are float64 regardless of X
    proba_dtype_rule = "float64"
    #: sklearn's KNeighbors fit has no sample_weight parameter
    accepts_sample_weight = False
    keyed_compatible = False

    @classmethod
    def extract_params(cls, estimator):
        return dict(estimator.get_params(deep=False))

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        classes, y_enc = encode_labels(y)
        data = {"X": np.ascontiguousarray(X, dtype=dtype), "y": y_enc}
        meta = {"n_classes": int(len(classes)), "classes": classes,
                "n_features": int(X.shape[1])}
        return data, meta

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        ks = [int(c.get("n_neighbors",
                        base_params.get("n_neighbors", 5)))
              for c in candidates] or [int(base_params.get("n_neighbors",
                                                           5))]
        meta["max_k"] = max(ks)
        # sklearn raises at kneighbors() when a fold's train count is
        # smaller than n_neighbors; the compiled vote table would
        # silently clip to k=n_train instead — refuse host-side so both
        # backends agree on such grids (ADVICE r3)
        mft = meta.get("min_fold_train_count")
        if mft is not None and meta["max_k"] > mft:
            raise ValueError(
                f"Expected n_neighbors <= n_samples_fit, but "
                f"n_neighbors = {meta['max_k']}, n_samples_fit = {mft} "
                f"(smallest CV train fold) — sklearn raises when "
                f"scoring such a fold")

    # the per-task cache is (n, n_classes) float votes
    @staticmethod
    def max_tasks_hint(n_samples: int, meta) -> int:
        kc = meta.get("n_classes", 2)
        budget = 1 << 30
        return max(1, budget // max(1, n_samples * kc * 4))

    @classmethod
    def _cum_votes(cls, data, static, train_w, meta, n_folds, val_fn):
        """Shared preamble: distance Gram + per-fold cumulative weighted
        votes.  `val_fn(idx) -> (n, maxk, V)` supplies what gets voted
        (one-hot labels for the classifier, y values for the
        regressor)."""
        _check_metric(static)
        X = data["X"]
        B = train_w.shape[0]
        nc = B // n_folds
        maxk = int(meta.get("max_k",
                            static.get("n_neighbors", 5)))
        maxk = min(maxk, X.shape[0])
        weights = static.get("weights", "uniform")
        D = _sq_dists(X)                         # ONE matmul, whole search
        fold_w = train_w.reshape(nc, n_folds, -1)[0]      # (F, n)

        def per_fold(wf):
            d2, idx, valid = _fold_neighbors(D, wf, maxk)
            wkn = _neighbor_weights(d2, valid, weights, X.dtype)
            vals = val_fn(idx)                   # (n, maxk, V)
            cum = jnp.cumsum(vals * wkn[:, :, None], axis=1)
            cumw = jnp.cumsum(wkn, axis=1)       # (n, maxk)
            return cum, cumw

        return jax.vmap(per_fold)(fold_w)        # (F, n, maxk, V), (F,n,maxk)

    @classmethod
    def fit_task_batched(cls, dynamic, static, data, train_w, meta):
        n_folds = int(static.get("__n_folds__", 0))
        if n_folds <= 0:
            raise ValueError("engine must pass __n_folds__ for KNN")
        X, y = data["X"], data["y"]
        B = train_w.shape[0]
        kc = meta["n_classes"]
        maxk = min(int(meta.get("max_k", static.get("n_neighbors", 5))),
                   X.shape[0])

        def one_hot_labels(idx):
            return jax.nn.one_hot(y[idx], kc, dtype=X.dtype)

        cum, _cumw = cls._cum_votes(
            data, static, train_w, meta, n_folds, one_hot_labels)

        k_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get("n_neighbors", static.get("n_neighbors", 5)),
            jnp.int32), (B,))
        kk = jnp.clip(k_task - 1, 0, maxk - 1)
        f_idx = jnp.arange(B, dtype=jnp.int32) % n_folds

        def per_task(f_i, k_i):
            votes = cum[f_i][:, k_i, :]                   # (n, kc)
            return votes / jnp.maximum(
                jnp.sum(votes, axis=1, keepdims=True), _EPS_DIST)

        proba = jax.vmap(per_task)(f_idx, kk)             # (B, n, kc)
        return {"proba": proba}

    # -- prediction from cached votes (search-internal) -------------------
    @classmethod
    def predict(cls, model, static, X, meta):
        return jnp.argmax(model["proba"], axis=-1).astype(jnp.int32)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        return model["proba"]

    @classmethod
    def decision(cls, model, static, X, meta):
        if meta["n_classes"] == 2:
            # ranking twin of sklearn's predict_proba[:, 1] for AUC
            return model["proba"][:, 1]
        return model["proba"]

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"classes_": meta["classes"],
                "n_features_in_": meta["n_features"]}


class KNeighborsRegressorFamily(KNeighborsClassifierFamily):
    name = "kneighbors_regressor"
    is_classifier = False

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        data = {"X": np.ascontiguousarray(X, dtype=dtype),
                "y": np.ascontiguousarray(y, dtype=dtype)}
        meta = {"n_features": int(X.shape[1])}
        return data, meta

    @staticmethod
    def max_tasks_hint(n_samples: int, meta) -> int:
        budget = 1 << 30
        return max(1, budget // max(1, n_samples * 4))

    @classmethod
    def fit_task_batched(cls, dynamic, static, data, train_w, meta):
        n_folds = int(static.get("__n_folds__", 0))
        if n_folds <= 0:
            raise ValueError("engine must pass __n_folds__ for KNN")
        X, y = data["X"], data["y"]
        B = train_w.shape[0]
        maxk = min(int(meta.get("max_k", static.get("n_neighbors", 5))),
                   X.shape[0])

        def y_vals(idx):
            return y[idx][:, :, None]                     # (n, maxk, 1)

        cum, cumw = cls._cum_votes(
            data, static, train_w, meta, n_folds, y_vals)

        k_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get("n_neighbors", static.get("n_neighbors", 5)),
            jnp.int32), (B,))
        kk = jnp.clip(k_task - 1, 0, maxk - 1)
        f_idx = jnp.arange(B, dtype=jnp.int32) % n_folds

        def per_task(f_i, k_i):
            s = cum[f_i][:, k_i, 0]
            w = cumw[f_i][:, k_i]
            return s / jnp.maximum(w, _EPS_DIST)

        pred = jax.vmap(per_task)(f_idx, kk)              # (B, n)
        return {"pred": pred}

    @classmethod
    def predict(cls, model, static, X, meta):
        return model["pred"]

    @classmethod
    def decision(cls, model, static, X, meta):
        raise NotImplementedError("KNeighborsRegressor has no decision")

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        raise NotImplementedError("KNeighborsRegressor has no proba")

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"n_features_in_": meta["n_features"]}


register_family(
    KNeighborsClassifierFamily,
    "sklearn.neighbors._classification.KNeighborsClassifier",
    "sklearn.neighbors.KNeighborsClassifier",
)
register_family(
    KNeighborsRegressorFamily,
    "sklearn.neighbors._regression.KNeighborsRegressor",
    "sklearn.neighbors.KNeighborsRegressor",
)
