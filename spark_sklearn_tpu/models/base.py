"""Tier-A estimator family protocol and registry.

The reference runs `clone(estimator).set_params(**p).fit(X[train], y[train])`
as arbitrary host Python inside each Spark task (reference: grid_search.py ->
sklearn _fit_and_score).  A TPU cannot run arbitrary Python; instead each
supported estimator *family* re-expresses fit/predict/score as pure JAX
functions with fixed shapes:

    fit(dynamic, static, data, train_w, meta)  -> model pytree
    predict(model, static, X, meta)            -> encoded predictions
    decision(model, static, X, meta)           -> scores/logits (optional)

- `dynamic`: dict of scalar hyperparameters that batch under vmap (C, alpha..)
- `static`:  dict of trace-shaping hyperparameters (penalty, hidden sizes..)
- `train_w`: per-sample weight mask (ragged CV folds -> fixed shapes,
  SURVEY §7.3 #2)
- `meta`:    host-side data facts (n_classes, classes_, feature means...)

The registry maps BOTH sklearn estimator classes and our own native
estimators to a family, so a user's existing `sklearn.linear_model.
LogisticRegression` instance is dispatched to the compiled path with no code
change — the same drop-in contract the reference had.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Type

import numpy as np

_FAMILIES_BY_CLASSNAME: Dict[str, Any] = {}


def register_family(family, *qualified_names: str):
    """Register a family under fully-qualified estimator class names
    (e.g. "sklearn.linear_model._logistic.LogisticRegression")."""
    for qn in qualified_names:
        _FAMILIES_BY_CLASSNAME[qn] = family
    return family


def _qualname(cls: Type) -> str:
    return f"{cls.__module__}.{cls.__name__}"


def resolve_family(estimator) -> Optional[Any]:
    """Find the Tier-A family for an estimator instance, or None (-> Tier B).

    Matching is by qualified class name, then by bare class name with module
    prefix "sklearn." — robust to sklearn's private-module shuffling.
    """
    cls = type(estimator)
    qn = _qualname(cls)
    if qn == "sklearn.pipeline.Pipeline":
        from spark_sklearn_tpu.models.pipeline import make_pipeline_family
        return make_pipeline_family(estimator)
    if qn in _FAMILIES_BY_CLASSNAME:
        return _FAMILIES_BY_CLASSNAME[qn]
    # tolerate sklearn's private-module shuffling, but ONLY for sklearn
    # classes — a third-party class that happens to be named
    # "LogisticRegression" must not silently get the compiled fit
    if qn.startswith("sklearn."):
        for known, fam in _FAMILIES_BY_CLASSNAME.items():
            if known.startswith("sklearn.") and \
                    known.split(".")[-1] == cls.__name__:
                return fam
    return None


class Family:
    """Base class for Tier-A families (documentation of the protocol)."""

    name: str = "base"
    #: dynamic (vmap-batchable) hyperparameter names -> numpy dtype
    dynamic_params: Dict[str, Any] = {}
    #: True for classifiers (label-encode y, default scorer = accuracy)
    is_classifier: bool = False

    #: families whose fit consumes the standard {"X", "y"[, "y1h"]} data
    #: dict; tree families (binned "codes" + grid-dependent meta) opt out
    #: of dispatchers that synthesise that dict (the keyed fleet)
    keyed_compatible: bool = True

    #: True when fit/predict tolerate data["X"] as a BCOO device operand
    #: (matmuls in operator form, no dense-only ops on X) AND the family
    #: implements `prepare_data_sparse` — consumed by the engine's
    #: `data_mode="sparse"` tier
    supports_sparse: bool = False

    #: True when the family implements the streaming-fold protocol
    #: (`stream_fit_partial` / `stream_fit_finalize`): per-fold fit
    #: statistics that are candidate-independent, additive over sample
    #: shards, and exactly reconstruct the in-core fit — consumed by the
    #: engine's `data_mode="stream"` tier
    supports_stream: bool = False

    @classmethod
    def has_per_task_fit(cls) -> bool:
        """True when the family implements the per-task `fit` (some, like
        SVC, only provide the task-batched form and cannot be composed by
        dispatchers that need one fit per vmap lane)."""
        return getattr(cls.fit, "__func__", cls.fit) is not \
            Family.fit.__func__

    # --- host side -------------------------------------------------------
    @classmethod
    def extract_params(cls, estimator) -> Dict[str, Any]:
        """estimator instance -> full param dict (host)."""
        return dict(estimator.get_params(deep=False))

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        """-> (data: dict of arrays ready for device, meta: dict of host
        facts).  Called once per search, not per candidate."""
        raise NotImplementedError

    @classmethod
    def prepare_data_sparse(cls, X, y, dtype=np.float32):
        """Sparse twin of `prepare_data`: `X` is a scipy CSR matrix and
        the returned data dict carries it as a
        `sparse.csr.SparseOperand` under "X" (the engine uploads its
        components and reassembles a device BCOO).  Host-side input
        validation (finiteness, sign checks) runs on `X.data` — never on
        a densified form.  Only meaningful with `supports_sparse`."""
        raise NotImplementedError

    # --- streaming-fold protocol (data_mode="stream") ---------------------
    # Per-fold fit statistics must be candidate-independent within one
    # compile group (static params may enter; dynamic ones may not) and
    # additive over row shards: the engine folds
    #   acc <- stream_fit_accumulate(acc, stream_fit_partial(shard))
    # on device in shard order, then vmaps stream_fit_finalize over the
    # chunk's candidates.  Scoring streams through the ordinary
    # `predict` on each shard.
    @classmethod
    def stream_fit_partial(cls, static, data, fit_w, meta):
        """One shard's per-fold fit statistics.  `data` holds the
        shard's row slices (same keys as `prepare_data`'s dict);
        `fit_w` is the (n_folds, shard_rows) fit-mask slice.  Returns a
        pytree whose leaves carry a leading fold axis and sum exactly
        across shards."""
        raise NotImplementedError

    @classmethod
    def stream_fit_finalize(cls, dynamic, static, stats, meta):
        """Folded statistics (one fold's slice, no fold axis) + one
        candidate's dynamic params -> the same model pytree `fit`
        returns.  The engine vmaps candidates x folds around this."""
        raise NotImplementedError

    # --- device side (pure, jit/vmap-safe) -------------------------------
    @classmethod
    def build_fit_data(cls, Xg, yg, meta):
        """Device-side data dict for a single-group fit (the keyed fleet's
        analog of prepare_data, traced under vmap).  `yg` is None for
        unsupervised fits; classifiers receive already-encoded labels.
        Families whose loss consumes extra keys (MLPRegressor's
        "y_target") override this so the contract lives with the family.
        """
        import jax
        import jax.numpy as jnp

        if yg is None:
            return {"X": Xg}
        if cls.is_classifier:
            yi = yg.astype(jnp.int32)
            return {"X": Xg, "y": yi,
                    "y1h": jax.nn.one_hot(yi, meta["n_classes"],
                                          dtype=Xg.dtype)}
        return {"X": Xg, "y": yg.astype(Xg.dtype)}

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        raise NotImplementedError

    @classmethod
    def predict(cls, model, static, X, meta):
        raise NotImplementedError

    @classmethod
    def decision(cls, model, static, X, meta):
        """Margins/logits for log-loss & AUC scorers; optional."""
        raise NotImplementedError

    # --- interop ---------------------------------------------------------
    @classmethod
    def sklearn_attrs(cls, model, static, meta) -> Dict[str, Any]:
        """Fitted-attribute dict (coef_, intercept_, classes_...) used by
        Converter and by refit write-back."""
        raise NotImplementedError


def encode_labels(y):
    """Host-side label encoding shared by all classifier families."""
    classes, y_enc = np.unique(y, return_inverse=True)
    return classes, y_enc.astype(np.int32)


def class_weight_multiplier(mask, y_enc, meta, class_weight):
    """Per-sample weight multipliers for `class_weight` (traced).

    mask: (..., n) fold masks (possibly many tasks batched on leading
    axes); y_enc: (n,) encoded labels.  Returns a same-shape multiplier.

    - dict {label: weight}: fold-independent lookup (host-built table).
    - "balanced": sklearn's n_train / (n_classes * bincount(y_train)),
      computed per fold from the mask's support (mask > 0), exactly the
      train-fold counts compute_class_weight sees on the host path.
    """
    import jax
    import jax.numpy as jnp

    if class_weight is None:
        return None
    k = meta["n_classes"]
    y1h = jax.nn.one_hot(y_enc, k, dtype=mask.dtype)         # (n, k)
    if isinstance(class_weight, str):
        if class_weight != "balanced":
            raise ValueError(
                f"class_weight={class_weight!r} is not compiled; use the "
                "host backend")
        ind = (mask > 0).astype(mask.dtype)                  # (..., n)
        cnt = ind @ y1h                                      # (..., k)
        n_eff = jnp.sum(ind, axis=-1, keepdims=True)         # (..., 1)
        per_class = n_eff / (k * jnp.maximum(cnt, 1.0))      # (..., k)
        return per_class @ y1h.T                             # (..., n)
    if isinstance(class_weight, dict):
        classes = list(meta["classes"])
        cw = np.ones(k, np.float64)
        for label, weight in class_weight.items():
            hits = [i for i, c in enumerate(classes) if c == label]
            if not hits:
                # sklearn raises its own wording on the host path
                raise ValueError(
                    f"class_weight key {label!r} is not a class label")
            cw[hits[0]] = weight
        arr = jnp.asarray(cw, mask.dtype)
        return jnp.broadcast_to(arr[y_enc], mask.shape)
    raise ValueError(
        f"class_weight={class_weight!r} is not compiled; use the host "
        "backend")


def apply_class_weight(mask, y_enc, meta, class_weight):
    """mask with `class_weight` multiplied in (identity when None)."""
    mult = class_weight_multiplier(mask, y_enc, meta, class_weight)
    return mask if mult is None else mask * mult
