"""Gradient boosting and random forest families on binned trees.

Reference counterpart: sklearn's GradientBoostingRegressor and
RandomForestClassifier running whole inside Spark tasks (BASELINE configs
#3/#4).  Exact-CART is replaced by the histogram grower in ops/trees.py;
the boosting/bagging layers are `lax.scan`/`vmap` programs:

  - GBDT: scan over trees, carry the prediction vector F on the FULL
    dataset (fold masks only weight the gradients), per-class trees for
    multiclass.  `n_estimators` is DYNAMIC: the program always grows the
    grid's maximum tree count and masks each tree's contribution by
    `t < n_estimators` — boosting is prefix-stable (tree t only depends on
    trees < t), so one compiled program serves every n_estimators value in
    the grid instead of one compile group per value.
  - Random forest: `vmap` over trees (independent by construction),
    Poisson(1) bootstrap weights (the standard streaming approximation of
    sampling with replacement), per-level random feature subsets, one-hot
    targets so the variance criterion matches gini up to scaling.

Known deviations from sklearn (accuracy-level parity, tested):
  256-bin quantile splits instead of exact; Poisson bootstrap;
  max_depth=None capped at 10 (fixed shapes need a bound).
"""

from __future__ import annotations

import warnings
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, encode_labels, register_family
from spark_sklearn_tpu.ops.trees import Tree, grow_tree, predict_tree

N_BINS = 256
#: fixed-shape compiled growers need a static depth bound
MAX_COMPILED_DEPTH = 10


def _prep_codes(X, dtype):
    from spark_sklearn_tpu.utils.native import quantile_bin
    edges, codes = quantile_bin(np.asarray(X, np.float32), N_BINS)
    return edges, codes.astype(np.int32)


def _seed(static):
    rs = static.get("random_state")
    return 0 if rs is None else int(rs)


def _observe_tree_candidates(cls, candidates, base_params, meta):
    """Engine hook body, host-side once per search (shared by the GBDT
    and forest families — they don't share a base class, so the hook is
    a free function that takes the concrete family).

    1. The compiled program always grows the grid's MAX tree count
       (contributions masked per candidate), so the static bound must be
       known before tracing.
    2. The once-per-search depth-fidelity warning (VERDICT r4 next #3):
       a `max_depth` of None or > MAX_COMPILED_DEPTH is truncated by the
       fixed-shape grower (None maps to the family's default bound),
       which can change the model on deep data — that must never happen
       without a visible signal.
    """
    # the base estimator's value only matters where a candidate does not
    # override it — unconditionally including it would grow (and warn
    # about) models the search never fits (e.g. the default
    # n_estimators=100 under a {"n_estimators": [5, 8]} grid)
    base = base_params.get("n_estimators", 100)
    vals = [c.get("n_estimators", base) for c in candidates] or [base]
    meta["max_estimators"] = int(
        max([v for v in vals
             if isinstance(v, (int, np.integer))] or [100]))
    base_md = base_params.get("max_depth", cls._sklearn_default_depth)
    depths = ({c.get("max_depth", base_md) for c in candidates}
              or {base_md})
    truncated = sorted(
        (d for d in depths
         if d is None or (isinstance(d, (int, np.integer))
                          and int(d) > MAX_COMPILED_DEPTH)),
        key=lambda d: (d is not None, d if d is not None else 0))
    if truncated:
        warnings.warn(
            f"compiled {cls.name}: max_depth values {truncated} exceed "
            f"the histogram grower's static bound — integers are capped "
            f"at {MAX_COMPILED_DEPTH} and None (sklearn: unbounded) "
            f"maps to the family default of {cls._default_depth}. The "
            f"fitted model can differ from sklearn's on deep data; "
            f"pass max_depth <= {MAX_COMPILED_DEPTH} for a faithful "
            f"compiled fit, or backend='host' for sklearn's exact "
            f"unbounded CART.",
            UserWarning, stacklevel=2)


def _depth(static, default):
    md = static.get("max_depth", default)
    return default if md is None else min(int(md), MAX_COMPILED_DEPTH)


class GradientBoostingRegressorFamily(Family):
    name = "gradient_boosting_regressor"
    is_classifier = False
    keyed_compatible = False   # consumes binned "codes", not raw "X"
    dynamic_params = {"learning_rate": np.float32,
                      "n_estimators": np.int32,
                      "subsample": np.float32}
    #: max_depth=None caps deeper than GBDT's usual 3
    _default_depth = 3
    #: sklearn's own ctor default (GradientBoosting*: max_depth=3)
    _sklearn_default_depth = 3

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        edges, codes = _prep_codes(X, dtype)
        y = np.asarray(y, dtype)
        data = {"codes": codes, "y": y}
        meta = {"n_features": int(X.shape[1]), "edges": edges,
                "max_estimators": None}
        return data, meta

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        _observe_tree_candidates(cls, candidates, base_params, meta)

    #: per-tree work is large (level histograms over all samples), so
    #: even small grids amortise the extra dispatches
    min_sort_candidates = 4

    @classmethod
    def convergence_proxy(cls, dynamic_params, static):
        """A launch's while_loop grows max-over-lanes(n_estimators)
        trees; sorting by n_estimators makes that max tight per
        launch."""
        return dynamic_params.get("n_estimators")

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        codes, y = data["codes"], data["y"]
        n = codes.shape[0]
        depth = _depth(static, cls._default_depth)
        t_max = int(meta.get("max_estimators")
                    or static.get("n_estimators", 100))
        lr = jnp.asarray(dynamic.get(
            "learning_rate", static.get("learning_rate", 0.1)), jnp.float32)
        n_est = jnp.asarray(dynamic.get(
            "n_estimators", static.get("n_estimators", 100)), jnp.int32)
        subsample = jnp.asarray(dynamic.get(
            "subsample", static.get("subsample", 1.0)), jnp.float32)
        min_leaf = float(static.get("min_samples_leaf", 1))
        key = jax.random.PRNGKey(_seed(static))

        wsum = jnp.sum(train_w) + 1e-12
        F0 = jnp.sum(train_w * y) / wsum
        F = jnp.full((n,), F0, jnp.float32)

        # while_loop with a per-lane trip count: a candidate stops
        # growing trees past ITS n_estimators (the stacked per-stage
        # trees were returned but never consumed — dropped, which also
        # cuts the model pytree by t_max tree buffers per lane)
        keys = jax.random.split(key, t_max)
        n_lim = jnp.minimum(n_est, t_max)

        def one_tree(carry):
            t, F = carry
            k_t = keys[t]
            g = (F - y)[:, None]                      # d(0.5(F-y)^2)/dF
            h = jnp.ones((n,), jnp.float32)
            w_t = train_w * (
                jax.random.uniform(k_t, (n,)) < subsample).astype(
                jnp.float32)
            tree = grow_tree(codes, g, h, w_t, depth, N_BINS,
                             min_child_weight=min_leaf, reg_lambda=1e-6)
            delta = predict_tree(tree, codes, depth)[:, 0]
            live = (t < n_est).astype(jnp.float32)
            return t + 1, F + lr * live * delta

        _, F = jax.lax.while_loop(
            lambda c: c[0] < n_lim, one_tree,
            (jnp.asarray(0, jnp.int32), F))
        return {"pred": F, "f0": F0, "lr": lr, "n_est": n_est,
                "n_iter": n_lim}

    @classmethod
    def predict(cls, model, static, X, meta):
        # the search scores on the training X: cached full-dataset preds
        return model["pred"]

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"n_features_in_": meta["n_features"]}


class GradientBoostingClassifierFamily(GradientBoostingRegressorFamily):
    name = "gradient_boosting_classifier"
    is_classifier = True
    #: sklearn's staged decision/proba arrays are float64 regardless of X
    proba_dtype_rule = "float64"

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        edges, codes = _prep_codes(X, dtype)
        classes, y_enc = encode_labels(y)
        k = len(classes)
        data = {"codes": codes, "y": y_enc,
                "y1h": np.eye(k, dtype=np.float32)[y_enc]}
        meta = {"n_features": int(X.shape[1]), "edges": edges,
                "n_classes": int(k), "classes": classes,
                "max_estimators": None}
        return data, meta

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        codes, y1h = data["codes"], data["y1h"]
        n = codes.shape[0]
        k = meta["n_classes"]
        depth = _depth(static, cls._default_depth)
        t_max = int(meta.get("max_estimators")
                    or static.get("n_estimators", 100))
        lr = jnp.asarray(dynamic.get(
            "learning_rate", static.get("learning_rate", 0.1)), jnp.float32)
        n_est = jnp.asarray(dynamic.get(
            "n_estimators", static.get("n_estimators", 100)), jnp.int32)
        subsample = jnp.asarray(dynamic.get(
            "subsample", static.get("subsample", 1.0)), jnp.float32)
        min_leaf = float(static.get("min_samples_leaf", 1))
        key = jax.random.PRNGKey(_seed(static))

        wsum = jnp.sum(train_w) + 1e-12
        prior = jnp.clip(
            (train_w[:, None] * y1h).sum(0) / wsum, 1e-6, 1 - 1e-6)
        F = jnp.broadcast_to(jnp.log(prior)[None, :], (n, k)).astype(
            jnp.float32) + jnp.zeros((n, k), jnp.float32)

        # per-lane trip count, as in the regressor (stacked stage trees
        # were never consumed — dropped)
        keys = jax.random.split(key, t_max)
        n_lim = jnp.minimum(n_est, t_max)

        def one_stage(carry):
            t, F = carry
            k_t = keys[t]
            P = jax.nn.softmax(F, axis=1)
            w_t = train_w * (
                jax.random.uniform(k_t, (n,)) < subsample).astype(
                jnp.float32)

            def per_class(g_c, h_c):
                return grow_tree(codes, g_c[:, None], h_c, w_t, depth,
                                 N_BINS, min_child_weight=min_leaf,
                                 reg_lambda=1e-6)

            G = (P - y1h)                              # (n, k)
            H = P * (1.0 - P)                          # (n, k)
            trees_k = jax.vmap(per_class, in_axes=(1, 1))(G, H)
            delta = jax.vmap(
                lambda tr: predict_tree(tr, codes, depth)[:, 0],
                in_axes=0, out_axes=1)(trees_k)        # (n, k)
            live = (t < n_est).astype(jnp.float32)
            return t + 1, F + lr * live * delta

        _, F = jax.lax.while_loop(
            lambda c: c[0] < n_lim, one_stage,
            (jnp.asarray(0, jnp.int32), F))
        return {"pred": jnp.argmax(F, axis=1).astype(jnp.int32),
                "logits": F, "n_est": n_est, "lr": lr, "n_iter": n_lim}

    @classmethod
    def predict(cls, model, static, X, meta):
        return model["pred"]

    @classmethod
    def decision(cls, model, static, X, meta):
        if meta["n_classes"] == 2:
            # scorer contract: binary decision is a 1-D margin
            return model["logits"][:, 1] - model["logits"][:, 0]
        return model["logits"]

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        return jax.nn.softmax(model["logits"], axis=1)


class RandomForestClassifierFamily(Family):
    name = "random_forest_classifier"
    is_classifier = True
    keyed_compatible = False   # consumes binned "codes", not raw "X"
    #: sklearn's vote-averaged probas are float64 regardless of X
    proba_dtype_rule = "float64"
    dynamic_params = {"n_estimators": np.int32}
    _default_depth = 10
    #: sklearn's own ctor default (RandomForest*: max_depth=None,
    #: i.e. unbounded — the compiled cap always applies, so a default
    #: forest search gets the fidelity warning)
    _sklearn_default_depth = None

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        edges, codes = _prep_codes(X, dtype)
        classes, y_enc = encode_labels(y)
        k = len(classes)
        data = {"codes": codes, "y": y_enc,
                "y1h": np.eye(k, dtype=np.float32)[y_enc]}
        meta = {"n_features": int(X.shape[1]), "edges": edges,
                "n_classes": int(k), "classes": classes,
                "max_estimators": None}
        return data, meta

    min_sort_candidates = 4
    convergence_proxy = GradientBoostingRegressorFamily.convergence_proxy

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        _observe_tree_candidates(cls, candidates, base_params, meta)

    @classmethod
    def _max_features(cls, static, d):
        mf = static.get("max_features", "sqrt")
        if mf in ("sqrt", "auto"):
            return max(1, int(np.sqrt(d)))
        if mf == "log2":
            return max(1, int(np.log2(d)))
        if mf is None:
            return d
        if isinstance(mf, float):
            return max(1, int(mf * d))
        return int(mf)

    @classmethod
    def _targets(cls, data):
        return data["y1h"]

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        codes = data["codes"]
        t = cls._targets(data)                          # (n, n_out)
        n, d = codes.shape
        n_out = t.shape[1]
        depth = _depth(static, cls._default_depth)
        t_max = int(meta.get("max_estimators")
                    or static.get("n_estimators", 100))
        n_est = jnp.asarray(dynamic.get(
            "n_estimators", static.get("n_estimators", 100)), jnp.int32)
        bootstrap = bool(static.get("bootstrap", True))
        min_leaf = float(static.get("min_samples_leaf", 1))
        mf = cls._max_features(static, d)
        key = jax.random.PRNGKey(_seed(static))

        # while_loop (not scan/vmap) over trees: level histograms are the
        # memory hot spot, one tree's workspace stays live — and the
        # per-lane trip count `i < n_est` means a candidate stops paying
        # for trees past ITS n_estimators (under vmap, jax's while
        # batching freezes finished lanes' carries; the launch runs the
        # max over its lanes, which convergence-sorted chunking makes
        # tight per launch instead of the grid maximum)
        keys = jax.random.split(key, t_max)
        n_lim = jnp.minimum(n_est, t_max)

        def one_tree(carry):
            ti, acc = carry
            k_t = keys[ti]
            if bootstrap:
                w_t = train_w * jax.random.poisson(
                    k_t, 1.0, (n,)).astype(jnp.float32)
            else:
                w_t = train_w
            # squared loss from F=0: grad = -target, hess = 1 -> leaf
            # value = weighted mean target (class distribution / mean y)
            tree = grow_tree(codes, -t, jnp.ones((n,), jnp.float32), w_t,
                             depth, N_BINS, min_child_weight=min_leaf,
                             reg_lambda=1e-9,
                             feat_mask_key=jax.random.fold_in(k_t, 7),
                             max_features=mf, n_out=n_out)
            pred = predict_tree(tree, codes, depth)     # (n, n_out)
            live = (ti < n_est).astype(jnp.float32)
            return ti + 1, acc + live * pred

        acc0 = jnp.zeros((n, n_out), jnp.float32)
        _, acc = jax.lax.while_loop(
            lambda c: c[0] < n_lim, one_tree,
            (jnp.asarray(0, jnp.int32), acc0))
        avg = acc / jnp.maximum(n_lim.astype(jnp.float32), 1.0)
        out = cls._finalize(avg)
        out["n_iter"] = n_lim   # executed trees, for launch accounting
        return out

    @classmethod
    def _finalize(cls, avg):
        return {"proba": avg,
                "pred": jnp.argmax(avg, axis=1).astype(jnp.int32)}

    @classmethod
    def predict(cls, model, static, X, meta):
        return model["pred"]

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        p = jnp.maximum(model["proba"], 0.0)
        return p / jnp.maximum(p.sum(axis=1, keepdims=True), 1e-12)

    @classmethod
    def decision(cls, model, static, X, meta):
        if meta.get("n_classes") == 2:
            # scorer contract: binary decision is a 1-D margin
            return model["proba"][:, 1] - model["proba"][:, 0]
        return model["proba"]

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"classes_": meta.get("classes"),
                "n_features_in_": meta["n_features"]}


class RandomForestRegressorFamily(RandomForestClassifierFamily):
    name = "random_forest_regressor"
    is_classifier = False

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        edges, codes = _prep_codes(X, dtype)
        y = np.asarray(y, dtype)
        data = {"codes": codes, "y": y,
                "y_target": y.reshape(len(y), 1)}
        meta = {"n_features": int(X.shape[1]), "edges": edges,
                "max_estimators": None}
        return data, meta

    @classmethod
    def _max_features(cls, static, d):
        mf = static.get("max_features", 1.0)   # sklearn regressor default
        if isinstance(mf, float) and mf == 1.0:
            return d                            # int 1 means ONE feature
        return RandomForestClassifierFamily._max_features.__func__(
            cls, static, d)

    @classmethod
    def _targets(cls, data):
        return data["y_target"]

    @classmethod
    def _finalize(cls, avg):
        return {"pred": avg[:, 0]}

    @classmethod
    def predict(cls, model, static, X, meta):
        return model["pred"]


register_family(
    GradientBoostingRegressorFamily,
    "sklearn.ensemble._gb.GradientBoostingRegressor",
    "sklearn.ensemble.GradientBoostingRegressor",
)
register_family(
    GradientBoostingClassifierFamily,
    "sklearn.ensemble._gb.GradientBoostingClassifier",
    "sklearn.ensemble.GradientBoostingClassifier",
)
register_family(
    RandomForestClassifierFamily,
    "sklearn.ensemble._forest.RandomForestClassifier",
    "sklearn.ensemble.RandomForestClassifier",
)
register_family(
    RandomForestRegressorFamily,
    "sklearn.ensemble._forest.RandomForestRegressor",
    "sklearn.ensemble.RandomForestRegressor",
)
