from spark_sklearn_tpu.models import linear  # noqa: F401 — registers families
from spark_sklearn_tpu.models import mlp  # noqa: F401 — registers families
from spark_sklearn_tpu.models import svm  # noqa: F401 — registers families
from spark_sklearn_tpu.models import svr  # noqa: F401 — registers families
from spark_sklearn_tpu.models import trees  # noqa: F401 — registers families
from spark_sklearn_tpu.models import cluster  # noqa: F401 — registers families
from spark_sklearn_tpu.models import discriminant  # noqa: F401 — registers families
from spark_sklearn_tpu.models import naive_bayes  # noqa: F401 — registers families
from spark_sklearn_tpu.models import neighbors  # noqa: F401 — registers families
from spark_sklearn_tpu.models.estimators import (  # noqa: F401
    ElasticNet,
    Lasso,
    LinearRegression,
    LogisticRegression,
    Ridge,
)
from spark_sklearn_tpu.models.standalone import (  # noqa: F401
    MLPClassifier,
    MLPRegressor,
    SVC,
)
