"""Preprocessing steps as pure, mask-weighted JAX functions.

The reference runs sklearn transformers unchanged inside each Spark task
(e.g. BASELINE config #5: Pipeline(StandardScaler + MLPClassifier) —
reference: grid_search.py fits the whole pipeline per task).  Under vmap a
transformer is a pair of pure functions with the fold expressed as a weight
mask — `fit_transform` statistics must be *weighted* statistics so each fold
sees only its training rows while shapes stay fixed:

    fit(static, X, w)          -> state pytree  (weighted stats)
    apply(static, state, X)    -> X'            (full-length transform)

These are deliberately tiny: XLA fuses them into the downstream matmuls, so
a pipeline costs nothing extra on TPU (no materialised intermediate the way
Spark materialises RDDs between stages).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

EPS = 1e-12


class StandardScalerStep:
    name = "standard_scaler"
    dynamic_params: dict = {}
    #: pure function of (static, X, fold mask): safe to hoist into a
    #: shared-prefix stage and reuse across suffix candidates
    prefix_safe = True
    #: strictly monotone per-feature map: quantile binning (and therefore
    #: histogram-tree fits) is provably invariant under this step
    monotone_per_feature = True

    @staticmethod
    def fit(static, X, w):
        wsum = jnp.sum(w) + EPS
        with_mean = bool(static.get("with_mean", True))
        with_std = bool(static.get("with_std", True))
        mean = (w @ X) / wsum
        # variance is always about the true mean (sklearn computes var_
        # even when with_mean=False); only the shift is disabled
        var = (w @ ((X - mean) ** 2)) / wsum
        scale = jnp.where(var > 0, jnp.sqrt(var), 1.0)
        if not with_std:
            scale = jnp.ones_like(scale)
        if not with_mean:
            mean = jnp.zeros_like(mean)
        return {"mean": mean, "scale": scale}

    @staticmethod
    def apply(static, state, X):
        return (X - state["mean"]) / state["scale"]


class MinMaxScalerStep:
    name = "minmax_scaler"
    dynamic_params: dict = {}
    #: pure function of (static, X, fold mask): safe to hoist into a
    #: shared-prefix stage and reuse across suffix candidates
    prefix_safe = True
    monotone_per_feature = True

    @staticmethod
    def fit(static, X, w):
        big = jnp.asarray(jnp.finfo(X.dtype).max, X.dtype)
        masked_min = jnp.min(jnp.where(w[:, None] > 0, X, big), axis=0)
        masked_max = jnp.max(jnp.where(w[:, None] > 0, X, -big), axis=0)
        lo, hi = static.get("feature_range", (0.0, 1.0))
        span = masked_max - masked_min
        scale = jnp.where(span > 0, (hi - lo) / span, 1.0)
        return {"min": masked_min, "scale": scale, "lo": lo}

    @staticmethod
    def apply(static, state, X):
        out = (X - state["min"]) * state["scale"] + state["lo"]
        if static.get("clip", False):
            lo, hi = static.get("feature_range", (0.0, 1.0))
            out = jnp.clip(out, lo, hi)
        return out


class MaxAbsScalerStep:
    name = "maxabs_scaler"
    dynamic_params: dict = {}
    #: pure function of (static, X, fold mask): safe to hoist into a
    #: shared-prefix stage and reuse across suffix candidates
    prefix_safe = True
    # |x|-scaling by a positive constant: monotone per feature
    monotone_per_feature = True

    @staticmethod
    def fit(static, X, w):
        m = jnp.max(jnp.abs(X) * (w[:, None] > 0), axis=0)
        return {"scale": jnp.where(m > 0, m, 1.0)}

    @staticmethod
    def apply(static, state, X):
        return X / state["scale"]


class NormalizerStep:
    """Stateless per-row normalisation (norm in l1/l2/max)."""

    name = "normalizer"
    dynamic_params: dict = {}
    #: pure function of (static, X, fold mask): safe to hoist into a
    #: shared-prefix stage and reuse across suffix candidates
    prefix_safe = True
    monotone_per_feature = False   # row-wise, mixes features

    @staticmethod
    def fit(static, X, w):
        return {}

    @staticmethod
    def apply(static, state, X):
        norm = static.get("norm", "l2")
        if norm == "l1":
            d = jnp.sum(jnp.abs(X), axis=1, keepdims=True)
        elif norm == "max":
            d = jnp.max(jnp.abs(X), axis=1, keepdims=True)
        else:
            d = jnp.linalg.norm(X, axis=1, keepdims=True)
        return X / jnp.maximum(d, EPS)


class PCAStep:
    """Weighted PCA via eigendecomposition of the fold-weighted covariance
    (n_components is static — it changes the transformed width).

    Matches sklearn's PCA(svd_solver='full') up to component sign on the
    training fold; whitening supported.  Randomized/arpack solvers and
    n_components='mle' are not compiled (fit raises -> host fallback).
    """

    name = "pca"
    dynamic_params: dict = {}
    #: pure function of (static, X, fold mask): safe to hoist into a
    #: shared-prefix stage and reuse across suffix candidates
    prefix_safe = True
    monotone_per_feature = False   # rotation, mixes features

    @staticmethod
    def min_group_size(static) -> int:
        """A PCA fit needs at least n_components rows (keyed-fleet
        eligibility hook, mirroring Family.min_group_size)."""
        nc = static.get("n_components")
        if isinstance(nc, (int, np.integer)) and not isinstance(nc, bool):
            return max(1, int(nc))
        return 1

    @staticmethod
    def check_static(static, n_features=None):
        """Raise ValueError for configs the compiled path cannot serve
        (callers probe this BEFORE launching so designed host fallbacks
        stay silent; fit also calls it so trace-time misuse still fails).

        sklearn raises for n_components outside [0, min(n_samples,
        n_features)]; a silent evecs[:, :nc] truncation would diverge
        from the host-fitted keys in a hybrid fleet.
        """
        nc = static.get("n_components")
        if nc is None or isinstance(nc, bool) or \
                not isinstance(nc, (int, np.integer)):
            raise ValueError(
                "PCA needs an integer n_components on the compiled path")
        if nc < 0:
            raise ValueError(f"n_components={nc} must be >= 0")
        if n_features is not None and nc > n_features:
            raise ValueError(
                f"n_components={nc} must be <= n_features={n_features}")
        if static.get("svd_solver", "auto") not in ("auto", "full",
                                                    "covariance_eigh"):
            raise ValueError("only full-SVD PCA is compiled")

    @staticmethod
    def fit(static, X, w):
        PCAStep.check_static(static, X.shape[1])
        nc = int(static["n_components"])
        wsum = jnp.sum(w) + EPS
        mean = (w @ X) / wsum
        Xc = X - mean
        cov = (Xc * w[:, None]).T @ Xc / wsum          # (d, d)
        evals, evecs = jnp.linalg.eigh(cov)            # ascending
        # top-nc components, descending eigenvalue order
        comps = evecs[:, ::-1][:, :nc].T               # (nc, d)
        var = jnp.maximum(evals[::-1][:nc], 0.0)
        return {"mean": mean, "components": comps, "var": var}

    @staticmethod
    def apply(static, state, X):
        Z = (X - state["mean"]) @ state["components"].T
        if static.get("whiten", False):
            Z = Z / jnp.sqrt(state["var"] + EPS)[None, :]
        return Z


#: sklearn transformer class name -> step implementation
STEP_REGISTRY = {
    "StandardScaler": StandardScalerStep,
    "MinMaxScaler": MinMaxScalerStep,
    "MaxAbsScaler": MaxAbsScalerStep,
    "Normalizer": NormalizerStep,
    "PCA": PCAStep,
}


def resolve_step(transformer) -> object | None:
    # sklearn classes only — a third-party class merely NAMED StandardScaler
    # must not silently get the compiled transform (same guard as
    # base.resolve_family)
    if not type(transformer).__module__.startswith("sklearn."):
        return None
    return STEP_REGISTRY.get(type(transformer).__name__)
