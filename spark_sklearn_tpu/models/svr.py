"""SVR / LinearSVC / LinearSVR families — the remaining libsvm/liblinear
estimators, re-designed for the MXU.

Reference counterpart: sklearn's SVR/LinearSVC/LinearSVR run unchanged as
host Python inside Spark tasks (reference: grid_search.py -> sklearn
_fit_and_score).  The TPU redesign:

- SVR solves the epsilon-SVR dual with the SAME box-and-hyperplane
  projected ascent as SVC (models/svm.py): the paired variables
  u = (a, a*) live in one (M, 2n) row per subproblem, the signs
  s = (+1...,-1...) take the role SVC's labels play in the equality
  constraint sum(a - a*) = 0, and the tiled kernel [[K,K],[K,K]] acts
  through ONE (M, n) @ (n, n) matmul per iteration (its top eigenvalue is
  2*lambda_max(K), so SVC's power-iteration step halves).
- LinearSVC/LinearSVR solve liblinear's smooth PRIMAL losses
  (squared_hinge / squared_epsilon_insensitive) with the same batched
  L-BFGS engine as logistic regression (ops/solvers.glm_lbfgs_batched),
  and the nonsmooth losses (hinge / epsilon_insensitive) through their
  box-constrained DUAL QPs with accelerated projected gradient
  (`_box_fista`) — the TPU answer to liblinear's sequential dual
  coordinate descent; all (candidate x fold) tasks advance as one wide
  matmul either way.  liblinear's augmented-column intercept convention
  (intercept_scaling, intercept REGULARISED) is reproduced exactly.
  crammer_singer and penalty='l1' raise -> the search falls back to the
  host tier, matching sklearn bit-for-bit there.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, register_family
from spark_sklearn_tpu.models.svm import (
    _box_fista,
    _kernel,
    _masked_mean_or_mid,
    _power_step,
    _project_box_hyperplane,
    _project_box_sum,
    _resolve_gamma,
    _run_dual,
    _tol_or_default,
)


def svr_dual_ascent(K, y, eps, bound_half, step, max_iter, tol=None):
    """Nesterov-accelerated projected ascent on the epsilon-SVR dual

        max_{a,a*}  -0.5 (a-a*)' K (a-a*) - eps 1'(a+a*) + y'(a-a*)
        0 <= a_i, a*_i <= C_i,   sum_i (a_i - a*_i) = 0

    in the stacked form u = (a, a*) with signs s = (+1^n, -1^n): the
    equality is sum(s*u) = 0 (SVC's hyperplane with s for labels) and the
    quadratic acts through beta = a - a* so each iteration is one
    (M, n) @ (n, n) matmul.  bound_half: (M, n) per-sample C (fold-masked,
    sample-weight-scaled); applies to both halves.  `tol` enables the
    per-lane prox-residual exit on the stacked (a, a*) iterate — the
    batched analog of libsvm's eps rule for epsilon-SVR (sklearn SVR
    tol, default 1e-3), same machinery SVC's duals got in round 4.
    Returns (beta, b, n_iter)."""
    M, n = bound_half.shape
    dtype = K.dtype
    s = jnp.concatenate([jnp.ones((n,), dtype), -jnp.ones((n,), dtype)])
    lin = s * jnp.concatenate([y, y]) - eps            # (2n,) per-element
    bound = jnp.concatenate([bound_half, bound_half], axis=1)   # (M, 2n)

    def grad(Z):                       # descent form of the ascent grad
        beta = (Z * s).reshape(M, 2, n).sum(axis=1)    # a - a*  (M, n)
        V_half = beta @ K                              # (M, n)
        V = jnp.concatenate([V_half, V_half], axis=1)  # (M, 2n)
        return -(lin - s * V)

    U, n_it = _run_dual(
        grad, lambda Zt: _project_box_hyperplane(Zt, s[None, :], bound),
        jnp.zeros_like(bound), step, max_iter, tol, dtype)
    beta = (U * s).reshape(M, 2, n).sum(axis=1)
    return beta, _svr_intercept(K, U, beta, y, eps, bound_half), n_it


def _svr_intercept(K, U, beta, y, eps, bound_half):
    """KKT intercept (libsvm's -rho for epsilon-SVR): over free SVs,
    y - f0 - b = +eps for 0 < a < C and -eps for 0 < a* < C; when nothing
    is free, the midpoint of the feasible [max lower, min upper] interval
    from the at-bound conditions."""
    M, n = bound_half.shape
    f0 = beta @ K                                       # (M, n)
    E = y[None, :] - f0
    a = U[:, :n]
    a_star = U[:, n:]
    inb = bound_half > 0
    tol_lo = bound_half * 1e-6
    tol_hi = bound_half * (1.0 - 1e-6)
    free_a = inb & (a > tol_lo) & (a < tol_hi)
    free_as = inb & (a_star > tol_lo) & (a_star < tol_hi)
    nfree = jnp.sum(free_a, axis=1) + jnp.sum(free_as, axis=1)
    b_free = (jnp.sum(jnp.where(free_a, E - eps, 0.0), axis=1)
              + jnp.sum(jnp.where(free_as, E + eps, 0.0), axis=1)) \
        / jnp.maximum(nfree, 1)
    # at-bound conditions: a=0 -> b >= E-eps; a*=C -> b >= E+eps;
    #                      a=C -> b <= E-eps; a*=0 -> b <= E+eps
    big = jnp.asarray(jnp.inf, E.dtype)
    lb = jnp.maximum(
        jnp.max(jnp.where(inb & (a <= tol_lo), E - eps, -big), axis=1),
        jnp.max(jnp.where(inb & (a_star >= tol_hi), E + eps, -big), axis=1))
    ub = jnp.minimum(
        jnp.min(jnp.where(inb & (a >= tol_hi), E - eps, big), axis=1),
        jnp.min(jnp.where(inb & (a_star <= tol_lo), E + eps, big), axis=1))
    b_mid = 0.5 * (lb + ub)
    b_mid = jnp.where(jnp.isfinite(b_mid), b_mid,
                      jnp.where(jnp.isfinite(lb), lb,
                                jnp.where(jnp.isfinite(ub), ub, 0.0)))
    return jnp.where(nfree > 0, b_free, b_mid)


def nu_svr_dual_ascent(K, y, nu, bound_half, step, max_iter, tol=None):
    """libsvm's nu-SVR dual (solve_nu_svr): stacked u = (a, a*) with
    per-element box C (already folded into `bound_half` by the caller,
    fold/sample-weight-scaled), sum over EACH half = C*nu*l/2 — i.e.
    nu/2 of the half's total box capacity, which keeps the libsvm value
    under fold masks and sample weights — and no epsilon in the
    objective: the tube width is implicit, recovered from the KKT
    conditions together with b.  Always feasible for nu in (0, 1].
    `tol` enables the per-lane residual exit (libsvm eps rule); returns
    (f, n_iter)."""
    M, n = bound_half.shape
    dtype = K.dtype
    s = jnp.concatenate([jnp.ones((n,), dtype), -jnp.ones((n,), dtype)])
    lin = s * jnp.concatenate([y, y])
    zero = jnp.zeros_like(bound_half)
    pos_b = jnp.concatenate([bound_half, zero], axis=1)       # (M, 2n)
    neg_b = jnp.concatenate([zero, bound_half], axis=1)
    cap = jnp.sum(bound_half, axis=1)
    target = jnp.broadcast_to(0.5 * nu * cap, (M,))
    feasible = target <= cap * (1.0 + 1e-6)

    def project(Zt):
        return _project_box_sum(Zt, pos_b, target) + \
            _project_box_sum(Zt, neg_b, target)

    def grad(Z):                       # descent form of the ascent grad
        beta = (Z * s).reshape(M, 2, n).sum(axis=1)
        V_half = beta @ K
        V = jnp.concatenate([V_half, V_half], axis=1)
        return -(lin - s * V)

    U, n_it = _run_dual(grad, project,
                        project(jnp.zeros((M, 2 * n), dtype)),
                        step, max_iter, tol, dtype)
    beta = (U * s).reshape(M, 2, n).sum(axis=1)
    # KKT: free a  -> y - f0 - b = +eps  (E estimates b + eps)
    #      free a* -> y - f0 - b = -eps  (E estimates b - eps)
    E = y[None, :] - beta @ K
    a, a_star = U[:, :n], U[:, n:]
    inb = bound_half > 0
    t_lo = bound_half * 1e-6
    t_hi = bound_half * (1.0 - 1e-6)
    free_a = inb & (a > t_lo) & (a < t_hi)
    free_as = inb & (a_star > t_lo) & (a_star < t_hi)
    # bound directions (cf. _svr_intercept's at-bound table): a=0 rows
    # LOWER-bound b+eps, a=C rows upper-bound it; a*=C rows LOWER-bound
    # b-eps, a*=0 rows upper-bound it
    m_a = _masked_mean_or_mid(E, free_a, inb & (a <= t_lo),
                              inb & (a >= t_hi))
    m_as = _masked_mean_or_mid(E, free_as, inb & (a_star >= t_hi),
                               inb & (a_star <= t_lo))
    b = 0.5 * (m_a + m_as)
    f = beta @ K + b[:, None]
    return jnp.where(feasible[:, None], f, jnp.nan), n_it


class SVRFamily(Family):
    name = "svr"
    is_classifier = False
    dynamic_params = {"C": np.float32, "gamma": np.float32,
                      "epsilon": np.float32}
    #: the third per-candidate scalar next to C/gamma (NuSVR swaps in nu)
    aux_param = "epsilon"
    aux_default = 0.1
    # task-batched only (like SVC): the keyed fleet and per-task callers
    # skip it via has_per_task_fit(); keyed_compatible stays True so
    # make_pipeline_family composes it as a fold-input final, NOT as a
    # binned-invariant tree final
    task_batched_accepts_fold_inputs = True

    @classmethod
    def _fold_dual(cls, K, y, C_c, aux_c, w_rows, step, max_iter,
                   tol=None):
        """Solve the fold subproblems for one candidate; returns ((F, n)
        full-set regression values, executed iterations).  `aux_c` is
        epsilon here; `tol` enables the per-candidate residual exit."""
        bound = C_c * w_rows
        beta, b, n_it = svr_dual_ascent(
            K, y, aux_c, bound, step, max_iter, tol)
        return beta @ K + b[:, None], n_it

    @staticmethod
    def max_tasks_hint(n_samples: int, meta) -> int:
        budget = 1 << 30
        return max(1, budget // max(1, n_samples * 8))

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        data = {"X": np.ascontiguousarray(X, dtype=dtype),
                "y": np.ascontiguousarray(y, dtype=dtype)}
        meta = {"n_features": int(X.shape[1]),
                "x_var": float(np.var(np.asarray(X)))}
        return data, meta

    @classmethod
    def fit_task_batched(cls, dynamic, static, data, train_w, meta):
        """Candidate-major tasks (task t = (cand t//F, fold t%F)); one
        kernel per candidate shared by its F fold subproblems.  Caches the
        full-dataset regression values f(x) per task (the search scores on
        masked rows, so predict never rebuilds kernels)."""
        X, y = data["X"], data["y"]
        n, d = X.shape
        B = train_w.shape[0]
        kind = static.get("kernel", "rbf")
        if kind == "precomputed":
            raise ValueError("precomputed kernels: use backend='host'")
        degree = float(static.get("degree", 3))
        coef0 = float(static.get("coef0", 0.0))
        max_iter = int(static.get("max_iter", -1))
        if max_iter in (-1, 0):
            max_iter = 300
        # libsvm's eps stopping rule (sklearn SVR tol, default 1e-3):
        # each candidate's paired (a, a*) dual exits at ITS convergence
        # inside the per-candidate scan — the same per-candidate tol
        # exit SVC's pair duals got in round 4 (VERDICT r4 next #2)
        tol_exit = _tol_or_default(static)
        n_folds = int(static.get("__n_folds__", 0))
        if n_folds <= 0:
            raise ValueError("engine must pass __n_folds__ for SVR")
        nc = B // n_folds

        gamma_default = _resolve_gamma(static.get("gamma", "scale"), meta)
        ap = cls.aux_param
        C_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get("C", static.get("C", 1.0)), X.dtype), (B,))
        g_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get("gamma", gamma_default), X.dtype), (B,))
        e_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get(ap, static.get(ap, cls.aux_default)),
            X.dtype), (B,))
        C_cand = C_task.reshape(nc, n_folds)[:, 0]
        g_cand = g_task.reshape(nc, n_folds)[:, 0]
        e_cand = e_task.reshape(nc, n_folds)[:, 0]
        w_cand = train_w.reshape(nc, n_folds, n)

        X_folds = data.get("X_folds")      # (F, n, d) pipeline mode
        gamma_is_scale = "gamma" not in dynamic and \
            static.get("gamma", "scale") == "scale"

        def one_candidate(carry, inp):
            C_c, g_c, e_c, w_f = inp
            if X_folds is None:
                K = _kernel(X, X, kind, g_c, degree, coef0)
                step = 0.5 * _power_step(K, n, X.dtype)   # lam_max doubles
                f, it = cls._fold_dual(
                    K, y, C_c, e_c, w_f, step, max_iter, tol_exit)
            else:
                def per_fold(Xf, w_row):
                    if gamma_is_scale:
                        mrow = (w_row > 0).astype(Xf.dtype)
                        cnt = jnp.sum(mrow) * Xf.shape[1] + 1e-12
                        mu = jnp.sum(Xf * mrow[:, None]) / cnt
                        var = jnp.sum(((Xf - mu) ** 2)
                                      * mrow[:, None]) / cnt
                        g_f = 1.0 / (Xf.shape[1]
                                     * jnp.maximum(var, 1e-12))
                    else:
                        g_f = g_c
                    Kf = _kernel(Xf, Xf, kind, g_f, degree, coef0)
                    step = 0.5 * _power_step(Kf, n, Xf.dtype)
                    ff, itf = cls._fold_dual(
                        Kf, y, C_c, e_c, w_row[None, :], step,
                        max_iter, tol_exit)
                    return ff[0], itf

                f, its = jax.vmap(per_fold)(X_folds, w_f)  # (F, n), (F,)
                it = jnp.max(its)
            return carry, (f, it)

        _, (fs, its) = jax.lax.scan(
            one_candidate, 0.0, (C_cand, g_cand, e_cand, w_cand))
        # per-candidate executed dual iterations repeat across the fold
        # axis for the engine's per-launch accounting (same layout as SVC)
        return {"f": fs.reshape(B, n),
                "n_iter": jnp.repeat(its, n_folds)}

    @classmethod
    def predict(cls, model, static, X, meta):
        return model["f"]

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"n_features_in_": meta["n_features"]}


# ----------------------------------------------------------------------------
# liblinear primal + dual families
# ----------------------------------------------------------------------------

def _check_linear_svc_static(static):
    if static.get("penalty", "l2") != "l2":
        raise ValueError("penalty='l1' is not compiled; use backend='host'")
    if static.get("loss", "squared_hinge") not in (
            "squared_hinge", "hinge"):
        raise ValueError(
            f"loss={static.get('loss')!r} is not compiled; use "
            "backend='host'")
    if static.get("multi_class", "ovr") != "ovr":
        raise ValueError(
            "multi_class='crammer_singer' is not compiled; use "
            "backend='host'")


def _gram_step(Xa, dtype):
    """1 / lambda_max(Xa Xa^T) via power iteration through the factored
    Gram (never materialised: two (n, da) matmuls per step)."""
    n = Xa.shape[0]
    v = jnp.ones((n,), dtype) / jnp.sqrt(n)

    def power(i, v):
        u = Xa @ (v @ Xa)
        return u / (jnp.linalg.norm(u) + 1e-30)

    v = jax.lax.fori_loop(0, 20, power, v)
    lam = jnp.dot(v, Xa @ (v @ Xa)) + 1e-6
    return 1.0 / lam


class LinearSVCFamily(Family):
    """liblinear's L2-regularised squared-hinge primal, one-vs-rest.

    liblinear regularises the intercept via the appended
    intercept_scaling column — reproduced exactly (coef dimension d+1,
    all penalised), so scores track sklearn's LinearSVC, not a
    hand-rolled unpenalised-intercept variant.
    """

    name = "linear_svc"
    is_classifier = True
    dynamic_params = {"C": np.float32, "tol": np.float32}

    min_sort_candidates = 32

    @classmethod
    def convergence_proxy(cls, dynamic_params, static):
        """Larger C = weaker regularisation = slower convergence (both
        the hinge dual's residual exit and the squared-hinge primal's
        L-BFGS stall exit fire sooner at small C) — sorted chunking
        lets the easy launches retire early."""
        return dynamic_params.get("C")

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        from spark_sklearn_tpu.models.base import encode_labels
        classes, y_enc = encode_labels(y)
        data = {
            "X": np.ascontiguousarray(X, dtype=dtype),
            "y": y_enc,
            "y1h": np.eye(len(classes), dtype=dtype)[y_enc],
        }
        meta = {"n_classes": int(len(classes)), "classes": classes,
                "n_features": int(X.shape[1])}
        return data, meta

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        model = cls.fit_task_batched(
            {k: jnp.asarray(v)[None] for k, v in dynamic.items()},
            static, data, train_w[None, :], meta)
        return jax.tree_util.tree_map(lambda a: a[0], model)

    @classmethod
    def fit_task_batched(cls, dynamic, static, data, train_w, meta):
        from spark_sklearn_tpu.ops.solvers import glm_lbfgs_batched

        _check_linear_svc_static(static)
        X = data["X"]
        n, d = X.shape
        k = meta["n_classes"]
        ko = 1 if k == 2 else k          # liblinear: one machine for binary
        B = train_w.shape[0]
        C = jnp.broadcast_to(jnp.asarray(
            dynamic.get("C", static.get("C", 1.0)), X.dtype), (B,))
        tol = jnp.broadcast_to(jnp.asarray(
            dynamic.get("tol", static.get("tol", 1e-4)), X.dtype), (B,))
        max_iter = int(static.get("max_iter", 1000))
        fit_intercept = bool(static.get("fit_intercept", True))
        isc = float(static.get("intercept_scaling", 1.0))

        from spark_sklearn_tpu.models.base import apply_class_weight
        train_w = apply_class_weight(
            train_w, data["y"], meta, static.get("class_weight"))

        # liblinear intercept: an appended constant column, REGULARISED
        Xa = jnp.concatenate(
            [X, jnp.full((n, 1), isc, X.dtype)], axis=1) if fit_intercept \
            else X
        da = Xa.shape[1]
        # targets in {-1, +1}: OvR per class; binary = one machine for
        # classes_[1]
        if k == 2:
            T = (2.0 * data["y"].astype(X.dtype) - 1.0)[:, None]  # (n, 1)
        else:
            T = 2.0 * data["y1h"] - 1.0                           # (n, k)
        wT = train_w.T                                            # (n, B)

        if static.get("loss", "squared_hinge") == "hinge":
            # liblinear's l1-loss dual per OvR machine m:
            #   min_a 0.5 a'Q a - 1'a,  0 <= a_i <= C * w_i,
            #   Q = diag(t) Xa Xa' diag(t)  (same spectrum as the Gram)
            # No equality constraint — the intercept is the regularised
            # appended column, exactly liblinear.  Solved by accelerated
            # projected gradient; the coordinate-descent answer is the
            # same optimum (the dual is a strictly convex QP on a box).
            step = _gram_step(Xa, X.dtype)
            Tt = T.T[None, :, :]                       # (1, ko, n)
            bound = (C[:, None, None]
                     * train_w[:, None, :])            # (B, 1->ko, n)

            def grad(a):                               # a (B, ko, n)
                v = jnp.einsum("bkn,nd->bkd", a * Tt, Xa)
                q = jnp.einsum("bkd,nd->bkn", v, Xa) * Tt
                return q - 1.0

            def project(a):
                return jnp.clip(a, 0.0, bound)

            a0 = jnp.zeros((B, ko, n), X.dtype)
            a, n_iter, converged = _box_fista(
                grad, project, a0, step, max_iter, tol=tol)
            W = jnp.einsum("bkn,nd->bkd", a * Tt, Xa)  # (B, ko, da)
            if fit_intercept:
                coef, intercept = W[:, :, :d], W[:, :, d] * isc
            else:
                coef = W
                intercept = jnp.zeros((B, ko), X.dtype)
            return {"coef": coef, "intercept": intercept,
                    "converged": converged, "n_iter": n_iter}

        def Ax(x):                                    # (B, da*ko) -> Z
            W = x.reshape(B, ko, da)
            return jnp.einsum("nd,bkd->nbk", Xa, W)

        def data_loss(Z):
            r = jnp.maximum(0.0, 1.0 - T[:, None, :] * Z)
            return C * jnp.sum(wT[:, :, None] * r * r, axis=(0, 2))

        def data_grad(Z):
            r = jnp.maximum(0.0, 1.0 - T[:, None, :] * Z)
            return C[None, :, None] * wT[:, :, None] \
                * (-2.0 * T[:, None, :] * r)

        def AT(G):
            return jnp.einsum("nbk,nd->bkd", G, Xa).reshape(B, ko * da)

        def reg_loss(x):
            return 0.5 * jnp.sum(x * x, axis=1)

        def reg_grad(x):
            return x

        res = glm_lbfgs_batched(
            Ax, data_loss, data_grad, AT, reg_loss, reg_grad,
            jnp.zeros((B, ko * da), X.dtype), max_iter=max_iter, tol=tol)
        W = res.x.reshape(B, ko, da)
        if fit_intercept:
            coef = W[:, :, :d]
            intercept = W[:, :, d] * isc
        else:
            coef = W
            intercept = jnp.zeros((B, ko), X.dtype)
        return {"coef": coef, "intercept": intercept,
                "converged": res.converged, "n_iter": res.n_iter}

    @classmethod
    def decision(cls, model, static, X, meta):
        Z = X @ jnp.swapaxes(model["coef"], -1, -2) + model["intercept"]
        if meta["n_classes"] == 2:
            return Z[..., 0]
        return Z

    @classmethod
    def predict(cls, model, static, X, meta):
        Z = cls.decision(model, static, X, meta)
        if meta["n_classes"] == 2:
            return (Z > 0).astype(jnp.int32)
        return jnp.argmax(Z, axis=-1).astype(jnp.int32)

    @classmethod
    def views_task_batched(cls, models, static, data, meta, needed):
        """Scorer views for all T tasks from one wide `X @ W_all^T`
        matmul (coef (T, ko, d) — the ovr/binary twin of the GLM
        family's wide scoring layout)."""
        X = data["X"]
        n = X.shape[0]
        W = models["coef"]                                 # (T, ko, d)
        b = models["intercept"]                            # (T, ko)
        T, ko, d = W.shape
        Z = jnp.matmul(X, W.reshape(T * ko, d).T,
                       preferred_element_type=X.dtype)
        Z = jnp.moveaxis(Z.reshape(n, T, ko) + b[None], 0, 1)  # (T, n, ko)
        z = Z[:, :, 0] if meta["n_classes"] == 2 else Z
        views = {}
        if "decision" in needed:
            views["decision"] = z
        if "pred" in needed:
            views["pred"] = (z > 0).astype(jnp.int32) \
                if meta["n_classes"] == 2 \
                else jnp.argmax(Z, axis=-1).astype(jnp.int32)
        return views

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {
            "coef_": np.asarray(model["coef"]),
            "intercept_": np.asarray(model["intercept"]),
            "classes_": meta["classes"],
            "n_features_in_": meta["n_features"],
            "n_iter_": int(np.asarray(model["n_iter"]))
            if "n_iter" in model else None,
        }


class LinearSVRFamily(Family):
    """liblinear's squared-epsilon-insensitive primal (LinearSVR with
    loss='squared_epsilon_insensitive'; the nonsmooth default
    'epsilon_insensitive' raises -> host tier).  Same regularised
    appended-column intercept convention as LinearSVC."""

    name = "linear_svr"
    is_classifier = False
    dynamic_params = {"C": np.float32, "tol": np.float32,
                      "epsilon": np.float32}

    min_sort_candidates = 32
    convergence_proxy = LinearSVCFamily.convergence_proxy

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        data = {"X": np.ascontiguousarray(X, dtype=dtype),
                "y": np.ascontiguousarray(y, dtype=dtype)}
        meta = {"n_features": int(X.shape[1])}
        return data, meta

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        model = cls.fit_task_batched(
            {k: jnp.asarray(v)[None] for k, v in dynamic.items()},
            static, data, train_w[None, :], meta)
        return jax.tree_util.tree_map(lambda a: a[0], model)

    @classmethod
    def fit_task_batched(cls, dynamic, static, data, train_w, meta):
        from spark_sklearn_tpu.ops.solvers import glm_lbfgs_batched

        loss = static.get("loss", "epsilon_insensitive")
        if loss not in ("epsilon_insensitive",
                        "squared_epsilon_insensitive"):
            raise ValueError(f"loss={loss!r} is not compiled")
        X, y = data["X"], data["y"]
        n, d = X.shape
        B = train_w.shape[0]
        C = jnp.broadcast_to(jnp.asarray(
            dynamic.get("C", static.get("C", 1.0)), X.dtype), (B,))
        eps_t = jnp.broadcast_to(jnp.asarray(
            dynamic.get("epsilon", static.get("epsilon", 0.0)),
            X.dtype), (B,))
        tol = jnp.broadcast_to(jnp.asarray(
            dynamic.get("tol", static.get("tol", 1e-4)), X.dtype), (B,))
        max_iter = int(static.get("max_iter", 1000))
        fit_intercept = bool(static.get("fit_intercept", True))
        isc = float(static.get("intercept_scaling", 1.0))

        Xa = jnp.concatenate(
            [X, jnp.full((n, 1), isc, X.dtype)], axis=1) if fit_intercept \
            else X
        da = Xa.shape[1]
        wT = train_w.T                                  # (n, B)

        if loss == "epsilon_insensitive":
            # liblinear's l1-loss dual in beta = a - a*: since a_i a*_i = 0
            # at the optimum, the paired dual collapses to
            #   min_b 0.5 b'(Xa Xa')b - y'b + eps*|b|_1,  |b_i| <= C*w_i
            # — a box-constrained lasso QP whose prox is soft-threshold
            # then clip (the box is symmetric/separable).  The intercept
            # is the regularised appended column, exactly liblinear.
            step = _gram_step(Xa, X.dtype)
            bound = C[:, None] * train_w                # (B, n)

            def grad(b):                                # (B, n)
                return (b @ Xa) @ Xa.T - y[None, :]

            def project(b):
                s = jnp.sign(b) * jnp.maximum(
                    jnp.abs(b) - step * eps_t[:, None], 0.0)
                return jnp.clip(s, -bound, bound)

            beta, n_iter, converged = _box_fista(
                grad, project, jnp.zeros((B, n), X.dtype), step, max_iter,
                tol=tol)
            Wd = beta @ Xa                              # (B, da)
            if fit_intercept:
                coef, intercept = Wd[:, :d], Wd[:, d] * isc
            else:
                coef = Wd
                intercept = jnp.zeros((B,), X.dtype)
            return {"coef": coef, "intercept": intercept,
                    "converged": converged, "n_iter": n_iter}

        def Ax(x):                                      # (B, da) -> (n, B)
            return Xa @ x.T

        def data_loss(Z):
            r = jnp.maximum(0.0, jnp.abs(Z - y[:, None]) - eps_t[None, :])
            return C * jnp.sum(wT * r * r, axis=0)

        def data_grad(Z):
            e = Z - y[:, None]
            r = jnp.maximum(0.0, jnp.abs(e) - eps_t[None, :])
            return C[None, :] * wT * 2.0 * jnp.sign(e) * r

        def AT(G):
            return G.T @ Xa

        res = glm_lbfgs_batched(
            Ax, data_loss, data_grad, AT,
            lambda x: 0.5 * jnp.sum(x * x, axis=1), lambda x: x,
            jnp.zeros((B, da), X.dtype), max_iter=max_iter, tol=tol)
        if fit_intercept:
            coef = res.x[:, :d]
            intercept = res.x[:, d] * isc
        else:
            coef = res.x
            intercept = jnp.zeros((B,), X.dtype)
        return {"coef": coef, "intercept": intercept,
                "converged": res.converged, "n_iter": res.n_iter}

    @classmethod
    def predict(cls, model, static, X, meta):
        return X @ model["coef"] + model["intercept"]

    @classmethod
    def views_task_batched(cls, models, static, data, meta, needed):
        """All T tasks' predictions as ONE (n, d) @ (d, T) matmul."""
        if "pred" not in needed:
            return {}
        X = data["X"]
        pred = jnp.matmul(X, models["coef"].T,
                          preferred_element_type=X.dtype)   # (n, T)
        return {"pred": (pred + models["intercept"][None]).T}

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"coef_": np.asarray(model["coef"]),
                "intercept_": np.asarray(model["intercept"]),
                "n_features_in_": meta["n_features"]}


class NuSVRFamily(SVRFamily):
    """nu-SVR: SVR's kernel scaffold with libsvm's nu dual — per-sample
    box C (solve_nu_svr's convention), per-half sum C*nu*l/2, epsilon
    implicit (recovered with b from the free-SV KKT conditions in
    `nu_svr_dual_ascent`)."""

    name = "nu_svr"
    dynamic_params = {"C": np.float32, "gamma": np.float32,
                      "nu": np.float32}
    aux_param = "nu"
    aux_default = 0.5

    @classmethod
    def _fold_dual(cls, K, y, C_c, aux_c, w_rows, step, max_iter,
                   tol=None):
        return nu_svr_dual_ascent(
            K, y, aux_c, C_c * w_rows, step, max_iter, tol)


register_family(
    SVRFamily,
    "sklearn.svm._classes.SVR",
    "sklearn.svm.SVR",
)
register_family(
    NuSVRFamily,
    "sklearn.svm._classes.NuSVR",
    "sklearn.svm.NuSVR",
)
register_family(
    LinearSVCFamily,
    "sklearn.svm._classes.LinearSVC",
    "sklearn.svm.LinearSVC",
)
register_family(
    LinearSVRFamily,
    "sklearn.svm._classes.LinearSVR",
    "sklearn.svm.LinearSVR",
)
