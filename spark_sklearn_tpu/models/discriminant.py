"""LinearDiscriminantAnalysis (lsqr solver) — closed-form discriminants.

Reference counterpart: sklearn's LDA running whole inside Spark tasks
(reference: grid_search.py -> sklearn _fit_and_score); the canonical
search grids the `shrinkage` float with solver='lsqr'/'eigen'.  The
compiled redesign covers solver='lsqr' (sklearn _solve_lstsq):

    means_c   = per-class fold means
    cov       = sum_c priors_c * shrunk(empirical_cov(X_c), s)
              = one weighted Gram matmul over class-mean residuals,
                then (1-s)*cov + s*(trace/d)*I
    coef      = lstsq(cov, means.T).T        (min-norm, like sklearn)
    intercept = -0.5 diag(means @ coef.T) + log priors

with sklearn's exact binary collapse (coef row1-row0, scalar
intercept, sigmoid probabilities).  `shrinkage` is a dynamic scalar
(None == 0.0 arithmetically), so a whole shrinkage grid is one
compiled program.  solver='svd' (rank-truncated, different singular
behavior), 'eigen' (different decision parameterisation) and
shrinkage='auto' (Ledoit-Wolf) raise -> the designed host fallback
runs sklearn exactly.  LDA.fit takes no sample_weight (sklearn), so
accepts_sample_weight is False.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import warnings

from spark_sklearn_tpu.models.base import Family, register_family
from spark_sklearn_tpu.models.naive_bayes import (_class_sums,
                                                  _prep_classifier_data)

_EPS = 1e-12


class LinearDiscriminantFamily(Family):
    name = "lda"
    is_classifier = True
    dynamic_params = {"shrinkage": np.float32}
    accepts_sample_weight = False
    #: sklearn's LDA preserves the user's X dtype to the proba output
    #: (grid.py's log_loss clip resolves the eps per family)
    proba_dtype_rule = "input"

    @classmethod
    def check_static(cls, static):
        solver = static.get("solver", "svd")
        if solver != "lsqr":
            raise ValueError(
                f"solver={solver!r} is not compiled (lsqr only); use "
                "backend='host'")
        if static.get("shrinkage") == "auto":
            raise ValueError(
                "shrinkage='auto' (Ledoit-Wolf) is not compiled; use "
                "backend='host'")
        if static.get("covariance_estimator") is not None:
            raise ValueError(
                "covariance_estimator is not compiled; use "
                "backend='host'")

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        """Host-side static/priors validation, per candidate (sklearn
        LDA.fit raises for negative priors, warns and renormalizes
        non-normalized ones — the compiled fit normalizes too, so the
        warning fires here, once per search)."""
        cls.check_static(base_params)
        seen = set()
        for params in [base_params] + [
                {**base_params, **c} for c in candidates]:
            cls.check_static(params)
            priors = params.get("priors")
            if priors is None or id(priors) in seen:
                continue
            seen.add(id(priors))
            p = np.asarray(priors, np.float64)
            k = meta.get("n_classes")
            if k is not None and len(p) != k:
                raise ValueError(
                    f"priors must have length n_classes ({k}); got "
                    f"{len(p)}")
            if (p < 0).any():
                raise ValueError("priors must be non-negative")
            if abs(p.sum() - 1.0) > 1e-5:
                warnings.warn("The priors do not sum to 1. "
                              "Renormalizing", UserWarning, stacklevel=2)

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        return _prep_classifier_data(X, y, dtype)

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        cls.check_static(static)
        X, y1h = data["X"], data["y1h"]
        d = X.shape[1]
        s_raw = dynamic.get("shrinkage", static.get("shrinkage"))
        s = jnp.asarray(0.0 if s_raw is None else s_raw, X.dtype)
        counts, wy, sums = _class_sums(y1h, train_w, X)      # (k,), (k, d)
        cnt = jnp.maximum(counts, _EPS)
        means = sums / cnt[:, None]                          # (k, d)
        priors = static.get("priors")
        if priors is not None:
            pri = jnp.asarray(priors, X.dtype)
            # sklearn warns and renormalizes (the warning fires
            # host-side in observe_candidates)
            pri = pri / jnp.maximum(jnp.sum(pri), _EPS)
        else:
            pri = counts / jnp.maximum(jnp.sum(counts), _EPS)
        # within-class covariance, priors-weighted (sklearn _class_cov):
        # residuals about each sample's OWN class mean (two-pass — the
        # same f32-cancellation discipline as the NB variance), scaled
        # so the weighted Gram sums priors_c/n_c per row
        r = X - means[data["y"]]                             # (n, d)
        row_w = train_w * (pri / cnt)[data["y"]]             # (n,)
        cov = (r * row_w[:, None]).T @ r                     # (d, d)
        mu = jnp.trace(cov) / d
        cov = (1.0 - s) * cov + s * mu * jnp.eye(d, dtype=X.dtype)
        coef, *_ = jnp.linalg.lstsq(cov, means.T)            # (d, k)
        coef = coef.T                                        # (k, d)
        intercept = -0.5 * jnp.sum(means * coef, axis=1) \
            + jnp.log(jnp.maximum(pri, _EPS))
        return {"coef": coef, "intercept": intercept}

    @classmethod
    def decision(cls, model, static, X, meta):
        Z = X @ model["coef"].T + model["intercept"][None, :]
        if meta["n_classes"] == 2:
            # sklearn's binary collapse: one row, log-likelihood ratio
            return Z[:, 1] - Z[:, 0]
        return Z

    @classmethod
    def predict(cls, model, static, X, meta):
        dec = cls.decision(model, static, X, meta)
        if meta["n_classes"] == 2:
            return (dec > 0).astype(jnp.int32)
        return jnp.argmax(dec, axis=1).astype(jnp.int32)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        dec = cls.decision(model, static, X, meta)
        if meta["n_classes"] == 2:
            p = jax.nn.sigmoid(dec)
            return jnp.stack([1.0 - p, p], axis=1)
        return jax.nn.softmax(dec, axis=1)

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        coef = np.asarray(model["coef"])
        icpt = np.asarray(model["intercept"])
        if meta["n_classes"] == 2:
            coef = (coef[1] - coef[0]).reshape(1, -1)
            icpt = np.asarray([icpt[1] - icpt[0]])
        return {"coef_": coef, "intercept_": icpt,
                "classes_": meta["classes"],
                "n_features_in_": meta["n_features"]}


register_family(
    LinearDiscriminantFamily,
    "sklearn.discriminant_analysis.LinearDiscriminantAnalysis",
)
