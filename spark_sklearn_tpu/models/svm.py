"""SVC family — kernel SVM re-designed for the MXU.

Reference counterpart: sklearn's SVC (libsvm SMO, one C++ working-set solve
per Spark task; BASELINE config #2 is an SVC(rbf) CxGamma grid on MNIST-10k).
SMO is a scalar, data-dependent algorithm that cannot map to a systolic
array, so the TPU redesign solves the same dual QP with **projected
gradient ascent** where every iteration is ONE kernel matmul for all
(fold x class-pair) subproblems of a candidate at once:

  max_a  1'a - 0.5 a' Q a,   0 <= a_i <= C,  sum_i y_i a_i = 0,
  Q = (y y') * K

This is the true libsvm dual, equality constraint included: each ascent
step projects onto the box-and-hyperplane set via a vectorized bisection
(`_project_box_hyperplane`) and the intercept comes from the KKT
conditions (`_kkt_intercept`, libsvm's -rho).  The step size
1/lambda_max(K) is safe for every masked subproblem because a principal
submatrix of a PSD matrix cannot have a larger top eigenvalue, and the
y-sign flip DKD is a similarity transform.

Multi-class follows sklearn: one-vs-one over all k(k-1)/2 pairs with
majority voting (confidence-scaled tie-break like _ovr_decision_function).

Deviation from libsvm (documented, tested at the accuracy level): a
fixed iteration budget instead of SMO's working-set convergence.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, encode_labels, register_family


def _pairs(k: int) -> np.ndarray:
    return np.array([(i, j) for i in range(k) for j in range(i + 1, k)],
                    dtype=np.int32)


def _kernel(X1, X2, kind, gamma, degree, coef0):
    if kind == "linear":
        return X1 @ X2.T
    if kind == "poly":
        return (gamma * (X1 @ X2.T) + coef0) ** degree
    if kind == "sigmoid":
        return jnp.tanh(gamma * (X1 @ X2.T) + coef0)
    # rbf
    sq1 = jnp.sum(X1 * X1, axis=1)
    sq2 = jnp.sum(X2 * X2, axis=1)
    d2 = sq1[:, None] - 2.0 * (X1 @ X2.T) + sq2[None, :]
    return jnp.exp(-gamma * jnp.maximum(d2, 0.0))


def _power_step(K, n, dtype):
    """1/lambda_max(K) via power iteration — a safe ascent step for every
    masked/sign-flipped subproblem (principal submatrices of a PSD matrix
    cannot have a larger top eigenvalue)."""
    v = jnp.ones((n,), dtype) / jnp.sqrt(n)

    def power(i, v):
        v = K @ v
        return v / (jnp.linalg.norm(v) + 1e-12)

    v = jax.lax.fori_loop(0, 20, power, v)
    return 1.0 / (jnp.dot(v, K @ v) + 1e-6)


def _box_fista(grad_fn, project, x0, step, max_iter, tol=None):
    """Nesterov-accelerated projected gradient on a constrained QP — the
    ONE loop behind every dual here (SVC pairs, nu-duals, SVR pairs, the
    liblinear hinge/epsilon duals): the TPU answer to libsvm/liblinear's
    sequential working-set and coordinate-descent solvers, where every
    (subproblem, sample) coordinate advances together through wide
    matmuls.  Minimises; ascent callers negate their gradient.

    With `tol=None` (SVC/NuSVC duals, which check KKT themselves) runs a
    fixed iteration count and returns `x`.  With a per-lane `tol` array
    (leading axis of x0 = lanes) it ALSO measures convergence honestly:
    the per-lane prox-gradient residual max|z - prox(z - step*grad(z))|
    divided by `step` — the generalized-gradient magnitude, so the
    criterion is scale-free in the step size (an absolute iterate-shift
    test would spuriously fire on the first iteration whenever
    1/lambda_max(Gram) < tol).  Not liblinear's dual-violation bound,
    but a real measurement rather than an assumed one.  Exits once every
    lane has converged and returns (x, n_iter, converged)."""
    dtype = x0.dtype

    if tol is None:
        def body(i, carry):
            x, z, t = carry
            x_new = project(z - step * grad_fn(z))
            t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
            z_new = x_new + ((t - 1.0) / t_new) * (x_new - x)
            return x_new, z_new, t_new

        x, _, _ = jax.lax.fori_loop(
            0, max_iter, body, (x0, x0, jnp.asarray(1.0, dtype)))
        return x

    lane_axes = tuple(range(1, x0.ndim))
    B = x0.shape[0]

    def cond(carry):
        *_, it, _n, done = carry
        return jnp.logical_and(it < max_iter,
                               jnp.logical_not(jnp.all(done)))

    def body(carry):
        x, z, t, it, n_iter, done = carry
        x_new = project(z - step * grad_fn(z))
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_new = x_new + ((t - 1.0) / t_new) * (x_new - x)
        resid = jnp.max(jnp.abs(x_new - z), axis=lane_axes) / step
        done_new = jnp.logical_or(done, resid <= tol)
        n_iter = jnp.where(jnp.logical_and(jnp.logical_not(done),
                                           done_new), it + 1, n_iter)
        return x_new, z_new, t_new, it + 1, n_iter, done_new

    x, _, _, it, n_iter, done = jax.lax.while_loop(
        cond, body,
        (x0, x0, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32),
         jnp.full((B,), max_iter, jnp.int32), jnp.zeros((B,), bool)))
    n_iter = jnp.where(done, n_iter, it)
    return x, n_iter, done


def _project_box_hyperplane(Z, yb, bound, n_bisect=40):
    """Euclidean projection of each row of Z onto its subproblem's feasible
    set {0 <= a_i <= bound_i} intersected with {sum_i y_i a_i = 0}.

    `bound` is per-element (C, class_weight-scaled C, or 0 outside the
    subproblem's rows).  The projection is clip(z - nu*y, 0, bound) for
    the nu making the hyperplane constraint hold; g(nu) = sum(y * clip(z
    - nu*y, 0, bound)) is monotone decreasing, so nu comes from a
    fixed-count vectorized bisection (cheap elementwise work next to the
    (M, n) @ (n, n) ascent matmul)."""
    lo = -(jnp.max(jnp.abs(Z), axis=1) + jnp.max(bound, axis=1))
    hi = -lo

    def bis(i, lh):
        lo, hi = lh
        mid = 0.5 * (lo + hi)
        a = jnp.clip(Z - mid[:, None] * yb, 0.0, bound)
        g = jnp.sum(yb * a, axis=1)
        take_hi = g > 0
        return jnp.where(take_hi, mid, lo), jnp.where(take_hi, hi, mid)

    lo, hi = jax.lax.fori_loop(0, n_bisect, bis, (lo, hi))
    nu = 0.5 * (lo + hi)
    return jnp.clip(Z - nu[:, None] * yb, 0.0, bound)


def _project_box_sum(Z, bound, target, n_bisect=40):
    """Euclidean projection of each row of Z onto
    {0 <= a_i <= bound_i, sum_i a_i = target} — clip(z - lam, 0, bound)
    for the lam making the sum hit `target` (monotone decreasing in lam,
    fixed-count vectorized bisection).  `target` is per-row (M,)."""
    zmax = jnp.max(jnp.abs(Z), axis=1) + jnp.max(bound, axis=1) + 1.0
    lo, hi = -zmax, zmax

    def bis(i, lh):
        lo, hi = lh
        mid = 0.5 * (lo + hi)
        g = jnp.sum(jnp.clip(Z - mid[:, None], 0.0, bound), axis=1)
        take_hi = g > target
        return jnp.where(take_hi, mid, lo), jnp.where(take_hi, hi, mid)

    lo, hi = jax.lax.fori_loop(0, n_bisect, bis, (lo, hi))
    mid = 0.5 * (lo + hi)
    return jnp.clip(Z - mid[:, None], 0.0, bound)


def _masked_mean_or_mid(vals, free, at_hi, at_lo):
    """libsvm's r1/r2 rule: mean of `vals` over free SVs; when none are
    free, the midpoint of [max over at-upper-bound, min over at-0]."""
    big = jnp.asarray(jnp.inf, vals.dtype)
    nfree = jnp.sum(free, axis=1)
    mean_free = jnp.sum(jnp.where(free, vals, 0.0), axis=1) / \
        jnp.maximum(nfree, 1)
    lb = jnp.max(jnp.where(at_hi, vals, -big), axis=1)
    ub = jnp.min(jnp.where(at_lo, vals, big), axis=1)
    mid = 0.5 * (lb + ub)
    mid = jnp.where(jnp.isfinite(mid), mid,
                    jnp.where(jnp.isfinite(lb), lb,
                              jnp.where(jnp.isfinite(ub), ub, 0.0)))
    return jnp.where(nfree > 0, mean_free, mid)


def _run_dual(grad, project, x0, step, max_iter, tol, dtype):
    """Shared tol dispatch for the kernel duals: `tol=None` runs the
    fixed count; otherwise `_box_fista`'s per-lane residual exit (the
    batched analog of libsvm's eps rule) with the executed-iteration
    max reported for accounting."""
    if tol is None:
        x = _box_fista(grad, project, x0, step, max_iter)
        return x, jnp.asarray(max_iter, jnp.int32)
    x, n_it, _ = _box_fista(grad, project, x0, step, max_iter,
                            tol=jnp.full((x0.shape[0],), tol, dtype))
    return x, jnp.max(n_it).astype(jnp.int32)


def _tol_or_default(static):
    """sklearn's SVC tol (libsvm eps), defaulting to libsvm's 1e-3."""
    tol = static.get("tol", 1e-3)
    return 1e-3 if tol is None else float(tol)


def _probability_value_on(value):
    """sklearn 1.9 deprecated SVC's `probability` and made its DEFAULT
    the string "deprecated" — which is truthy, so a naive bool() turns
    every plain SVC search into one that computes Platt calibration.
    Only an explicit boolean True (python or numpy) counts."""
    return isinstance(value, (bool, np.bool_)) and bool(value)


def _probability_on(params):
    return _probability_value_on(params.get("probability", False))


def nu_dual_ascent(K, yb, bound, nu, step, max_iter, tol=None):
    """libsvm's nu-SVC dual (Solver_NU), batched over M subproblems:

        min_a 0.5 a'Q a,   0 <= a_i <= bound_i,
        y'a = 0,  e'a = nu * l          (l = subproblem row count)

    The two equalities DECOMPOSE over the class signs: sum over the
    positive half = sum over the negative half = nu*l/2, so each
    projection is two independent box+sum bisections — no coupled 2-D
    multiplier search.  After the solve, the KKT multipliers follow
    libsvm's calculate_rho: free +1 SVs average the gradient to r1, free
    -1 SVs to r2; the decision is rescaled by r = (r1+r2)/2 (alpha /= r,
    rho = (r1-r2)/2 / r).  Returns per-subproblem full-set decision rows;
    infeasible subproblems (nu*l/2 exceeding a half's box capacity — the
    case where sklearn raises 'specified nu is infeasible') come back as
    NaN rows for the engine's failed-fit detector.
    """
    pos_b = jnp.where(yb > 0, bound, 0.0)
    neg_b = jnp.where(yb < 0, bound, 0.0)
    l_sub = jnp.sum(bound > 0, axis=1).astype(K.dtype)
    target = 0.5 * nu * l_sub                                   # (M,)
    cap = jnp.minimum(jnp.sum(pos_b, axis=1), jnp.sum(neg_b, axis=1))
    feasible = target <= cap * (1.0 + 1e-6)

    def project(Zt):
        return _project_box_sum(Zt, pos_b, target) + \
            _project_box_sum(Zt, neg_b, target)

    def grad(Z):
        return yb * ((Z * yb) @ K)

    A, n_it = _run_dual(grad, project, project(jnp.zeros_like(bound)),
                        step, max_iter, tol, K.dtype)

    V = (A * yb) @ K
    G = yb * V                         # gradient of 0.5 a'Qa
    inb = bound > 0
    at_lo = A <= bound * 1e-6
    at_hi = A >= bound * (1.0 - 1e-6)
    free = inb & ~at_lo & ~at_hi
    pos, neg = yb > 0, yb < 0
    r1 = _masked_mean_or_mid(G, free & pos, inb & pos & at_hi,
                             inb & pos & at_lo)
    r2 = _masked_mean_or_mid(G, free & neg, inb & neg & at_hi,
                             inb & neg & at_lo)
    r = 0.5 * (r1 + r2)                # lambda_e: the alpha rescale
    rho = 0.5 * (r1 - r2)              # lambda_y
    ok = jnp.logical_and(feasible, r > 1e-12)
    dec = (V - rho[:, None]) / r[:, None]
    return jnp.where(ok[:, None], dec, jnp.nan), n_it


def _kkt_intercept(K, A, yb, bound):
    """Per-subproblem intercept b from the KKT conditions (libsvm's -rho):
    mean of E_i = y_i - f0(x_i) over free SVs; when every alpha sits at a
    bound, the midpoint of the feasible [max lower, min upper] interval."""
    V = (A * yb) @ K                                     # (M, n)
    E = yb - V
    inb = bound > 0
    at_lo = A <= bound * 1e-6
    at_hi = A >= bound * (1.0 - 1e-6)
    free = inb & ~at_lo & ~at_hi
    nfree = jnp.sum(free, axis=1)
    b_free = jnp.sum(jnp.where(free, E, 0.0), axis=1) / \
        jnp.maximum(nfree, 1)
    lo_mask = inb & ((at_lo & (yb > 0)) | (at_hi & (yb < 0)))
    up_mask = inb & ((at_lo & (yb < 0)) | (at_hi & (yb > 0)))
    big = jnp.asarray(jnp.inf, E.dtype)
    max_lo = jnp.max(jnp.where(lo_mask, E, -big), axis=1)
    min_up = jnp.min(jnp.where(up_mask, E, big), axis=1)
    b_mid = 0.5 * (max_lo + min_up)
    b_mid = jnp.where(
        jnp.isfinite(b_mid), b_mid,
        jnp.where(jnp.isfinite(max_lo), max_lo,
                  jnp.where(jnp.isfinite(min_up), min_up, 0.0)))
    return jnp.where(nfree > 0, b_free, b_mid)


def fista_dual_ascent(K, yb, bound, step, max_iter, tol=None):
    """Nesterov-accelerated projected gradient ascent on the SVM dual

        max_a  1'a - 0.5 a' Q a,   0 <= a_i <= bound_i,
        sum_i y_i a_i = 0

    (the true libsvm dual, equality constraint included; per-sample upper
    bounds carry both the subproblem box mask and class_weight-scaled C).
    K: (n, n) kernel; yb/bound: (M, n) signed labels and box bounds for M
    subproblems advanced together — every iteration is ONE (M, n) @ (n, n)
    matmul plus a vectorized hyperplane projection.  Returns
    (A, b, n_iter): alphas, the KKT intercept per subproblem, and the
    executed iteration count (== max_iter when tol is None; with `tol`,
    the per-lane prox-gradient-residual exit stops when every subproblem
    is below it — the batched analog of libsvm's eps stopping rule,
    which defaults to the same 1e-3 the sklearn `tol` parameter
    carries).  Shared by the search's task-batched fit and the
    standalone SVC so the numerics live once."""

    def grad(Z):                       # descent form of the ascent grad
        return -(1.0 - yb * ((Z * yb) @ K))

    A, n_it = _run_dual(
        grad, lambda Zt: _project_box_hyperplane(Zt, yb, bound),
        jnp.zeros_like(bound), step, max_iter, tol, K.dtype)
    return A, _kkt_intercept(K, A, yb, bound), n_it


def _platt_fit(f, t, w, n_iter=50):
    """Vectorized Platt sigmoid calibration: per task (leading axis),
    minimise the weighted logloss of P(y=1|f) = sigmoid(-(A*f + B))
    against Platt's smoothed targets `t` with sample weights `w`, by
    damped Newton on the 2-parameter convex problem (closed-form 2x2
    solve per task — libsvm's sigmoid_train, batched).  Damping = per-
    task step halving: full Newton steps can overshoot on near-separable
    folds (libsvm guards with the same line search); a step that fails
    to decrease the loss at every halving is rejected outright, and
    tasks whose gradient is below libsvm's eps stop moving.

    Returns (A, B) arrays of shape f.shape[:1]."""
    B_ = f.shape[0]
    dtype = f.dtype
    wsum = jnp.sum(w, axis=1) + 1e-12
    # libsvm init: A=0, B=log((prior0+1)/(prior1+1)) from the targets
    np_w = jnp.sum(w * t, axis=1)
    nn_w = wsum - np_w
    A0 = jnp.zeros((B_,), dtype)
    B0 = jnp.log((nn_w + 1.0) / (np_w + 1.0))

    def loss(A, Bb):
        # sum_i w_i * [log(1+e^{u_i}) - (1-t_i) u_i], the stable form of
        # the weighted cross-entropy of targets t under p = sigmoid(-u)
        u = A[:, None] * f + Bb[:, None]
        return jnp.sum(w * (jnp.logaddexp(0.0, u) - (1.0 - t) * u),
                       axis=1)

    halvings = (2.0 ** -jnp.arange(8)).astype(dtype)   # 1, 1/2, .. 1/128

    def body(i, carry):
        A, Bb = carry
        u = A[:, None] * f + Bb[:, None]
        s = jax.nn.sigmoid(u)                    # = 1 - p
        r = w * (s - (1.0 - t))                  # dL/du per sample
        gA = jnp.sum(r * f, axis=1)
        gB = jnp.sum(r, axis=1)
        h = w * s * (1.0 - s)
        hAA = jnp.sum(h * f * f, axis=1) + 1e-9
        hAB = jnp.sum(h * f, axis=1)
        hBB = jnp.sum(h, axis=1) + 1e-9
        det = hAA * hBB - hAB * hAB
        dA = (hBB * gA - hAB * gB) / det
        dB = (hAA * gB - hAB * gA) / det
        # step halving: first step size that does not increase the loss
        # wins; none -> no update this iteration (monotone by design)
        L0 = loss(A, Bb)
        Ls = jax.vmap(lambda st: loss(A - st * dA, Bb - st * dB))(halvings)
        ok = Ls <= L0[None, :]
        first = jnp.argmax(ok, axis=0)
        step = jnp.where(jnp.any(ok, axis=0), halvings[first], 0.0)
        # converged tasks (libsvm eps) stop moving
        step = jnp.where(
            jnp.maximum(jnp.abs(gA), jnp.abs(gB)) >= 1e-5, step, 0.0)
        # a rejected step must not touch A/B at all: with a non-finite
        # Newton direction (degenerate 2x2 system), 0 * inf = NaN would
        # poison the task permanently
        upd = step > 0
        return (jnp.where(upd, A - step * dA, A),
                jnp.where(upd, Bb - step * dB, Bb))

    A, Bb = jax.lax.fori_loop(0, n_iter, body, (A0, B0))
    return A, Bb


def _pair_probs_to_R(r, pairs, k):
    """(n, P) per-pair sigmoid probabilities -> the (n, k, k) pairwise
    matrix Wu-Lin consumes: R[i_p, j_p] = r_p, R[j_p, i_p] = 1 - r_p,
    with libsvm's clip away from {0, 1}.  Shared by the search-internal
    (train-fold Platt) and converted-model (libsvm probA/probB) paths so
    the coupling input can never desynchronize between them."""
    r = jnp.clip(r, 1e-7, 1.0 - 1e-7)
    pos = jax.nn.one_hot(pairs[:, 0], k, dtype=r.dtype)
    neg = jax.nn.one_hot(pairs[:, 1], k, dtype=r.dtype)
    return jnp.einsum("np,pi,pj->nij", r, pos, neg) \
        + jnp.einsum("np,pi,pj->nij", 1.0 - r, neg, pos)


def _pairwise_coupling(R, n_iter=100):
    """Wu & Lin (2004) "second approach" pairwise coupling — libsvm's
    multiclass_probability, batched over arbitrary leading axes.

    R[..., i, j] ~ P(class i | class i or j) from per-pair Platt
    sigmoids (diagonal ignored).  Solves min_p sum_{i!=j}
    (r_ji p_i - r_ij p_j)^2 on the simplex by libsvm's normalised
    Gauss-Seidel sweeps (fixed iteration count; libsvm's max is
    max(100, k) with early exit — the extra sweeps past convergence
    are no-ops since diff -> 0).  Returns (..., k) probabilities."""
    k = R.shape[-1]
    eye = jnp.eye(k, dtype=R.dtype)
    R0 = R * (1.0 - eye)
    RT = jnp.swapaxes(R0, -1, -2)
    # Q[t,t] = sum_{j!=t} r_jt^2 ; Q[t,j] = -r_jt * r_tj  (symmetric PSD)
    Q = -(RT * R0)
    Q = Q + eye * jnp.sum(RT ** 2, axis=-1)[..., :, None]

    def outer(_, p):
        Qp = jnp.einsum("...tj,...j->...t", Q, p)
        pQp = jnp.sum(p * Qp, axis=-1)

        def inner(t, carry):
            p, Qp, pQp = carry
            Qtt = Q[..., t, t]
            diff = (-Qp[..., t] + pQp) / Qtt
            pQp = (pQp + diff * (diff * Qtt + 2.0 * Qp[..., t])) \
                / (1.0 + diff) ** 2
            Qp = (Qp + diff[..., None] * Q[..., t, :]) \
                / (1.0 + diff[..., None])
            p = (p + diff[..., None] * eye[t]) / (1.0 + diff[..., None])
            return p, Qp, pQp

        p, _, _ = jax.lax.fori_loop(0, k, inner, (p, Qp, pQp))
        return p

    p0 = jnp.full(R.shape[:-1], 1.0 / k, dtype=R.dtype)
    return jax.lax.fori_loop(0, n_iter, outer, p0)


def _resolve_gamma(gamma, meta):
    if isinstance(gamma, str):
        if gamma == "scale":
            # X variance precomputed host-side in prepare_data
            return 1.0 / (meta["n_features"] * meta["x_var"])
        if gamma == "auto":
            return 1.0 / meta["n_features"]
        raise ValueError(f"gamma={gamma!r} not understood")
    return float(gamma)


class SVCFamily(Family):
    name = "svc"
    is_classifier = True
    dynamic_params = {"C": np.float32, "gamma": np.float32}
    #: libsvm computes probabilities in f64 whatever the input dtype, so
    #: sklearn's log_loss clips them at f64 eps (engine: logloss_clip_eps)
    proba_dtype_rule = "float64"
    #: the per-candidate scalar the dual consumes (NuSVC swaps in "nu")
    primary_param = "C"
    primary_default = 1.0
    #: the task-batched fit understands per-fold-transformed inputs
    #: (data["X_folds"], shape (F, n, d)) — what compiled Pipelines feed it
    task_batched_accepts_fold_inputs = True

    @classmethod
    def _pair_dec(cls, K, p_c, base_bound, yb, step, max_iter, tol=None):
        """Solve the M stacked pair subproblems and return their (M, n)
        full-set decision rows plus the executed iteration count.  `p_c`
        is the candidate's primary scalar (C here: scales the box),
        `base_bound` the fold/weight/pair box mask; `tol` enables the
        per-lane residual exit (libsvm's eps stopping rule)."""
        bound = p_c * base_bound
        A, b, n_it = fista_dual_ascent(K, yb, bound, step, max_iter, tol)
        return (A * yb) @ K + b[:, None], n_it

    # kernel matrices + per-task decision caches are the memory hot spot;
    # tell the search to keep task batches small
    @staticmethod
    def max_tasks_hint(n_samples: int, meta) -> int:
        k = meta["n_classes"]
        p = max(1, k * (k - 1) // 2)
        budget = 1 << 30   # ~1 GiB of decision cache per launch
        return max(1, budget // max(1, n_samples * p * 4))

    @classmethod
    def extract_params(cls, estimator):
        params = dict(estimator.get_params(deep=False))
        return params

    @classmethod
    def observe_candidates(cls, candidates, base_params, meta):
        """Host-side, once per fit: warn about the compiled Platt
        approximation when any candidate requests probability=True
        (the traced fit code cannot warn reliably — a program-cache
        hit skips tracing entirely)."""
        if _probability_on(base_params) or any(
                _probability_on(c) for c in candidates):
            warnings.warn(
                "compiled SVC(probability=True): Platt calibration uses "
                "train-fold decision values, not libsvm's internal "
                "5-fold CV — probabilities are slightly overconfident "
                "vs sklearn's (documented in docs/ROADMAP.md)",
                UserWarning, stacklevel=2)

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        classes, y_enc = encode_labels(y)
        k = len(classes)
        data = {
            "X": np.ascontiguousarray(X, dtype=dtype),
            "y": y_enc,
        }
        meta = {"n_classes": int(k), "classes": classes,
                "n_features": int(X.shape[1]),
                "x_var": float(np.var(np.asarray(X))),
                "pairs": _pairs(k)}
        return data, meta

    @classmethod
    def fit_task_batched(cls, dynamic, static, data, train_w, meta):
        """Tasks arrive candidate-major (task t = (cand t//F, fold t%F)).
        One `lax.scan` step per candidate: its kernel matrix is built once
        and shared by every (fold x pair) subproblem, which are advanced
        together — each ascent iteration is a single (F*P, n) @ (n, n)
        matmul.  Returns per-task full-dataset pair decisions (the search
        scores on masked rows of the training X, so caching decisions
        avoids rebuilding kernels in the scoring phase)."""
        X = data["X"]
        y = data["y"]
        n, d = X.shape
        k = meta["n_classes"]
        pairs = jnp.asarray(meta["pairs"])                    # (P, 2)
        P = pairs.shape[0]
        B = train_w.shape[0]
        kind = static.get("kernel", "rbf")
        if kind == "precomputed":
            raise ValueError("precomputed kernels: use backend='host'")
        degree = float(static.get("degree", 3))
        coef0 = float(static.get("coef0", 0.0))
        max_iter = int(static.get("max_iter", -1))
        if max_iter in (-1, 0):
            max_iter = 300
        # libsvm's eps stopping rule (sklearn tol, default 1e-3): each
        # candidate's dual solve exits at ITS convergence inside the
        # per-candidate scan — easy (small-C) candidates stop in tens of
        # iterations instead of paying max_iter
        tol_exit = _tol_or_default(static)
        # tasks are candidate-major with a fixed fold count injected by the
        # engine; the candidate count is B // n_folds
        n_folds = int(static.get("__n_folds__", 0))
        if n_folds <= 0:
            raise ValueError("engine must pass __n_folds__ for SVC")
        nc = B // n_folds

        gamma_default = _resolve_gamma(static.get("gamma", "scale"), meta)
        pp = cls.primary_param
        C_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get(pp, static.get(pp, cls.primary_default)),
            X.dtype), (B,))
        g_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get("gamma", gamma_default), X.dtype), (B,))
        C_cand = C_task.reshape(nc, n_folds)[:, 0]
        g_cand = g_task.reshape(nc, n_folds)[:, 0]
        w_cand = train_w.reshape(nc, n_folds, n)

        # per-pair signed labels: +1 for pairs[p,0], -1 for pairs[p,1]
        ypos = (y[None, :] == pairs[:, 0][:, None])
        yneg = (y[None, :] == pairs[:, 1][:, None])
        ybin = ypos.astype(X.dtype) - yneg.astype(X.dtype)    # (P, n)
        if k == 2:
            # sklearn convention: binary decision_function > 0 -> classes_[1]
            ybin = -ybin
        in_pair = (ypos | yneg).astype(X.dtype)               # (P, n)

        X_folds = data.get("X_folds")     # (F, n, d) fold-transformed, or
        # None (plain SVC: one shared X, one kernel per candidate)
        gamma_is_scale = "gamma" not in dynamic and \
            static.get("gamma", "scale") == "scale"

        # class_weight scales each sample's box bound: 0 <= a_i <= C * cw_i
        # (libsvm's per-class C); "balanced" follows each fold's counts
        from spark_sklearn_tpu.models.base import class_weight_multiplier
        w_fold_masks = train_w.reshape(nc, n_folds, n)[0]     # (F, n)
        cw_fold = class_weight_multiplier(
            w_fold_masks, y, meta, static.get("class_weight"))
        if cw_fold is None:
            cw_fold = jnp.ones((n_folds, n), X.dtype)

        def one_candidate(carry, inp):
            C_c, g_c, w_f = inp                               # w_f (F, n)
            if X_folds is None:
                K = _kernel(X, X, kind, g_c, degree, coef0)   # (n, n)
                step = _power_step(K, n, X.dtype)
                # subproblem box masks: (F, P, n) -> flatten (F*P, n)
                base = ((w_f * cw_fold)[:, None, :]
                        * in_pair[None, :, :]).reshape(-1, n)
                yb = jnp.broadcast_to(
                    ybin[None], (n_folds, P, n)).reshape(-1, n)
                dec, it = cls._pair_dec(
                    K, C_c, base, yb, step, max_iter, tol_exit)
                dec = dec.reshape(n_folds, P, n)
            else:
                # pipeline mode: each fold has its own transformed X, so
                # kernels are per (candidate, fold); the P pair
                # subproblems of a fold advance together and folds batch
                # via vmap (an (F, P, n) x (F, n, n) bmm on the MXU).
                # gamma='scale' must follow the TRANSFORMED fold X
                # (sklearn resolves it on the X the final step receives).
                def per_fold(Xf, w_row, cw_row):
                    if gamma_is_scale:
                        mrow = (w_row > 0).astype(Xf.dtype)
                        cnt = jnp.sum(mrow) * Xf.shape[1] + 1e-12
                        mu = jnp.sum(Xf * mrow[:, None]) / cnt
                        var = jnp.sum(((Xf - mu) ** 2)
                                      * mrow[:, None]) / cnt
                        g_f = 1.0 / (Xf.shape[1]
                                     * jnp.maximum(var, 1e-12))
                    else:
                        g_f = g_c
                    Kf = _kernel(Xf, Xf, kind, g_f, degree, coef0)
                    step = _power_step(Kf, n, Xf.dtype)
                    base = (w_row * cw_row)[None, :] * in_pair
                    return cls._pair_dec(
                        Kf, C_c, base, ybin, step, max_iter,
                        tol_exit)                         # (P, n), it

                dec, its = jax.vmap(per_fold)(
                    X_folds, w_f, cw_fold)                # (F,P,n), (F,)
                it = jnp.max(its)
            return carry, (jnp.transpose(dec, (0, 2, 1)), it)  # (F,n,P)

        _, (decs, its) = jax.lax.scan(
            one_candidate, 0.0, (C_cand, g_cand, w_cand))
        # (nc, F, n, P) -> task-major (B, n, P); per-candidate executed
        # dual iterations repeat across the fold axis for the engine's
        # per-launch accounting
        model = {"pair_dec": decs.reshape(B, n, P),
                 "n_iter": jnp.repeat(its, n_folds)}
        if _probability_on(static):
            # compiled Platt scaling: calibrate a sigmoid on the
            # TRAIN-fold decision values per task, stored with the model
            # so predict_proba / neg_log_loss scoring stay compiled.
            # Approximation vs libsvm: libsvm calibrates on internal
            # 5-fold CV decisions; these are in-sample train decisions
            # (slightly overconfident — documented in docs/ROADMAP.md;
            # the user-facing warning fires host-side per fit, in
            # observe_candidates — this code is jit-traced, so a warn
            # here would fire only on the first compile)
            if k == 2:
                fdec = model["pair_dec"][:, :, 0]             # (B, n)
                yp = (y == 1).astype(X.dtype)[None, :]        # classes_[1]
                np_w = jnp.sum(train_w * yp, axis=1)
                nn_w = jnp.sum(train_w * (1.0 - yp), axis=1)
                t_pos = (np_w + 1.0) / (np_w + 2.0)
                t_neg = 1.0 / (nn_w + 2.0)
                t = jnp.where(yp > 0, t_pos[:, None], t_neg[:, None])
                A, Bb = _platt_fit(fdec, t, train_w)
                model["platt"] = jnp.stack([A, Bb], axis=1)   # (B, 2)
            else:
                # multiclass: one Platt sigmoid per PAIR, fitted on that
                # pair's train-fold members only; predict_proba couples
                # them with Wu-Lin (libsvm's multiclass_probability)
                f_bp = jnp.transpose(
                    model["pair_dec"], (0, 2, 1))             # (B, P, n)
                yp = ypos.astype(X.dtype)                     # (P, n)
                w_bp = train_w[:, None, :] * in_pair[None]    # (B, P, n)
                np_w = jnp.sum(w_bp * yp[None], axis=2)       # (B, P)
                nn_w = jnp.sum(w_bp, axis=2) - np_w
                t_pos = (np_w + 1.0) / (np_w + 2.0)
                t_neg = 1.0 / (nn_w + 2.0)
                t = jnp.where(yp[None] > 0,
                              t_pos[..., None], t_neg[..., None])
                A, Bb = _platt_fit(f_bp.reshape(B * P, n),
                                   t.reshape(B * P, n),
                                   w_bp.reshape(B * P, n))
                model["platt_pair"] = jnp.stack(
                    [A, Bb], axis=1).reshape(B, P, 2)
        return model

    # -- prediction from cached decisions (search-internal) or from the
    # -- support-vector/representer form (Converter.toTPU) ----------------
    @classmethod
    def _pair_dec_of(cls, model, static, X, meta):
        """Pair decisions (n, P): the search caches them per task
        ("pair_dec", full training set, X ignored); converted models
        carry the representer form instead ("sv_X" support vectors +
        per-pair signed "alphas" + "intercepts") and evaluate new X
        with one kernel matmul."""
        if "pair_dec" in model:
            return model["pair_dec"]
        g = meta.get("resolved_gamma")
        if g is None:
            g = _resolve_gamma(static.get("gamma", "scale"), meta)
        K = _kernel(X, model["sv_X"], static.get("kernel", "rbf"), g,
                    float(static.get("degree", 3)),
                    float(static.get("coef0", 0.0)))
        return K @ model["alphas"].T + model["intercepts"][None, :]

    @classmethod
    def _votes(cls, dec, meta):
        pairs = jnp.asarray(meta["pairs"])                    # (P, 2)
        k = meta["n_classes"]
        P = pairs.shape[0]
        pos_mat = jax.nn.one_hot(pairs[:, 0], k, dtype=dec.dtype)  # (P, k)
        neg_mat = jax.nn.one_hot(pairs[:, 1], k, dtype=dec.dtype)
        win_pos = (dec > 0).astype(dec.dtype)                 # (n, P)
        votes = win_pos @ pos_mat + (1.0 - win_pos) @ neg_mat
        # confidence tie-break, bounded to (-.5, .5) like sklearn's
        # _ovr_decision_function
        conf = dec @ pos_mat - dec @ neg_mat                  # (n, k)
        conf = conf / (3.0 * (jnp.abs(conf) + 1.0))
        return votes + conf

    @classmethod
    def predict(cls, model, static, X, meta):
        dec = cls._pair_dec_of(model, static, X, meta)
        if meta["n_classes"] == 2:
            return (dec[:, 0] > 0).astype(jnp.int32)
        return jnp.argmax(cls._votes(dec, meta),
                          axis=1).astype(jnp.int32)

    @classmethod
    def decision(cls, model, static, X, meta):
        dec = cls._pair_dec_of(model, static, X, meta)
        if meta["n_classes"] == 2:
            return dec[:, 0]
        return cls._votes(dec, meta)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        """Compiled Platt probabilities (probability=True — calibration
        fitted alongside the duals in fit_task_batched).  Binary: one
        sigmoid.  Multiclass: per-pair sigmoids coupled with Wu-Lin
        (`_pairwise_coupling`, libsvm's multiclass_probability), fully
        compiled — proba-scoring multiclass searches stay on the
        compiled tier."""
        if "probA" in model:
            # converted sklearn SVC: libsvm's own (probA_, probB_) pair
            # sigmoids — exact parity with sklearn's predict_proba
            dec = cls._pair_dec_of(model, static, X, meta)
            A, Bp = model["probA"], model["probB"]
            k = meta["n_classes"]
            if k == 2:
                # libsvm's binary pair is classes_[0]-positive while the
                # public decision_function is classes_[1]-positive, so
                # the calibrated sigmoid sees the NEGATED public margin
                r0 = jax.nn.sigmoid(-(A[0] * (-dec[:, 0]) + Bp[0]))
                return jnp.stack([r0, 1.0 - r0], axis=1)
            pairs = jnp.asarray(meta["pairs"])
            r = jax.nn.sigmoid(-(dec * A[None, :] + Bp[None, :]))
            return _pairwise_coupling(_pair_probs_to_R(r, pairs, k))
        if "platt" in model:
            f = model["pair_dec"][:, 0]
            A, B = model["platt"][0], model["platt"][1]
            p1 = jax.nn.sigmoid(-(A * f + B))
            return jnp.stack([1.0 - p1, p1], axis=1)
        if "platt_pair" in model:
            k = meta["n_classes"]
            pairs = jnp.asarray(meta["pairs"])
            f = model["pair_dec"]                             # (n, P)
            A = model["platt_pair"][:, 0]                     # (P,)
            B = model["platt_pair"][:, 1]
            r = jax.nn.sigmoid(-(f * A[None, :] + B[None, :]))
            return _pairwise_coupling(_pair_probs_to_R(r, pairs, k))
        raise NotImplementedError(
            "predict_proba requires SVC(probability=True)")

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"classes_": meta["classes"],
                "n_features_in_": meta["n_features"]}


class NuSVCFamily(SVCFamily):
    """nu-SVC: same one-vs-one kernel machinery as SVC, but each pair
    subproblem solves libsvm's nu-parameterised dual (`nu_dual_ascent`)
    — box bound 1 per sample (class_weight-scaled), the two equality
    constraints split into per-class-half sum projections, and the
    decision rescaled by the KKT multiplier r.  Infeasible nu (sklearn
    raises ValueError in fit) surfaces as NaN decisions -> the search's
    failed-fit detector assigns error_score, the compiled analog of the
    host tier's raise."""

    name = "nu_svc"
    dynamic_params = {"nu": np.float32, "gamma": np.float32}
    primary_param = "nu"
    primary_default = 0.5

    @classmethod
    def _pair_dec(cls, K, p_c, base_bound, yb, step, max_iter, tol=None):
        return nu_dual_ascent(K, yb, base_bound, p_c, step, max_iter, tol)


register_family(
    SVCFamily,
    "sklearn.svm._classes.SVC",
    "sklearn.svm.SVC",
)
register_family(
    NuSVCFamily,
    "sklearn.svm._classes.NuSVC",
    "sklearn.svm.NuSVC",
)
