"""SVC family — kernel SVM re-designed for the MXU.

Reference counterpart: sklearn's SVC (libsvm SMO, one C++ working-set solve
per Spark task; BASELINE config #2 is an SVC(rbf) CxGamma grid on MNIST-10k).
SMO is a scalar, data-dependent algorithm that cannot map to a systolic
array, so the TPU redesign solves the same dual QP with **projected
gradient ascent** where every iteration is ONE kernel matmul for all
(fold x class-pair) subproblems of a candidate at once:

  max_a  1'a - 0.5 a' Q a,   0 <= a_i <= C,  sum_i y_i a_i = 0,
  Q = (y y') * K

This is the true libsvm dual, equality constraint included: each ascent
step projects onto the box-and-hyperplane set via a vectorized bisection
(`_project_box_hyperplane`) and the intercept comes from the KKT
conditions (`_kkt_intercept`, libsvm's -rho).  The step size
1/lambda_max(K) is safe for every masked subproblem because a principal
submatrix of a PSD matrix cannot have a larger top eigenvalue, and the
y-sign flip DKD is a similarity transform.

Multi-class follows sklearn: one-vs-one over all k(k-1)/2 pairs with
majority voting (confidence-scaled tie-break like _ovr_decision_function).

Deviation from libsvm (documented, tested at the accuracy level): a
fixed iteration budget instead of SMO's working-set convergence.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, encode_labels, register_family


def _pairs(k: int) -> np.ndarray:
    return np.array([(i, j) for i in range(k) for j in range(i + 1, k)],
                    dtype=np.int32)


def _kernel(X1, X2, kind, gamma, degree, coef0):
    if kind == "linear":
        return X1 @ X2.T
    if kind == "poly":
        return (gamma * (X1 @ X2.T) + coef0) ** degree
    if kind == "sigmoid":
        return jnp.tanh(gamma * (X1 @ X2.T) + coef0)
    # rbf
    sq1 = jnp.sum(X1 * X1, axis=1)
    sq2 = jnp.sum(X2 * X2, axis=1)
    d2 = sq1[:, None] - 2.0 * (X1 @ X2.T) + sq2[None, :]
    return jnp.exp(-gamma * jnp.maximum(d2, 0.0))


def _power_step(K, n, dtype):
    """1/lambda_max(K) via power iteration — a safe ascent step for every
    masked/sign-flipped subproblem (principal submatrices of a PSD matrix
    cannot have a larger top eigenvalue)."""
    v = jnp.ones((n,), dtype) / jnp.sqrt(n)

    def power(i, v):
        v = K @ v
        return v / (jnp.linalg.norm(v) + 1e-12)

    v = jax.lax.fori_loop(0, 20, power, v)
    return 1.0 / (jnp.dot(v, K @ v) + 1e-6)


def _box_fista(grad_fn, project, x0, step, max_iter, tol=None):
    """Nesterov-accelerated projected gradient on a constrained QP — the
    ONE loop behind every dual here (SVC pairs, nu-duals, SVR pairs, the
    liblinear hinge/epsilon duals): the TPU answer to libsvm/liblinear's
    sequential working-set and coordinate-descent solvers, where every
    (subproblem, sample) coordinate advances together through wide
    matmuls.  Minimises; ascent callers negate their gradient.

    With `tol=None` (SVC/NuSVC duals, which check KKT themselves) runs a
    fixed iteration count and returns `x`.  With a per-lane `tol` array
    (leading axis of x0 = lanes) it ALSO measures convergence honestly:
    the per-lane prox-gradient residual max|z - prox(z - step*grad(z))|
    divided by `step` — the generalized-gradient magnitude, so the
    criterion is scale-free in the step size (an absolute iterate-shift
    test would spuriously fire on the first iteration whenever
    1/lambda_max(Gram) < tol).  Not liblinear's dual-violation bound,
    but a real measurement rather than an assumed one.  Exits once every
    lane has converged and returns (x, n_iter, converged)."""
    dtype = x0.dtype

    if tol is None:
        def body(i, carry):
            x, z, t = carry
            x_new = project(z - step * grad_fn(z))
            t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
            z_new = x_new + ((t - 1.0) / t_new) * (x_new - x)
            return x_new, z_new, t_new

        x, _, _ = jax.lax.fori_loop(
            0, max_iter, body, (x0, x0, jnp.asarray(1.0, dtype)))
        return x

    lane_axes = tuple(range(1, x0.ndim))
    B = x0.shape[0]

    def cond(carry):
        *_, it, _n, done = carry
        return jnp.logical_and(it < max_iter,
                               jnp.logical_not(jnp.all(done)))

    def body(carry):
        x, z, t, it, n_iter, done = carry
        x_new = project(z - step * grad_fn(z))
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_new = x_new + ((t - 1.0) / t_new) * (x_new - x)
        resid = jnp.max(jnp.abs(x_new - z), axis=lane_axes) / step
        done_new = jnp.logical_or(done, resid <= tol)
        n_iter = jnp.where(jnp.logical_and(jnp.logical_not(done),
                                           done_new), it + 1, n_iter)
        return x_new, z_new, t_new, it + 1, n_iter, done_new

    x, _, _, it, n_iter, done = jax.lax.while_loop(
        cond, body,
        (x0, x0, jnp.asarray(1.0, dtype), jnp.asarray(0, jnp.int32),
         jnp.full((B,), max_iter, jnp.int32), jnp.zeros((B,), bool)))
    n_iter = jnp.where(done, n_iter, it)
    return x, n_iter, done


def _project_box_hyperplane(Z, yb, bound, n_bisect=40):
    """Euclidean projection of each row of Z onto its subproblem's feasible
    set {0 <= a_i <= bound_i} intersected with {sum_i y_i a_i = 0}.

    `bound` is per-element (C, class_weight-scaled C, or 0 outside the
    subproblem's rows).  The projection is clip(z - nu*y, 0, bound) for
    the nu making the hyperplane constraint hold; g(nu) = sum(y * clip(z
    - nu*y, 0, bound)) is monotone decreasing, so nu comes from a
    fixed-count vectorized bisection (cheap elementwise work next to the
    (M, n) @ (n, n) ascent matmul)."""
    lo = -(jnp.max(jnp.abs(Z), axis=1) + jnp.max(bound, axis=1))
    hi = -lo

    def bis(i, lh):
        lo, hi = lh
        mid = 0.5 * (lo + hi)
        a = jnp.clip(Z - mid[:, None] * yb, 0.0, bound)
        g = jnp.sum(yb * a, axis=1)
        take_hi = g > 0
        return jnp.where(take_hi, mid, lo), jnp.where(take_hi, hi, mid)

    lo, hi = jax.lax.fori_loop(0, n_bisect, bis, (lo, hi))
    nu = 0.5 * (lo + hi)
    return jnp.clip(Z - nu[:, None] * yb, 0.0, bound)


def _project_box_sum(Z, bound, target, n_bisect=40):
    """Euclidean projection of each row of Z onto
    {0 <= a_i <= bound_i, sum_i a_i = target} — clip(z - lam, 0, bound)
    for the lam making the sum hit `target` (monotone decreasing in lam,
    fixed-count vectorized bisection).  `target` is per-row (M,)."""
    zmax = jnp.max(jnp.abs(Z), axis=1) + jnp.max(bound, axis=1) + 1.0
    lo, hi = -zmax, zmax

    def bis(i, lh):
        lo, hi = lh
        mid = 0.5 * (lo + hi)
        g = jnp.sum(jnp.clip(Z - mid[:, None], 0.0, bound), axis=1)
        take_hi = g > target
        return jnp.where(take_hi, mid, lo), jnp.where(take_hi, hi, mid)

    lo, hi = jax.lax.fori_loop(0, n_bisect, bis, (lo, hi))
    mid = 0.5 * (lo + hi)
    return jnp.clip(Z - mid[:, None], 0.0, bound)


def _masked_mean_or_mid(vals, free, at_hi, at_lo):
    """libsvm's r1/r2 rule: mean of `vals` over free SVs; when none are
    free, the midpoint of [max over at-upper-bound, min over at-0]."""
    big = jnp.asarray(jnp.inf, vals.dtype)
    nfree = jnp.sum(free, axis=1)
    mean_free = jnp.sum(jnp.where(free, vals, 0.0), axis=1) / \
        jnp.maximum(nfree, 1)
    lb = jnp.max(jnp.where(at_hi, vals, -big), axis=1)
    ub = jnp.min(jnp.where(at_lo, vals, big), axis=1)
    mid = 0.5 * (lb + ub)
    mid = jnp.where(jnp.isfinite(mid), mid,
                    jnp.where(jnp.isfinite(lb), lb,
                              jnp.where(jnp.isfinite(ub), ub, 0.0)))
    return jnp.where(nfree > 0, mean_free, mid)


def nu_dual_ascent(K, yb, bound, nu, step, max_iter):
    """libsvm's nu-SVC dual (Solver_NU), batched over M subproblems:

        min_a 0.5 a'Q a,   0 <= a_i <= bound_i,
        y'a = 0,  e'a = nu * l          (l = subproblem row count)

    The two equalities DECOMPOSE over the class signs: sum over the
    positive half = sum over the negative half = nu*l/2, so each
    projection is two independent box+sum bisections — no coupled 2-D
    multiplier search.  After the solve, the KKT multipliers follow
    libsvm's calculate_rho: free +1 SVs average the gradient to r1, free
    -1 SVs to r2; the decision is rescaled by r = (r1+r2)/2 (alpha /= r,
    rho = (r1-r2)/2 / r).  Returns per-subproblem full-set decision rows;
    infeasible subproblems (nu*l/2 exceeding a half's box capacity — the
    case where sklearn raises 'specified nu is infeasible') come back as
    NaN rows for the engine's failed-fit detector.
    """
    pos_b = jnp.where(yb > 0, bound, 0.0)
    neg_b = jnp.where(yb < 0, bound, 0.0)
    l_sub = jnp.sum(bound > 0, axis=1).astype(K.dtype)
    target = 0.5 * nu * l_sub                                   # (M,)
    cap = jnp.minimum(jnp.sum(pos_b, axis=1), jnp.sum(neg_b, axis=1))
    feasible = target <= cap * (1.0 + 1e-6)

    def project(Zt):
        return _project_box_sum(Zt, pos_b, target) + \
            _project_box_sum(Zt, neg_b, target)

    def grad(Z):
        return yb * ((Z * yb) @ K)

    A = _box_fista(grad, project, project(jnp.zeros_like(bound)),
                   step, max_iter)

    V = (A * yb) @ K
    G = yb * V                         # gradient of 0.5 a'Qa
    inb = bound > 0
    at_lo = A <= bound * 1e-6
    at_hi = A >= bound * (1.0 - 1e-6)
    free = inb & ~at_lo & ~at_hi
    pos, neg = yb > 0, yb < 0
    r1 = _masked_mean_or_mid(G, free & pos, inb & pos & at_hi,
                             inb & pos & at_lo)
    r2 = _masked_mean_or_mid(G, free & neg, inb & neg & at_hi,
                             inb & neg & at_lo)
    r = 0.5 * (r1 + r2)                # lambda_e: the alpha rescale
    rho = 0.5 * (r1 - r2)              # lambda_y
    ok = jnp.logical_and(feasible, r > 1e-12)
    dec = (V - rho[:, None]) / r[:, None]
    return jnp.where(ok[:, None], dec, jnp.nan)


def _kkt_intercept(K, A, yb, bound):
    """Per-subproblem intercept b from the KKT conditions (libsvm's -rho):
    mean of E_i = y_i - f0(x_i) over free SVs; when every alpha sits at a
    bound, the midpoint of the feasible [max lower, min upper] interval."""
    V = (A * yb) @ K                                     # (M, n)
    E = yb - V
    inb = bound > 0
    at_lo = A <= bound * 1e-6
    at_hi = A >= bound * (1.0 - 1e-6)
    free = inb & ~at_lo & ~at_hi
    nfree = jnp.sum(free, axis=1)
    b_free = jnp.sum(jnp.where(free, E, 0.0), axis=1) / \
        jnp.maximum(nfree, 1)
    lo_mask = inb & ((at_lo & (yb > 0)) | (at_hi & (yb < 0)))
    up_mask = inb & ((at_lo & (yb < 0)) | (at_hi & (yb > 0)))
    big = jnp.asarray(jnp.inf, E.dtype)
    max_lo = jnp.max(jnp.where(lo_mask, E, -big), axis=1)
    min_up = jnp.min(jnp.where(up_mask, E, big), axis=1)
    b_mid = 0.5 * (max_lo + min_up)
    b_mid = jnp.where(
        jnp.isfinite(b_mid), b_mid,
        jnp.where(jnp.isfinite(max_lo), max_lo,
                  jnp.where(jnp.isfinite(min_up), min_up, 0.0)))
    return jnp.where(nfree > 0, b_free, b_mid)


def fista_dual_ascent(K, yb, bound, step, max_iter):
    """Nesterov-accelerated projected gradient ascent on the SVM dual

        max_a  1'a - 0.5 a' Q a,   0 <= a_i <= bound_i,
        sum_i y_i a_i = 0

    (the true libsvm dual, equality constraint included; per-sample upper
    bounds carry both the subproblem box mask and class_weight-scaled C).
    K: (n, n) kernel; yb/bound: (M, n) signed labels and box bounds for M
    subproblems advanced together — every iteration is ONE (M, n) @ (n, n)
    matmul plus a vectorized hyperplane projection.  Returns (A, b):
    alphas and the KKT intercept per subproblem.  Shared by the search's
    task-batched fit and the standalone SVC so the numerics live once.
    """

    def grad(Z):                       # descent form of the ascent grad
        return -(1.0 - yb * ((Z * yb) @ K))

    A = _box_fista(
        grad, lambda Zt: _project_box_hyperplane(Zt, yb, bound),
        jnp.zeros_like(bound), step, max_iter)
    return A, _kkt_intercept(K, A, yb, bound)


def _platt_fit(f, t, w, n_iter=50):
    """Vectorized Platt sigmoid calibration: per task (leading axis),
    minimise the weighted logloss of P(y=1|f) = sigmoid(-(A*f + B))
    against Platt's smoothed targets `t` with sample weights `w`, by
    damped Newton on the 2-parameter convex problem (closed-form 2x2
    solve per task — libsvm's sigmoid_train, batched).

    Returns (A, B) arrays of shape f.shape[:1]."""
    B_ = f.shape[0]
    dtype = f.dtype
    wsum = jnp.sum(w, axis=1) + 1e-12
    # libsvm init: A=0, B=log((prior0+1)/(prior1+1)) from the targets
    np_w = jnp.sum(w * t, axis=1)
    nn_w = wsum - np_w
    A0 = jnp.zeros((B_,), dtype)
    B0 = jnp.log((nn_w + 1.0) / (np_w + 1.0))

    def body(i, carry):
        A, Bb = carry
        u = A[:, None] * f + Bb[:, None]
        s = jax.nn.sigmoid(u)                    # = 1 - p
        r = w * (s - (1.0 - t))                  # dL/du per sample
        gA = jnp.sum(r * f, axis=1)
        gB = jnp.sum(r, axis=1)
        h = w * s * (1.0 - s)
        hAA = jnp.sum(h * f * f, axis=1) + 1e-9
        hAB = jnp.sum(h * f, axis=1)
        hBB = jnp.sum(h, axis=1) + 1e-9
        det = hAA * hBB - hAB * hAB
        dA = (hBB * gA - hAB * gB) / det
        dB = (hAA * gB - hAB * gA) / det
        return A - dA, Bb - dB

    A, Bb = jax.lax.fori_loop(0, n_iter, body, (A0, B0))
    return A, Bb


def _resolve_gamma(gamma, meta):
    if isinstance(gamma, str):
        if gamma == "scale":
            # X variance precomputed host-side in prepare_data
            return 1.0 / (meta["n_features"] * meta["x_var"])
        if gamma == "auto":
            return 1.0 / meta["n_features"]
        raise ValueError(f"gamma={gamma!r} not understood")
    return float(gamma)


class SVCFamily(Family):
    name = "svc"
    is_classifier = True
    dynamic_params = {"C": np.float32, "gamma": np.float32}
    #: the per-candidate scalar the dual consumes (NuSVC swaps in "nu")
    primary_param = "C"
    primary_default = 1.0
    #: the task-batched fit understands per-fold-transformed inputs
    #: (data["X_folds"], shape (F, n, d)) — what compiled Pipelines feed it
    task_batched_accepts_fold_inputs = True

    @classmethod
    def _pair_dec(cls, K, p_c, base_bound, yb, step, max_iter):
        """Solve the M stacked pair subproblems and return their (M, n)
        full-set decision rows.  `p_c` is the candidate's primary scalar
        (C here: scales the box), `base_bound` the fold/weight/pair box
        mask."""
        bound = p_c * base_bound
        A, b = fista_dual_ascent(K, yb, bound, step, max_iter)
        return (A * yb) @ K + b[:, None]

    # kernel matrices + per-task decision caches are the memory hot spot;
    # tell the search to keep task batches small
    @staticmethod
    def max_tasks_hint(n_samples: int, meta) -> int:
        k = meta["n_classes"]
        p = max(1, k * (k - 1) // 2)
        budget = 1 << 30   # ~1 GiB of decision cache per launch
        return max(1, budget // max(1, n_samples * p * 4))

    @classmethod
    def extract_params(cls, estimator):
        params = dict(estimator.get_params(deep=False))
        return params

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        classes, y_enc = encode_labels(y)
        k = len(classes)
        data = {
            "X": np.ascontiguousarray(X, dtype=dtype),
            "y": y_enc,
        }
        meta = {"n_classes": int(k), "classes": classes,
                "n_features": int(X.shape[1]),
                "x_var": float(np.var(np.asarray(X))),
                "pairs": _pairs(k)}
        return data, meta

    @classmethod
    def fit_task_batched(cls, dynamic, static, data, train_w, meta):
        """Tasks arrive candidate-major (task t = (cand t//F, fold t%F)).
        One `lax.scan` step per candidate: its kernel matrix is built once
        and shared by every (fold x pair) subproblem, which are advanced
        together — each ascent iteration is a single (F*P, n) @ (n, n)
        matmul.  Returns per-task full-dataset pair decisions (the search
        scores on masked rows of the training X, so caching decisions
        avoids rebuilding kernels in the scoring phase)."""
        X = data["X"]
        y = data["y"]
        n, d = X.shape
        k = meta["n_classes"]
        pairs = jnp.asarray(meta["pairs"])                    # (P, 2)
        P = pairs.shape[0]
        B = train_w.shape[0]
        kind = static.get("kernel", "rbf")
        if kind == "precomputed":
            raise ValueError("precomputed kernels: use backend='host'")
        degree = float(static.get("degree", 3))
        coef0 = float(static.get("coef0", 0.0))
        max_iter = int(static.get("max_iter", -1))
        if max_iter in (-1, 0):
            max_iter = 300
        # tasks are candidate-major with a fixed fold count injected by the
        # engine; the candidate count is B // n_folds
        n_folds = int(static.get("__n_folds__", 0))
        if n_folds <= 0:
            raise ValueError("engine must pass __n_folds__ for SVC")
        nc = B // n_folds

        gamma_default = _resolve_gamma(static.get("gamma", "scale"), meta)
        pp = cls.primary_param
        C_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get(pp, static.get(pp, cls.primary_default)),
            X.dtype), (B,))
        g_task = jnp.broadcast_to(jnp.asarray(
            dynamic.get("gamma", gamma_default), X.dtype), (B,))
        C_cand = C_task.reshape(nc, n_folds)[:, 0]
        g_cand = g_task.reshape(nc, n_folds)[:, 0]
        w_cand = train_w.reshape(nc, n_folds, n)

        # per-pair signed labels: +1 for pairs[p,0], -1 for pairs[p,1]
        ypos = (y[None, :] == pairs[:, 0][:, None])
        yneg = (y[None, :] == pairs[:, 1][:, None])
        ybin = ypos.astype(X.dtype) - yneg.astype(X.dtype)    # (P, n)
        if k == 2:
            # sklearn convention: binary decision_function > 0 -> classes_[1]
            ybin = -ybin
        in_pair = (ypos | yneg).astype(X.dtype)               # (P, n)

        X_folds = data.get("X_folds")     # (F, n, d) fold-transformed, or
        # None (plain SVC: one shared X, one kernel per candidate)
        gamma_is_scale = "gamma" not in dynamic and \
            static.get("gamma", "scale") == "scale"

        # class_weight scales each sample's box bound: 0 <= a_i <= C * cw_i
        # (libsvm's per-class C); "balanced" follows each fold's counts
        from spark_sklearn_tpu.models.base import class_weight_multiplier
        w_fold_masks = train_w.reshape(nc, n_folds, n)[0]     # (F, n)
        cw_fold = class_weight_multiplier(
            w_fold_masks, y, meta, static.get("class_weight"))
        if cw_fold is None:
            cw_fold = jnp.ones((n_folds, n), X.dtype)

        def one_candidate(carry, inp):
            C_c, g_c, w_f = inp                               # w_f (F, n)
            if X_folds is None:
                K = _kernel(X, X, kind, g_c, degree, coef0)   # (n, n)
                step = _power_step(K, n, X.dtype)
                # subproblem box masks: (F, P, n) -> flatten (F*P, n)
                base = ((w_f * cw_fold)[:, None, :]
                        * in_pair[None, :, :]).reshape(-1, n)
                yb = jnp.broadcast_to(
                    ybin[None], (n_folds, P, n)).reshape(-1, n)
                dec = cls._pair_dec(
                    K, C_c, base, yb, step, max_iter).reshape(
                    n_folds, P, n)
            else:
                # pipeline mode: each fold has its own transformed X, so
                # kernels are per (candidate, fold); the P pair
                # subproblems of a fold advance together and folds batch
                # via vmap (an (F, P, n) x (F, n, n) bmm on the MXU).
                # gamma='scale' must follow the TRANSFORMED fold X
                # (sklearn resolves it on the X the final step receives).
                def per_fold(Xf, w_row, cw_row):
                    if gamma_is_scale:
                        mrow = (w_row > 0).astype(Xf.dtype)
                        cnt = jnp.sum(mrow) * Xf.shape[1] + 1e-12
                        mu = jnp.sum(Xf * mrow[:, None]) / cnt
                        var = jnp.sum(((Xf - mu) ** 2)
                                      * mrow[:, None]) / cnt
                        g_f = 1.0 / (Xf.shape[1]
                                     * jnp.maximum(var, 1e-12))
                    else:
                        g_f = g_c
                    Kf = _kernel(Xf, Xf, kind, g_f, degree, coef0)
                    step = _power_step(Kf, n, Xf.dtype)
                    base = (w_row * cw_row)[None, :] * in_pair
                    return cls._pair_dec(
                        Kf, C_c, base, ybin, step, max_iter)  # (P, n)

                dec = jax.vmap(per_fold)(X_folds, w_f, cw_fold)  # (F,P,n)
            return carry, jnp.transpose(dec, (0, 2, 1))       # (F, n, P)

        _, decs = jax.lax.scan(
            one_candidate, 0.0, (C_cand, g_cand, w_cand))
        # (nc, F, n, P) -> task-major (B, n, P)
        model = {"pair_dec": decs.reshape(B, n, P)}
        if bool(static.get("probability", False)) and k == 2:
            # compiled Platt scaling (binary): calibrate a sigmoid on the
            # TRAIN-fold decision values per task, stored with the model
            # so predict_proba / neg_log_loss scoring stay compiled.
            # Approximation vs libsvm: libsvm calibrates on internal
            # 5-fold CV decisions; these are in-sample train decisions
            # (slightly overconfident — documented in docs/ROADMAP.md).
            # Multiclass (pairwise coupling) stays on the host path.
            fdec = model["pair_dec"][:, :, 0]                 # (B, n)
            ypos = (y == 1).astype(X.dtype)[None, :]          # classes_[1]
            np_w = jnp.sum(train_w * ypos, axis=1)
            nn_w = jnp.sum(train_w * (1.0 - ypos), axis=1)
            t_pos = (np_w + 1.0) / (np_w + 2.0)
            t_neg = 1.0 / (nn_w + 2.0)
            t = jnp.where(ypos > 0, t_pos[:, None], t_neg[:, None])
            A, Bb = _platt_fit(fdec, t, train_w)
            model["platt"] = jnp.stack([A, Bb], axis=1)       # (B, 2)
        return model

    # -- prediction from cached decisions (search-internal) ---------------
    @classmethod
    def _votes(cls, dec, meta):
        pairs = jnp.asarray(meta["pairs"])                    # (P, 2)
        k = meta["n_classes"]
        P = pairs.shape[0]
        pos_mat = jax.nn.one_hot(pairs[:, 0], k, dtype=dec.dtype)  # (P, k)
        neg_mat = jax.nn.one_hot(pairs[:, 1], k, dtype=dec.dtype)
        win_pos = (dec > 0).astype(dec.dtype)                 # (n, P)
        votes = win_pos @ pos_mat + (1.0 - win_pos) @ neg_mat
        # confidence tie-break, bounded to (-.5, .5) like sklearn's
        # _ovr_decision_function
        conf = dec @ pos_mat - dec @ neg_mat                  # (n, k)
        conf = conf / (3.0 * (jnp.abs(conf) + 1.0))
        return votes + conf

    @classmethod
    def predict(cls, model, static, X, meta):
        if meta["n_classes"] == 2:
            return (model["pair_dec"][:, 0] > 0).astype(jnp.int32)
        return jnp.argmax(cls._votes(model["pair_dec"], meta),
                          axis=1).astype(jnp.int32)

    @classmethod
    def decision(cls, model, static, X, meta):
        if meta["n_classes"] == 2:
            return model["pair_dec"][:, 0]
        return cls._votes(model["pair_dec"], meta)

    @classmethod
    def predict_proba(cls, model, static, X, meta):
        """Compiled Platt probabilities (binary, probability=True —
        calibration fitted alongside the duals in fit_task_batched).
        Multiclass pairwise coupling is not compiled: raising here sends
        proba-scoring searches to the host tier, and user-facing
        predict_proba comes from the sklearn refit best_estimator_."""
        if "platt" not in model:
            raise NotImplementedError(
                "predict_proba is compiled only for binary "
                "SVC(probability=True)")
        f = model["pair_dec"][:, 0]
        A, B = model["platt"][0], model["platt"][1]
        p1 = jax.nn.sigmoid(-(A * f + B))
        return jnp.stack([1.0 - p1, p1], axis=1)

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {"classes_": meta["classes"],
                "n_features_in_": meta["n_features"]}


class NuSVCFamily(SVCFamily):
    """nu-SVC: same one-vs-one kernel machinery as SVC, but each pair
    subproblem solves libsvm's nu-parameterised dual (`nu_dual_ascent`)
    — box bound 1 per sample (class_weight-scaled), the two equality
    constraints split into per-class-half sum projections, and the
    decision rescaled by the KKT multiplier r.  Infeasible nu (sklearn
    raises ValueError in fit) surfaces as NaN decisions -> the search's
    failed-fit detector assigns error_score, the compiled analog of the
    host tier's raise."""

    name = "nu_svc"
    dynamic_params = {"nu": np.float32, "gamma": np.float32}
    primary_param = "nu"
    primary_default = 0.5

    @classmethod
    def _pair_dec(cls, K, p_c, base_bound, yb, step, max_iter):
        return nu_dual_ascent(K, yb, base_bound, p_c, step, max_iter)


register_family(
    SVCFamily,
    "sklearn.svm._classes.SVC",
    "sklearn.svm.SVC",
)
register_family(
    NuSVCFamily,
    "sklearn.svm._classes.NuSVC",
    "sklearn.svm.NuSVC",
)
