"""Compiled sklearn Pipeline support.

Reference behavior: a Pipeline is just another estimator cloned and fitted
whole inside each Spark task, with grid keys like "mlp__alpha" routed by
sklearn's set_params (BASELINE config #5).  Here a Pipeline whose
transformers are all registered preprocessing steps and whose final step is
a compiled family becomes a **fused family**: transformer statistics are
weighted by the fold mask, the transform feeds the final fit inside the same
XLA program (no materialised intermediates), and "step__param" grid keys are
routed to dynamic/static leaves (SURVEY §7.3 hard part #5).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from spark_sklearn_tpu.models import preprocessing as prep
from spark_sklearn_tpu.models.base import resolve_family
from spark_sklearn_tpu.utils.checkpoint import fingerprint


class PipelineFamily:
    """Instance-level family (duck-typed to the Family protocol) built for a
    concrete sklearn Pipeline."""

    #: sklearn raises on a bare sample_weight to Pipeline.fit (step
    #: routing requires "step__sample_weight"); weighted searches take the
    #: host path so that contract is reproduced, not silently reinvented
    accepts_sample_weight = False

    def __init__(self, steps: List[Tuple[str, Any]], final_name: str,
                 final_family):
        self.steps = steps              # [(name, StepImpl), ...] transformers
        self.final_name = final_name
        self.final = final_family
        self.name = f"pipeline({'+'.join(n for n, _ in steps)}" \
                    f"+{final_family.name})"
        self.is_classifier = final_family.is_classifier
        # the sklearn twin's proba dtype is the FINAL step's fact (the
        # transformers only feed it X) — forward it so log_loss clips
        # where the oracle pipeline clips
        self.proba_dtype_rule = getattr(
            final_family, "proba_dtype_rule", "float64")
        self.dynamic_params = {
            f"{final_name}__{k}": v
            for k, v in final_family.dynamic_params.items()
        }
        self._suffix_family: Optional["PipelineFamily"] = None
        if not final_family.has_per_task_fit() and \
                getattr(final_family, "task_batched_accepts_fold_inputs",
                        False):
            # task-batched-only finals (SVC): compose by feeding per-fold
            # transformed inputs into the final's task-batched fit
            self.fit_task_batched = self._fit_task_batched_folds
            hint = getattr(final_family, "max_tasks_hint", None)
            if hint is not None:
                self.max_tasks_hint = hint
        # forward the final step's default scorer (e.g. KMeans -> -inertia)
        # through the transformer chain
        final_default = getattr(final_family, "default_scorer", None)
        if final_default is not None:
            def default_scorer(family, model, static, data, meta, w,
                               _fd=final_default):
                Xt = family._transform(model, static, data["X"])
                return _fd(family.final, model["final"],
                           family._final_static(static),
                           {**data, "X": Xt}, meta, w)
            self.default_scorer = default_scorer

    def has_per_task_fit(self) -> bool:
        # task-batched-only finals (SVC) have no per-task fit to compose:
        # dispatchers that vmap one fit per lane (the keyed fleet) must
        # take their host path instead of tracing into NotImplementedError
        return self.final.has_per_task_fit()

    # -- host side -------------------------------------------------------
    def extract_params(self, estimator) -> Dict[str, Any]:
        out = {}
        for sname, step_est in estimator.named_steps.items():
            for k, v in step_est.get_params(deep=False).items():
                out[f"{sname}__{k}"] = v
        return out

    def prepare_data(self, X, y, dtype=np.float32):
        return self.final.prepare_data(X, y, dtype=dtype)

    def _split_static(self, static):
        per_step: Dict[str, Dict[str, Any]] = {n: {} for n, _ in self.steps}
        per_step[self.final_name] = {}
        for key, v in static.items():
            if "__" not in key:
                continue
            sname, pname = key.split("__", 1)
            if sname in per_step:
                per_step[sname][pname] = v
        return per_step

    # -- shared-prefix search support ------------------------------------
    def prefix_digest(self, static) -> Optional[str]:
        """Content digest of the transformer-chain configuration.

        Candidates whose digests match see the identical transformed
        design matrix: every step's params are static (steps expose no
        dynamic leaves) and the only other fit input is the fold mask,
        which the shared-prefix scheduler keys separately.  The final
        step's params are deliberately EXCLUDED — compile groups that
        differ only in final-step statics share the digest, so the
        cached prefix is reused across groups too.  None when the
        chain is empty (depth 0) or a step opted out of prefix safety.
        """
        if not self.steps:
            return None
        per_step = self._split_static(static)
        parts = []
        for sname, step in self.steps:
            if not getattr(step, "prefix_safe", False):
                return None
            parts.append((sname, getattr(step, "name", step.__name__),
                          tuple(sorted((k, repr(v)) for k, v in
                                       per_step[sname].items()))))
        return fingerprint("prefix-v1", tuple(parts))

    def prefix_transform(self, static, data, fold_w):
        """Prefix-only compiled transform: fold masks (F, n) -> the
        stacked per-fold transformed design matrix (F, n, d') with the
        exact mask-weighted statistics the fused fit computes inline
        (same ops, same order — the split is bit-exact by
        construction)."""
        import jax

        per_step = self._split_static(static)

        def tf(w_f):
            X = data["X"]
            for sname, step in self.steps:
                st = step.fit(per_step[sname], X, w_f)
                X = step.apply(per_step[sname], st, X)
            return X

        return jax.vmap(tf)(fold_w)                    # (F, n, d')

    def suffix_family(self) -> "PipelineFamily":
        """The final-step-only family the shared-prefix scheduler fans
        over cached prefix matrices.  Cached per parent instance so
        program-cache keys (which hash family identity) stay stable
        across chunks/rungs; the name is distinct from the atomic
        pipeline's so persistent-store artifacts never alias programs
        traced on untransformed shapes."""
        if self._suffix_family is None:
            fam = PipelineFamily([], self.final_name, self.final)
            fam.name = f"suffix[{self.name}]"
            self._suffix_family = fam
        return self._suffix_family

    # -- device side -----------------------------------------------------
    def fit(self, dynamic, static, data, train_w, meta):
        per_step = self._split_static(static)
        final_dynamic = {
            k.split("__", 1)[1]: v for k, v in dynamic.items()
            if k.startswith(f"{self.final_name}__")
        }
        X = data["X"]
        states = []
        for sname, step in self.steps:
            st = step.fit(per_step[sname], X, train_w)
            X = step.apply(per_step[sname], st, X)
            states.append(st)
        final_model = self.final.fit(
            final_dynamic, per_step[self.final_name],
            {**data, "X": X}, train_w, meta)
        return {"steps": states, "final": final_model}

    def _fit_task_batched_folds(self, dynamic, static, data, w_task, meta):
        """Task-batched composition: the transformer chain is fitted per
        FOLD (first candidate's fold masks — tasks are candidate-major
        with identical fold masks across candidates) and the stacked
        (F, n, d) result feeds the final family's task-batched fit via
        data["X_folds"].  The final (SVC) caches full-dataset decisions,
        so scoring never needs the transformed X back."""
        import jax

        per_step = self._split_static(static)
        n_folds = int(static.get("__n_folds__", 0))
        if n_folds <= 0:
            raise ValueError("engine must pass __n_folds__")
        fold_w = w_task[:n_folds]                      # (F, n)

        def tf(w_f):
            X = data["X"]
            for sname, step in self.steps:
                st = step.fit(per_step[sname], X, w_f)
                X = step.apply(per_step[sname], st, X)
            return X

        X_folds = jax.vmap(tf)(fold_w)                 # (F, n, d')
        final_dynamic = {
            k.split("__", 1)[1]: v for k, v in dynamic.items()
            if k.startswith(f"{self.final_name}__")
        }
        final_static = {**per_step[self.final_name],
                        "__n_folds__": n_folds,
                        "__bf16__": static.get("__bf16__", False)}
        model = self.final.fit_task_batched(
            final_dynamic, final_static, {**data, "X_folds": X_folds},
            w_task, meta)
        # steps=None marks decision-cached mode: _transform is skipped
        # (the final never consumes X at scoring time)
        return {"steps": None, "final": model}

    def _transform(self, model, static, X):
        if model["steps"] is None:       # decision-cached task-batched mode
            return X
        per_step = self._split_static(static)
        for (sname, step), st in zip(self.steps, model["steps"]):
            X = step.apply(per_step[sname], st, X)
        return X

    def _final_static(self, static):
        return self._split_static(static)[self.final_name]

    def predict(self, model, static, X, meta):
        X = self._transform(model, static, X)
        return self.final.predict(model["final"], self._final_static(static),
                                  X, meta)

    def decision(self, model, static, X, meta):
        X = self._transform(model, static, X)
        return self.final.decision(model["final"],
                                   self._final_static(static), X, meta)

    def predict_proba(self, model, static, X, meta):
        X = self._transform(model, static, X)
        return self.final.predict_proba(
            model["final"], self._final_static(static), X, meta)

    def sklearn_attrs(self, model, static, meta):
        return self.final.sklearn_attrs(
            model["final"], self._final_static(static), meta)


class BinnedInvariantPipelineFamily:
    """Pipeline of monotone per-feature scalers feeding a histogram-tree
    final.  Quantile binning is invariant under strictly monotone
    per-feature maps, so the scaler steps provably cannot change the
    binned codes the tree consumes: the compiled fit/score delegate
    straight to the final family (the transform is the identity on
    codes), keeping scaler+GBDT/RF grids fully compiled — the TPU-first
    answer to BASELINE-config-#4/#5-shaped pipelines."""

    accepts_sample_weight = False    # same Pipeline.fit contract as above

    def __init__(self, final_name: str, final_family):
        self.final_name = final_name
        self.final = final_family
        self.name = f"pipeline(binned-invariant+{final_family.name})"
        self.is_classifier = final_family.is_classifier
        self.keyed_compatible = False
        self.dynamic_params = {
            f"{final_name}__{k}": v
            for k, v in final_family.dynamic_params.items()
        }

    def has_per_task_fit(self) -> bool:
        return True

    def _strip(self, d):
        pref = f"{self.final_name}__"
        return {k[len(pref):]: v for k, v in d.items()
                if k.startswith(pref)}

    def extract_params(self, estimator) -> Dict[str, Any]:
        out = {}
        for sname, step_est in estimator.named_steps.items():
            for k, v in step_est.get_params(deep=False).items():
                out[f"{sname}__{k}"] = v
        return out

    def prepare_data(self, X, y, dtype=np.float32):
        return self.final.prepare_data(X, y, dtype=dtype)

    def observe_candidates(self, candidates, base_params, meta):
        if hasattr(self.final, "observe_candidates"):
            self.final.observe_candidates(
                [self._strip(c) for c in candidates],
                self._strip(base_params), meta)

    def fit(self, dynamic, static, data, train_w, meta):
        return self.final.fit(self._strip(dynamic), self._strip(static),
                              data, train_w, meta)

    def predict(self, model, static, X, meta):
        return self.final.predict(model, self._strip(static), X, meta)

    def decision(self, model, static, X, meta):
        return self.final.decision(model, self._strip(static), X, meta)

    def predict_proba(self, model, static, X, meta):
        return self.final.predict_proba(model, self._strip(static), X,
                                        meta)

    def sklearn_attrs(self, model, static, meta):
        return self.final.sklearn_attrs(model, self._strip(static), meta)


def make_pipeline_family(pipeline):
    """Pipeline instance -> a pipeline family, or None when any step is
    outside the compiled registries (-> Tier B host path runs the pipeline
    whole)."""
    try:
        steps = list(pipeline.steps)
    except AttributeError:
        return None
    if not steps:
        return None
    *transformers, (final_name, final_est) = steps
    resolved = []
    for sname, t in transformers:
        if t is None or t == "passthrough":
            continue
        step = prep.resolve_step(t)
        if step is None:
            return None
        resolved.append((sname, step))
    final_family = resolve_family(final_est)
    if final_family is None or isinstance(
            final_family, (PipelineFamily, BinnedInvariantPipelineFamily)):
        return None
    if not getattr(final_family, "keyed_compatible", True):
        # tree finals consume pre-binned "codes"; they compose only with
        # monotone per-feature steps, under which the codes are provably
        # unchanged (anything else -> Tier B)
        if all(getattr(s, "monotone_per_feature", False)
               for _, s in resolved):
            return BinnedInvariantPipelineFamily(final_name, final_family)
        return None
    if not final_family.has_per_task_fit() and not getattr(
            final_family, "task_batched_accepts_fold_inputs", False):
        # task-batched-only finals must understand per-fold inputs
        return None
    return PipelineFamily(resolved, final_name, final_family)
