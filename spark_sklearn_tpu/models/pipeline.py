"""Compiled sklearn Pipeline support.

Reference behavior: a Pipeline is just another estimator cloned and fitted
whole inside each Spark task, with grid keys like "mlp__alpha" routed by
sklearn's set_params (BASELINE config #5).  Here a Pipeline whose
transformers are all registered preprocessing steps and whose final step is
a compiled family becomes a **fused family**: transformer statistics are
weighted by the fold mask, the transform feeds the final fit inside the same
XLA program (no materialised intermediates), and "step__param" grid keys are
routed to dynamic/static leaves (SURVEY §7.3 hard part #5).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from spark_sklearn_tpu.models import preprocessing as prep
from spark_sklearn_tpu.models.base import resolve_family


class PipelineFamily:
    """Instance-level family (duck-typed to the Family protocol) built for a
    concrete sklearn Pipeline."""

    def __init__(self, steps: List[Tuple[str, Any]], final_name: str,
                 final_family):
        self.steps = steps              # [(name, StepImpl), ...] transformers
        self.final_name = final_name
        self.final = final_family
        self.name = f"pipeline({'+'.join(n for n, _ in steps)}" \
                    f"+{final_family.name})"
        self.is_classifier = final_family.is_classifier
        self.dynamic_params = {
            f"{final_name}__{k}": v
            for k, v in final_family.dynamic_params.items()
        }
        # forward the final step's default scorer (e.g. KMeans -> -inertia)
        # through the transformer chain
        final_default = getattr(final_family, "default_scorer", None)
        if final_default is not None:
            def default_scorer(family, model, static, data, meta, w,
                               _fd=final_default):
                Xt = family._transform(model, static, data["X"])
                return _fd(family.final, model["final"],
                           family._final_static(static),
                           {**data, "X": Xt}, meta, w)
            self.default_scorer = default_scorer

    def has_per_task_fit(self) -> bool:
        return True

    # -- host side -------------------------------------------------------
    def extract_params(self, estimator) -> Dict[str, Any]:
        out = {}
        for sname, step_est in estimator.named_steps.items():
            for k, v in step_est.get_params(deep=False).items():
                out[f"{sname}__{k}"] = v
        return out

    def prepare_data(self, X, y, dtype=np.float32):
        return self.final.prepare_data(X, y, dtype=dtype)

    def _split_static(self, static):
        per_step: Dict[str, Dict[str, Any]] = {n: {} for n, _ in self.steps}
        per_step[self.final_name] = {}
        for key, v in static.items():
            if "__" not in key:
                continue
            sname, pname = key.split("__", 1)
            if sname in per_step:
                per_step[sname][pname] = v
        return per_step

    # -- device side -----------------------------------------------------
    def fit(self, dynamic, static, data, train_w, meta):
        per_step = self._split_static(static)
        final_dynamic = {
            k.split("__", 1)[1]: v for k, v in dynamic.items()
            if k.startswith(f"{self.final_name}__")
        }
        X = data["X"]
        states = []
        for sname, step in self.steps:
            st = step.fit(per_step[sname], X, train_w)
            X = step.apply(per_step[sname], st, X)
            states.append(st)
        final_model = self.final.fit(
            final_dynamic, per_step[self.final_name],
            {**data, "X": X}, train_w, meta)
        return {"steps": states, "final": final_model}

    def _transform(self, model, static, X):
        per_step = self._split_static(static)
        for (sname, step), st in zip(self.steps, model["steps"]):
            X = step.apply(per_step[sname], st, X)
        return X

    def _final_static(self, static):
        return self._split_static(static)[self.final_name]

    def predict(self, model, static, X, meta):
        X = self._transform(model, static, X)
        return self.final.predict(model["final"], self._final_static(static),
                                  X, meta)

    def decision(self, model, static, X, meta):
        X = self._transform(model, static, X)
        return self.final.decision(model["final"],
                                   self._final_static(static), X, meta)

    def predict_proba(self, model, static, X, meta):
        X = self._transform(model, static, X)
        return self.final.predict_proba(
            model["final"], self._final_static(static), X, meta)

    def sklearn_attrs(self, model, static, meta):
        return self.final.sklearn_attrs(
            model["final"], self._final_static(static), meta)


def make_pipeline_family(pipeline) -> Optional[PipelineFamily]:
    """Pipeline instance -> PipelineFamily, or None when any step is outside
    the compiled registries (-> Tier B host path runs the pipeline whole)."""
    try:
        steps = list(pipeline.steps)
    except AttributeError:
        return None
    if not steps:
        return None
    *transformers, (final_name, final_est) = steps
    resolved = []
    for sname, t in transformers:
        if t is None or t == "passthrough":
            continue
        step = prep.resolve_step(t)
        if step is None:
            return None
        resolved.append((sname, step))
    final_family = resolve_family(final_est)
    if final_family is None or isinstance(final_family, PipelineFamily):
        return None
    if not final_family.has_per_task_fit():
        # families exposing only fit_task_batched (SVC) can't compose with
        # per-task fold-transformed inputs yet -> whole pipeline to Tier B
        return None
    if not getattr(final_family, "keyed_compatible", True):
        # tree families consume pre-binned "codes", not the raw "X" the
        # transformer chain produces -> whole pipeline to Tier B
        return None
    return PipelineFamily(resolved, final_name, final_family)
