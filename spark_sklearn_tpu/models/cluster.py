"""KMeans family — Lloyd's algorithm as matmuls.

Reference counterpart: sklearn KMeans running whole inside Spark tasks
(and as a KeyedEstimator clusterer — reference: keyed_models.py
estimatorType="clusterer").  Lloyd maps perfectly to the MXU:

  - distances: ||x - c||^2 = ||x||^2 + ||c||^2 - 2 x.c — one (n, d)x(d, k)
    matmul per iteration;
  - center update: one-hot(assignments)^T @ X — one (k, n)x(n, d) matmul
    (no scatter);
  - k-means++ seeding: a `fori_loop` over k centers, each step one
    distance update + a Gumbel-max categorical draw over the weighted
    min-distances (sklearn's D^2 sampling, minus its local-trial
    refinement — accuracy-level parity, oracle-tested).

Fold masks enter as sample weights in both the sampling probabilities and
the center updates, like every other family.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from spark_sklearn_tpu.models.base import Family, register_family


def _sq_dists(X, C):
    """(n, k) squared distances via the matmul identity."""
    xx = jnp.sum(X * X, axis=1, keepdims=True)
    cc = jnp.sum(C * C, axis=1)
    return jnp.maximum(xx - 2.0 * (X @ C.T) + cc[None, :], 0.0)


def _neg_inertia(family, model, static, data, meta, w):
    """Default scorer: sklearn's KMeans.score = -inertia on the fold."""
    d2 = _sq_dists(data["X"], model["centers"])
    return -jnp.sum(w * jnp.min(d2, axis=1))


class KMeansFamily(Family):
    name = "kmeans"
    is_classifier = False
    dynamic_params = {"tol": np.float32}
    default_scorer = staticmethod(_neg_inertia)

    @classmethod
    def min_group_size(cls, static) -> int:
        # a fit needs at least n_clusters real samples (sklearn raises on
        # fewer; padded fleet groups must fall back instead of silently
        # seeding centers from zero-padding)
        return int(static.get("n_clusters", 8))

    @classmethod
    def prepare_data(cls, X, y, dtype=np.float32):
        data = {"X": np.ascontiguousarray(X, dtype=dtype)}
        if y is not None:
            y_arr = np.asarray(y)
            if np.issubdtype(y_arr.dtype, np.number):
                data["y"] = y_arr   # object labels never reach the device
        meta = {"n_features": int(X.shape[1])}
        return data, meta

    @classmethod
    def fit(cls, dynamic, static, data, train_w, meta):
        X = data["X"]
        n, d = X.shape
        k = int(static.get("n_clusters", 8))
        max_iter = int(static.get("max_iter", 300))
        # sklearn scales tol by the mean feature variance of the FIT-TIME
        # X (_kmeans.py _tolerance) — weighted, so zero-weight padding rows
        # (keyed fleets) don't deflate a key's own variance scale
        w0 = train_w
        wsum0 = jnp.sum(w0) + 1e-12
        xbar = (w0 @ X) / wsum0
        wvar = (w0 @ ((X - xbar) ** 2)) / wsum0
        tol = jnp.asarray(dynamic.get("tol", static.get("tol", 1e-4)),
                          X.dtype) * jnp.mean(wvar)
        seed = static.get("random_state")
        base_key = jax.random.PRNGKey(0 if seed is None else int(seed))
        init = static.get("init", "k-means++")
        if not isinstance(init, str) or init not in ("k-means++", "random"):
            raise ValueError(
                f"init={init!r} is not compiled; use backend='host'")
        n_init = static.get("n_init", "auto")
        if n_init == "auto":
            n_init = 1 if init == "k-means++" else 10
        n_init = int(n_init)
        w = train_w

        def seed_centers(key):
            if init == "random":
                idx = jax.random.choice(
                    key, n, (k,), replace=False,
                    p=w / (jnp.sum(w) + 1e-12))
                return X[idx]
            # k-means++ D^2 sampling
            k0, key = jax.random.split(key)
            logw = jnp.where(w > 0, jnp.log(w + 1e-12), -jnp.inf)
            first = jnp.argmax(logw + jax.random.gumbel(k0, (n,)))
            C0 = jnp.zeros((k, d), X.dtype).at[0].set(X[first])
            min_d2 = jnp.sum((X - X[first]) ** 2, axis=1)

            def place(i, carry):
                C, min_d2, key = carry
                key, kk = jax.random.split(key)
                logits = jnp.where(
                    (w > 0) & (min_d2 > 0),
                    jnp.log(w * min_d2 + 1e-30), -jnp.inf)
                nxt = jnp.argmax(logits + jax.random.gumbel(kk, (n,)))
                C = C.at[i].set(X[nxt])
                min_d2 = jnp.minimum(
                    min_d2, jnp.sum((X - X[nxt]) ** 2, axis=1))
                return C, min_d2, key

            C0, _, _ = jax.lax.fori_loop(1, k, place, (C0, min_d2, key))
            return C0

        def lloyd(C0):
            def cond(carry):
                C, prev_shift, it = carry
                return jnp.logical_and(it < max_iter, prev_shift > tol)

            def body(carry):
                C, _, it = carry
                d2 = _sq_dists(X, C)
                assign = jnp.argmin(d2, axis=1)
                oh = jax.nn.one_hot(assign, k, dtype=X.dtype) * w[:, None]
                counts = jnp.sum(oh, axis=0)                   # (k,)
                sums = oh.T @ X                                # (k, d)
                C_new = jnp.where(
                    counts[:, None] > 0,
                    sums / jnp.maximum(counts[:, None], 1e-12),
                    C)                                         # keep empties
                shift = jnp.sum((C_new - C) ** 2)
                return C_new, shift, it + 1

            C, _, n_iter = jax.lax.while_loop(
                cond, body,
                (C0, jnp.asarray(jnp.inf, X.dtype),
                 jnp.asarray(0, jnp.int32)))
            d2 = _sq_dists(X, C)
            return C, jnp.sum(w * jnp.min(d2, axis=1)), n_iter

        def one_init(t, best):
            bC, b_inertia, b_iter = best
            C, inertia, n_iter = lloyd(
                seed_centers(jax.random.fold_in(base_key, t)))
            better = inertia < b_inertia
            return (jnp.where(better, C, bC),
                    jnp.where(better, inertia, b_inertia),
                    jnp.where(better, n_iter, b_iter))

        best = (jnp.zeros((k, d), X.dtype),
                jnp.asarray(jnp.inf, X.dtype), jnp.asarray(0, jnp.int32))
        C, inertia, n_iter = jax.lax.fori_loop(0, n_init, one_init, best)
        return {"centers": C, "inertia": inertia, "n_iter": n_iter}

    @classmethod
    def predict(cls, model, static, X, meta):
        return jnp.argmin(_sq_dists(X, model["centers"]),
                          axis=1).astype(jnp.int32)

    @classmethod
    def decision(cls, model, static, X, meta):
        return -_sq_dists(X, model["centers"])

    @classmethod
    def sklearn_attrs(cls, model, static, meta):
        return {
            "cluster_centers_": np.asarray(model["centers"]),
            "inertia_": float(model["inertia"]),
            "n_iter_": int(model["n_iter"]),
            "n_features_in_": meta["n_features"],
        }


register_family(
    KMeansFamily,
    "sklearn.cluster._kmeans.KMeans",
    "sklearn.cluster.KMeans",
)
