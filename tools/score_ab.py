"""A/B the wide vs nested score phase on the virtual CPU mesh.

The win is shape-level (one wide matmul + shared views vs per-task
matvecs per scorer), so the CPU mesh measures the same program
structure the chip runs.  Usage: python tools/score_ab.py [n_cand]
"""

import os
import subprocess
import sys

CHILD = """
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import spark_sklearn_tpu as sst
from sklearn.datasets import load_digits
from sklearn.linear_model import LogisticRegression
from sklearn.model_selection import StratifiedKFold

n_cand = int(sys.argv[1])
X, y = load_digits(return_X_y=True)
X = (X / 16.0).astype(np.float32)
grid = {"C": list(np.logspace(-4, 3, n_cand))}
cv = StratifiedKFold(n_splits=5)
est = LogisticRegression(max_iter=100)

wall = rep = None
for tag in ("cold", "warm"):
    gs = sst.GridSearchCV(est, grid, cv=cv, backend="tpu", refit=False,
                          scoring=["accuracy", "neg_log_loss"])
    t0 = time.perf_counter()
    gs.fit(X, y)
    wall = time.perf_counter() - t0
    rep = gs._search_report
mode = "nested" if os.environ.get("SST_NESTED_SCORE") else "wide"
print(f"MODE={mode} warm_wall={wall:.2f}s fit={rep['fit_wall_s']:.2f}s "
      f"score={rep['score_wall_s']:.2f}s")
"""


def main():
    n_cand = sys.argv[1] if len(sys.argv) > 1 else "200"
    for env_extra in ({}, {"SST_NESTED_SCORE": "1"}):
        env = dict(os.environ, **env_extra)
        r = subprocess.run([sys.executable, "-c", CHILD, n_cand],
                           capture_output=True, text=True, env=env,
                           timeout=1800)
        print(r.stdout.strip() or r.stderr[-400:])


if __name__ == "__main__":
    main()
