"""A/B the score-phase designs on the virtual CPU mesh.

Three arms (same search, same shapes, fresh process each):
  * fused   — default: fit + health + scoring in ONE launch per chunk
  * wide    — TpuConfig(fuse_fit_score=False): separate score launch,
              views once per launch over the flat task axis
  * nested  — SST_NESTED_SCORE=1: per-(candidate, fold) scorer calls
              (the round-2 control arm)

The win is shape-level (one wide matmul + shared views vs per-task
matvecs per scorer; one launch vs two + host sync), so the CPU mesh
measures the same program structure the chip runs.  Wall clocks on the
1-core box are NOT TPU numbers — only the relative ordering carries.

Usage: python tools/score_ab.py [n_cand]
"""

import os
import subprocess
import sys

CHILD = """
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import spark_sklearn_tpu as sst
from sklearn.datasets import load_digits
from sklearn.linear_model import LogisticRegression
from sklearn.model_selection import StratifiedKFold

n_cand = int(sys.argv[1])
X, y = load_digits(return_X_y=True)
X = (X / 16.0).astype(np.float32)
grid = {"C": list(np.logspace(-4, 3, n_cand))}
cv = StratifiedKFold(n_splits=5)
est = LogisticRegression(max_iter=100)
cfg = sst.TpuConfig(fuse_fit_score=not os.environ.get("SST_NO_FUSE"))

wall = rep = None
for tag in ("cold", "warm"):
    gs = sst.GridSearchCV(est, grid, cv=cv, backend="tpu", refit=False,
                          scoring=["accuracy", "neg_log_loss"], config=cfg)
    t0 = time.perf_counter()
    gs.fit(X, y)
    wall = time.perf_counter() - t0
    rep = gs._search_report
mode = ("nested" if os.environ.get("SST_NESTED_SCORE")
        else "fused" if cfg.fuse_fit_score else "wide")
print(f"MODE={mode} warm_wall={wall:.2f}s fit={rep['fit_wall_s']:.2f}s "
      f"score={rep['score_wall_s']:.2f}s launches={rep['n_launches']}")
"""

#: env overlays per arm; SST_NESTED_SCORE is explicitly cleared when not
#: part of the arm so an inherited value can't contaminate the defaults
ARMS = [
    {"SST_NO_FUSE": None, "SST_NESTED_SCORE": None},
    {"SST_NO_FUSE": "1", "SST_NESTED_SCORE": None},
    {"SST_NO_FUSE": "1", "SST_NESTED_SCORE": "1"},
]


def main():
    n_cand = sys.argv[1] if len(sys.argv) > 1 else "200"
    for overlay in ARMS:
        env = dict(os.environ)
        for k, v in overlay.items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        r = subprocess.run([sys.executable, "-c", CHILD, n_cand],
                           capture_output=True, text=True, env=env,
                           timeout=1800)
        print(r.stdout.strip() or r.stderr[-400:], flush=True)


if __name__ == "__main__":
    main()
