"""Digest a Chrome trace-event JSON exported by `obs.export`.

Prints a top-spans / per-thread / critical-path summary of a trace, so
the pipeline's overlap story can be read in a terminal without loading
Perfetto:

    python tools/trace_summary.py TRACE.json [--top N]

Works on any Trace Event Format file (object form with "traceEvents"
or bare array form).  Exits nonzero when the trace holds no spans —
the CI smoke leg uses that as its assertion.

Span names are interpreted through the registered vocabulary
(``spark_sklearn_tpu/obs/spans.py`` — the same single source of truth
``tools/sstlint`` enforces at the instrumentation sites): async spans
group by their registered prefix, and names the vocabulary has never
heard of produce a stderr warning so a drifting producer is visible
even from a bare trace file.

Multi-tenant traces (searches submitted through a TpuSession's
fair-share executor) carry a ``tenant``/``handle`` correlation on
every span; ``--tenant NAME`` restricts the digest to one tenant's
events, and the per-tenant rollup section attributes span time across
tenants.  Flight-recorder bundles (obs/telemetry.py) embed their trace
slice under the standard ``traceEvents`` key, so a bundle file digests
here directly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional

__all__ = ["filter_tenant", "load_bundle_memory", "load_events",
           "load_vocabulary", "summarize", "format_summary", "main"]


def load_vocabulary():
    """The span-vocabulary module, loaded directly by file path so the
    digest never pays the package (jax) import; None when the source
    tree is not alongside this tool."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir,
        "spark_sklearn_tpu", "obs", "spans.py")
    if not os.path.isfile(path):
        return None
    import importlib.util

    spec = importlib.util.spec_from_file_location("_sst_spans", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["_sst_spans"] = module
    spec.loader.exec_module(module)
    return module


def load_events(path: str) -> List[Dict[str, Any]]:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("traceEvents", [])
    return [e for e in data if isinstance(e, dict)]


def load_bundle_memory(path: str) -> Dict[str, Any]:
    """The device-memory ledger section of a flight-recorder bundle
    (``obs/telemetry.py`` stamps ``memory`` into every dump), or {}
    for a plain Chrome trace file."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        mem = data.get("memory")
        if isinstance(mem, dict):
            return mem
    return {}


def filter_tenant(events: List[Dict[str, Any]],
                  tenant: str) -> List[Dict[str, Any]]:
    """Only the events stamped with ``tenant`` (correlation attrs from
    the multi-tenant executor), keeping the ``M`` metadata records that
    name threads — so a per-tenant digest still labels its tracks."""
    return [e for e in events
            if e.get("ph") == "M"
            or (e.get("args") or {}).get("tenant") == tenant]


def _tenant_rollup(spans: List[Dict[str, Any]],
                   events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-tenant span attribution: count/total-ms over the tenant-
    stamped X spans plus each tenant's async launch count."""
    roll: Dict[str, Dict[str, Any]] = {}
    for e in spans:
        tenant = (e.get("args") or {}).get("tenant")
        if not tenant:
            continue
        rec = roll.setdefault(
            tenant, {"n_spans": 0, "total_ms": 0.0, "n_launches": 0})
        rec["n_spans"] += 1
        rec["total_ms"] += float(e.get("dur", 0.0)) / 1e3
    for e in events:
        if e.get("ph") != "b" or \
                not str(e.get("name", "")).startswith("launch"):
            continue
        tenant = (e.get("args") or {}).get("tenant")
        if not tenant:
            continue
        rec = roll.setdefault(
            tenant, {"n_spans": 0, "total_ms": 0.0, "n_launches": 0})
        rec["n_launches"] += 1
    for rec in roll.values():
        rec["total_ms"] = round(rec["total_ms"], 3)
    return roll


def _self_times(spans: List[Dict[str, Any]]) -> Dict[int, float]:
    """Self time (dur minus nested children) per span index, for one
    thread's complete events.  Spans are stack-nested by construction,
    so a sweep with an enclosing-span stack suffices."""
    order = sorted(range(len(spans)),
                   key=lambda i: (spans[i]["ts"], -spans[i]["dur"]))
    self_us = {i: float(spans[i]["dur"]) for i in order}
    stack: List[int] = []
    for i in order:
        ts = spans[i]["ts"]
        while stack and ts >= (spans[stack[-1]]["ts"]
                               + spans[stack[-1]]["dur"]):
            stack.pop()
        if stack:
            self_us[stack[-1]] -= float(spans[i]["dur"])
        stack.append(i)
    return self_us


def summarize(events: List[Dict[str, Any]], top: int = 12,
              vocab=None) -> Dict[str, Any]:
    """Aggregate a trace into the printed digest's data structure.
    `vocab` is the registered span vocabulary (load_vocabulary());
    unknown names land in the digest's "unknown_names" list."""
    if vocab is None:
        vocab = load_vocabulary()
    thread_names: Dict[Any, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")

    spans = [e for e in events if e.get("ph") == "X"]
    unknown: set = set()
    asyncs = defaultdict(int)
    for e in events:
        if e.get("ph") == "b":
            name = e.get("name", "")
            prefix = vocab.async_prefix(name) if vocab else None
            if prefix is None:
                # ad-hoc grouping for vocabulary-less / foreign traces
                prefix = name.split(" ")[0] or "?"
                if vocab is not None:
                    unknown.add(name)
            asyncs[prefix] += 1
    if vocab is not None:
        for e in spans:
            name = e.get("name", "")
            if name and not vocab.is_known_span(name):
                unknown.add(name)

    by_thread: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for e in spans:
        key = (e.get("pid"), e.get("tid"))
        by_thread[thread_names.get(key, f"tid {e.get('tid')}")].append(e)

    t_lo = min((e["ts"] for e in spans), default=0.0)
    t_hi = max((e["ts"] + e["dur"] for e in spans), default=0.0)
    wall_ms = (t_hi - t_lo) / 1e3

    names: Dict[str, Dict[str, float]] = {}
    threads: Dict[str, Dict[str, Any]] = {}
    for tname, tev in by_thread.items():
        self_us = _self_times(tev)
        busy_us = sum(self_us.values())
        threads[tname] = {
            "n_spans": len(tev),
            "busy_ms": round(busy_us / 1e3, 3),
            "utilization": round(busy_us / 1e3 / wall_ms, 4)
            if wall_ms > 0 else 0.0,
        }
        for i, e in enumerate(tev):
            rec = names.setdefault(e["name"], {
                "count": 0, "total_ms": 0.0, "self_ms": 0.0, "max_ms": 0.0})
            dur_ms = float(e["dur"]) / 1e3
            rec["count"] += 1
            rec["total_ms"] += dur_ms
            rec["self_ms"] += self_us[i] / 1e3
            rec["max_ms"] = max(rec["max_ms"], dur_ms)

    top_spans = sorted(names.items(), key=lambda kv: -kv[1]["self_ms"])[:top]
    # critical path digest: the busiest thread is the run's bottleneck;
    # its top self-time spans are where optimization effort goes
    bottleneck = max(threads.items(), key=lambda kv: kv[1]["busy_ms"],
                     default=(None, None))[0]
    # host->device transfer digest from the data plane's upload spans
    # (each carries its byte count in args): per-launch bytes make
    # transfer regressions visible without loading Perfetto
    h2d_bytes = 0
    h2d_uploads = 0
    tiled_bytes = 0
    for e in spans:
        args = e.get("args", {}) or {}
        if e.get("name") == "dataplane.upload":
            h2d_bytes += int(args.get("bytes", 0) or 0)
            h2d_uploads += 1
        elif e.get("name") == "dataplane.tile":
            tiled_bytes += int(args.get("bytes", 0) or 0)
    n_launches = asyncs.get("launch", 0)
    # under chunk_loop="scan" one launch executes a whole segment (13
    # chunks -> 1 launch), so a raw per-launch average is misleading:
    # normalize by scanned steps instead, and label which denominator
    # the digest used so the printed line stays honest either way
    scan_steps = 0
    n_scan_spans = 0
    for e in spans:
        if e.get("name") == "chunkloop.scan":
            n_scan_spans += 1
            scan_steps += int(
                (e.get("args") or {}).get("n_chunks", 0) or 0)
    if scan_steps:
        launch_units = scan_steps + max(0, n_launches - n_scan_spans)
        launch_unit = "scanned step"
    else:
        launch_units = n_launches
        launch_unit = "launch"
    h2d = {
        "bytes_total": h2d_bytes,
        "n_uploads": h2d_uploads,
        "bytes_tiled_on_device": tiled_bytes,
        "bytes_per_launch": round(h2d_bytes / launch_units, 1)
        if launch_units else 0.0,
        "launch_unit": launch_unit,
        "n_launch_units": launch_units,
    }
    # compile digest from the AOT spans: the compile wall (sst-compile
    # thread) next to the program store's traffic (programstore.load /
    # .save spans each carry hit flags and byte counts) — the
    # zero-cold-start observable: a prewarmed process shows hit rate
    # 1.0 and a (near-)zero compile wall
    compile_ms = sum(float(e["dur"]) / 1e3 for e in spans
                     if e.get("name") == "compile")
    store_loads = store_hits = 0
    store_bytes_loaded = store_bytes_saved = 0
    for e in spans:
        args = e.get("args", {}) or {}
        if e.get("name") == "programstore.load":
            store_loads += 1
            if args.get("hit"):
                store_hits += 1
            store_bytes_loaded += int(args.get("bytes", 0) or 0)
        elif e.get("name") == "programstore.save":
            store_bytes_saved += int(args.get("bytes", 0) or 0)
    # device-memory digest from the ledger's trace events: the modeled
    # peak footprint per compile group (memory.footprint instants) and
    # the launch-boundary allocator samples (memory.sample spans) —
    # the per-group HBM story next to the per-launch time story
    mem_groups: Dict[str, int] = {}
    mem_capped: Dict[str, bool] = {}
    mem_samples = 0
    mem_peak_in_use = 0
    mem_measured = False
    for e in events:
        name = e.get("name")
        args = e.get("args", {}) or {}
        if name == "memory.footprint":
            g = str(args.get("group", "?"))
            b = int(args.get("modeled_bytes",
                             args.get("chunk_bytes", 0)) or 0)
            mem_groups[g] = max(mem_groups.get(g, 0), b)
            if args.get("capped"):
                mem_capped[g] = True
        elif name == "memory.sample":
            mem_samples += 1
            mem_peak_in_use = max(
                mem_peak_in_use, int(args.get("bytes_in_use", 0) or 0))
            mem_measured = mem_measured or bool(args.get("measured"))
    memory_digest = {
        "per_group_peak_modeled_bytes": mem_groups,
        "capped_groups": sorted(mem_capped),
        "n_samples": mem_samples,
        "peak_bytes_in_use": mem_peak_in_use,
        "measured": mem_measured,
    }
    compile_digest = {
        "compile_wall_ms": round(compile_ms, 3),
        "compile_ms_per_launch": round(compile_ms / launch_units, 3)
        if launch_units else 0.0,
        "launch_unit": launch_unit,
        "store_loads": store_loads,
        "store_hits": store_hits,
        "store_hit_rate": round(store_hits / store_loads, 4)
        if store_loads else 0.0,
        "store_bytes_loaded": store_bytes_loaded,
        "store_bytes_saved": store_bytes_saved,
    }
    return {
        "h2d": h2d,
        "memory": memory_digest,
        "compile": compile_digest,
        "tenants": _tenant_rollup(spans, events),
        "unknown_names": sorted(unknown),
        "n_events": len(events),
        "n_spans": len(spans),
        "wall_ms": round(wall_ms, 3),
        "threads": threads,
        "top_spans": [
            {"name": k, "count": int(v["count"]),
             "total_ms": round(v["total_ms"], 3),
             "self_ms": round(v["self_ms"], 3),
             "mean_ms": round(v["total_ms"] / v["count"], 3),
             "max_ms": round(v["max_ms"], 3)}
            for k, v in top_spans],
        "async_tracks": dict(asyncs),
        "bottleneck_thread": bottleneck,
    }


def format_summary(s: Dict[str, Any]) -> str:
    out = [f"trace: {s['n_spans']} spans / {s['n_events']} events, "
           f"wall {s['wall_ms']:.1f} ms"]
    out.append("\nthreads (self-time busy / utilization):")
    for tname, t in sorted(s["threads"].items(),
                           key=lambda kv: -kv[1]["busy_ms"]):
        mark = "  <- critical path" if tname == s["bottleneck_thread"] \
            else ""
        out.append(f"  {tname:<24} {t['busy_ms']:>10.1f} ms  "
                   f"{100 * t['utilization']:>5.1f}%  "
                   f"({t['n_spans']} spans){mark}")
    out.append("\ntop spans by self time:")
    out.append(f"  {'name':<28} {'count':>5} {'self ms':>10} "
               f"{'total ms':>10} {'mean ms':>9} {'max ms':>9}")
    for r in s["top_spans"]:
        out.append(f"  {r['name']:<28} {r['count']:>5} "
                   f"{r['self_ms']:>10.1f} {r['total_ms']:>10.1f} "
                   f"{r['mean_ms']:>9.2f} {r['max_ms']:>9.2f}")
    if s["async_tracks"]:
        counts = ", ".join(f"{k}={v}"
                           for k, v in sorted(s["async_tracks"].items()))
        out.append(f"\nasync spans: {counts}")
    h2d = s.get("h2d") or {}
    if h2d.get("n_uploads"):
        unit = h2d.get("launch_unit", "launch")
        out.append(
            f"\nbytes host->device: "
            f"{h2d['bytes_total'] / 1e6:.3f} MB over "
            f"{h2d['n_uploads']} uploads "
            f"({h2d['bytes_per_launch'] / 1e6:.3f} MB per {unit}, "
            f"over {h2d.get('n_launch_units', 0)} {unit}(s)); "
            f"{h2d['bytes_tiled_on_device'] / 1e6:.3f} MB tiled "
            "on-device (no transfer)")
    mem = s.get("memory") or {}
    if mem.get("per_group_peak_modeled_bytes"):
        per_g = mem["per_group_peak_modeled_bytes"]
        parts = ", ".join(
            f"g{g}={per_g[g] / 1e6:.3f} MB"
            + ("[capped]" if g in (mem.get("capped_groups") or ()) else "")
            for g in sorted(per_g))
        line = f"memory: peak modeled footprint per compile group: {parts}"
        if mem.get("measured"):
            line += (f"; measured peak {mem['peak_bytes_in_use'] / 1e6:.3f}"
                     f" MB over {mem['n_samples']} sample(s)")
        elif mem.get("n_samples"):
            line += f" ({mem['n_samples']} unmeasured sample(s))"
        out.append(line)
    bm = s.get("bundle_memory") or {}
    if bm:
        out.append(
            "flight-bundle ledger: modeled peak "
            f"{bm.get('modeled_peak_bytes', 0) / 1e6:.3f} MB, watermark "
            f"{bm.get('watermark_bytes', 0) / 1e6:.3f} MB, safety margin "
            f"{bm.get('safety_margin', 1.0)}x, "
            f"{len(bm.get('groups') or ())} group footprint(s), "
            f"{bm.get('n_oom_observed', 0)} OOM(s) observed")
    tenants = s.get("tenants") or {}
    if tenants:
        out.append("\nper-tenant rollup (correlation-stamped spans):")
        out.append(f"  {'tenant':<20} {'spans':>6} {'span ms':>10} "
                   f"{'launches':>9}")
        for tenant in sorted(tenants):
            r = tenants[tenant]
            out.append(f"  {tenant:<20} {r['n_spans']:>6} "
                       f"{r['total_ms']:>10.1f} {r['n_launches']:>9}")
    comp = s.get("compile") or {}
    if comp.get("compile_wall_ms") or comp.get("store_loads"):
        out.append(
            f"compile: {comp['compile_wall_ms'] / 1e3:.2f} s wall "
            f"({comp.get('compile_ms_per_launch', 0.0):.1f} ms per "
            f"{comp.get('launch_unit', 'launch')}); "
            f"program store {comp['store_hits']}/{comp['store_loads']} "
            f"hits ({100 * comp['store_hit_rate']:.0f}%), "
            f"{comp['store_bytes_loaded'] / 1e6:.3f} MB loaded, "
            f"{comp['store_bytes_saved'] / 1e6:.3f} MB published")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace-event JSON file")
    ap.add_argument("--top", type=int, default=12,
                    help="how many span names to list (default 12)")
    ap.add_argument("--tenant", default=None,
                    help="restrict the digest to one tenant's "
                         "correlation-stamped events")
    ap.add_argument("--json", action="store_true",
                    help="emit the digest as JSON instead of a table")
    args = ap.parse_args(argv)
    # one parse serves both the trace slice and the bundle's ledger
    # section — flight bundles can be tens of MB and must not be
    # json.load'ed twice
    with open(args.trace) as f:
        data = json.load(f)
    bundle_mem: Dict[str, Any] = {}
    if isinstance(data, dict):
        # flight-recorder bundles carry the device-memory ledger
        # snapshot next to their trace slice: digest it alongside
        if isinstance(data.get("memory"), dict):
            bundle_mem = data["memory"]
        data = data.get("traceEvents", [])
    events = [e for e in data if isinstance(e, dict)]
    if args.tenant:
        events = filter_tenant(events, args.tenant)
    s = summarize(events, top=args.top)
    if bundle_mem:
        s["bundle_memory"] = bundle_mem
    try:
        if args.json:
            print(json.dumps(s, indent=2))
        else:
            print(format_summary(s))
    except BrokenPipeError:      # `... | head` is a legitimate use
        pass
    for name in s.get("unknown_names", []):
        print(f"warning: span name {name!r} is not in the registered "
              "vocabulary (spark_sklearn_tpu/obs/spans.py)",
              file=sys.stderr)
    if s["n_spans"] == 0:
        print("error: trace contains no complete spans", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
