"""Chaos soak harness for the self-protecting search service.

Drives M tenants x N searches through one shared
:class:`~spark_sklearn_tpu.utils.session.TpuSession` under a
deterministic chaos plan, then asserts the service's protection
contract held:

  - **zero process crashes** — the harness itself finishes, the
    executor still admits and completes a clean search afterwards;
  - **every search accounted for** — each submission ends exactly one
    of: bit-exact vs its solo baseline, cleanly rejected with a
    machine-readable :class:`AdmissionError`, or explicitly partial
    with a ``search_report["protection"]`` block naming EVERY shed or
    quarantined candidate;
  - **bounded p95 queue wait** — no tenant's telemetry queue-wait p95
    exceeds ``--max-p95``.

The chaos plan is a superset of the ``TpuConfig(fault_plan)`` grammar
(parallel/faults.py): launch-fault tokens are distributed round-robin
onto the tenants' fault plans, and two session-level event tokens run
on the harness clock:

  ============================  =====================================
  token                         event
  ============================  =====================================
  ``transient@N[xK]``           retryable launch failure(s)
  ``oom@N`` / ``oom_deep@N``    chunk OOM / sticky deep OOM
  ``hung@N``                    wedged launch (watchdog recovers)
  ``fatal@N`` / ``fatal_deep@N``  poison launch / sticky poison range
  ``slow@N:F``                  brownout: launch N stalls F seconds
  ``submit_storm@T[xK]``        at T s, K threads race session.submit
  ``evict_storm@T``             at T s, distinct-content submissions
                                churn the device data plane
  ============================  =====================================

    python tools/sst_soak.py                       # default soak
    python tools/sst_soak.py --tenants 3 --searches 4 \
        --plan "transient@1;oom_deep@2;hung@1;slow@3:0.3;submit_storm@0x6"

``--crash-drill`` runs the crash-safety arc instead: a child process
journals a search (``serve/journal.py``) and is ``kill -9``ed
mid-flight once its checkpoint journal holds at least one chunk; the
harness then fences the dead owner's lease, recovers the journaled
search through ``TpuSession.recover()`` / ``resubmit()``, and asserts
the recovered ``cv_results_`` is bit-exact (``np.array_equal``)
against the uncrashed baseline, the crash-marker flight bundle
landed, and the journal owes nothing afterwards.

Exits nonzero when any assertion fails; ``--json`` emits the full
per-search ledger for CI artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# runnable as `python tools/sst_soak.py` from a checkout: the repo
# root (the package's parent) joins sys.path like `python -m` would
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

__all__ = ["parse_chaos_plan", "run_crash_drill", "run_soak", "main"]

#: session-level events: (name, t_s, count)
_EVENT_RE = re.compile(
    r"(?i)^(submit_storm|evict_storm)@([0-9.]+)(?:x(\d+))?$")

#: the default plan: transients, one deep OOM, a hang, a brownout, a
#: sticky poison range and a submit storm — every protection layer
#: fires at least once
DEFAULT_PLAN = ("transient@1;oom_deep@2;fatal_deep@3;slow@3:0.3;"
                "hung@5;submit_storm@0x6")


def parse_chaos_plan(plan: str) -> Tuple[List[str], List[Tuple[str,
                                                               float,
                                                               int]]]:
    """Split a chaos plan into (launch-fault tokens, session events).
    Launch tokens are validated against the fault-plan grammar so a
    typo fails at harness start, not mid-soak."""
    from spark_sklearn_tpu.parallel.faults import FaultPlan
    tokens: List[str] = []
    events: List[Tuple[str, float, int]] = []
    for raw in re.split(r"[;,]", plan or ""):
        tok = raw.strip()
        if not tok:
            continue
        m = _EVENT_RE.match(tok)
        if m:
            events.append((m.group(1).lower(), float(m.group(2)),
                           int(m.group(3) or 1)))
            continue
        FaultPlan.parse(tok)        # raises on a malformed token
        tokens.append(tok)
    return tokens, sorted(events, key=lambda e: e[1])


def _make_search(sst, cfg, seed: int):
    from sklearn.linear_model import LogisticRegression
    import numpy as np
    c_grid = np.logspace(-2 + 0.01 * seed, 1, 12).tolist()
    return sst.GridSearchCV(
        LogisticRegression(max_iter=10), {"C": c_grid}, cv=2,
        refit=False, backend="tpu", error_score=-999.0, config=cfg)


def _drill_data():
    """The crash drill's dataset — one definition imported by BOTH the
    to-be-killed child and the recovering harness, so the fingerprint
    check in ``TpuSession.resubmit()`` compares like with like."""
    import numpy as np
    rng = np.random.RandomState(11)
    X = rng.randn(120, 6).astype(np.float32)
    y = (X[:, 0] + 0.25 * rng.randn(120) > 0).astype(np.int64)
    return X, y


def _classify(search, fut, baseline, n_cand: int) -> Dict[str, Any]:
    """One submission's verdict: exact / partial-declared / failed."""
    import numpy as np
    try:
        fut.result()
    except Exception as exc:   # noqa: BLE001 — the soak LEDGERS
        # failures instead of crashing; anything landing here fails
        # the zero-crash assertion below with its type on record
        return {"outcome": "failed",
                "error": f"{type(exc).__name__}: {exc}"[:300]}
    prot = (search.search_report or {}).get("protection") or {}
    scores = search.cv_results_["mean_test_score"]
    declared = sorted({int(i)
                       for entry in (list(prot.get("shed") or [])
                                     + list(prot.get("quarantined")
                                            or []))
                       for i in entry.get("candidates", [])})
    if prot.get("partial"):
        # every non-declared candidate must still be bit-exact, and
        # every declared one must carry error_score
        undeclared = [i for i in range(n_cand) if i not in declared]
        ok = (all(scores[i] == -999.0 for i in declared)
              and bool(np.allclose(scores[undeclared],
                                   baseline[undeclared]))
              if declared else False)
        return {"outcome": "partial-declared" if ok else "failed",
                "verdict": prot.get("verdict", ""),
                "n_declared": len(declared),
                "error": None if ok else
                "partial block does not name every missing candidate"}
    if np.allclose(scores, baseline):
        return {"outcome": "exact", "verdict": prot.get("verdict",
                                                        "complete")}
    return {"outcome": "failed",
            "error": "scores diverged without a declared-partial "
                     "protection block"}


def run_soak(n_tenants: int = 2, n_searches: int = 3,
             plan: str = DEFAULT_PLAN, deadline_s: float = 120.0,
             max_p95_s: float = 60.0, quarantine_k: int = 2,
             launch_timeout_s: float = 20.0,
             verbose: bool = True) -> Dict[str, Any]:
    import numpy as np
    import spark_sklearn_tpu as sst
    from spark_sklearn_tpu.obs import telemetry as _telemetry
    from spark_sklearn_tpu.serve.executor import AdmissionError

    def say(msg: str) -> None:
        if verbose:
            print(f"[soak] {msg}", flush=True)

    tokens, events = parse_chaos_plan(plan)
    rng = np.random.RandomState(7)
    X = rng.randn(96, 6).astype(np.float32)
    y = (X[:, 0] + 0.25 * rng.randn(96) > 0).astype(np.int64)

    # one clean solo baseline per seed (protection off, no faults)
    say(f"baselines for {n_searches} search shape(s)")
    baselines: Dict[int, Any] = {}
    for seed in range(n_searches):
        solo = _make_search(sst, None, seed)
        solo.fit(X, y)
        baselines[seed] = solo.cv_results_["mean_test_score"].copy()

    # tenant configs: protection on everywhere, launch faults
    # round-robin over tenants
    tenant_plans: List[List[str]] = [[] for _ in range(n_tenants)]
    for i, tok in enumerate(tokens):
        tenant_plans[i % n_tenants].append(tok)
    from spark_sklearn_tpu.parallel.faults import FaultPlan
    for t, tp in enumerate(tenant_plans):
        if tp:
            # fail at harness start (duplicate indices after the
            # round-robin split), not inside a soak thread
            FaultPlan.parse(",".join(tp))

    def tenant_cfg(t: int, fault_tokens: List[str]):
        return sst.TpuConfig(
            tenant=f"tenant{t}", partial_results="best_effort",
            search_deadline_s=deadline_s, admission_mode="predictive",
            quarantine_fatal_k=quarantine_k,
            launch_timeout_s=launch_timeout_s,
            max_tasks_per_batch=8, telemetry_port=0,
            max_concurrent_searches=2, max_queued_searches=4,
            fault_plan=",".join(fault_tokens) or None)

    session_cfg = tenant_cfg(0, [])
    sess = sst.createLocalTpuSession("sst-soak", session_cfg)
    ledger: List[Dict[str, Any]] = []
    ledger_lock = threading.Lock()
    t0 = time.perf_counter()

    def submit_one(t: int, seed: int, fault_tokens: List[str],
                   tag: str, data=None) -> None:
        cfg = tenant_cfg(t, fault_tokens)
        search = _make_search(sst, cfg, seed)
        rec: Dict[str, Any] = {"tenant": f"tenant{t}", "seed": seed,
                               "tag": tag,
                               "faults": ",".join(fault_tokens)}
        Xs, ys = data if data is not None else (X, y)
        try:
            t_sub = time.perf_counter()
            fut = sess.submit(search, Xs, ys)
        except AdmissionError as exc:
            rec.update(outcome="rejected-clean",
                       reason=exc.reason,
                       retry_after_s=exc.retry_after_s)
            with ledger_lock:
                ledger.append(rec)
            return
        rec.update(_classify(search, fut, baselines[seed],
                             len(baselines[seed])))
        rec["wall_s"] = round(time.perf_counter() - t_sub, 3)
        with ledger_lock:
            ledger.append(rec)

    # main soak wave: every tenant submits its searches on its own
    # thread while the event clock fires storms
    say(f"soak wave: {n_tenants} tenant(s) x {n_searches} search(es), "
        f"faults={tokens}, events={events}")
    threads: List[threading.Thread] = []
    for t in range(n_tenants):
        def tenant_body(t=t):
            for seed in range(n_searches):
                # the tenant's fault plan applies to its FIRST search
                # (fault indices are per-search); later ones run clean
                submit_one(t, seed,
                           tenant_plans[t] if seed == 0 else [],
                           tag="wave")
        th = threading.Thread(target=tenant_body,
                              name=f"soak-tenant{t}")
        th.start()
        threads.append(th)

    for name, t_s, count in events:
        delay = t_s - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        if name == "submit_storm":
            say(f"submit storm: {count} racing submission(s)")
            storm: List[threading.Thread] = []
            for k in range(count):
                th = threading.Thread(
                    target=submit_one,
                    args=(k % n_tenants, k % n_searches, [],
                          "storm"),
                    name=f"soak-storm{k}")
                th.start()
                storm.append(th)
            threads.extend(storm)
        elif name == "evict_storm":
            say(f"evict storm: {count} distinct-content "
                "submission(s)")
            for k in range(count):
                Xk = X + np.float32(1e-6 * (k + 1))
                th = threading.Thread(
                    target=submit_one,
                    args=(k % n_tenants, k % n_searches, [],
                          "evict"),
                    kwargs={"data": (Xk, y)},
                    name=f"soak-evict{k}")
                th.start()
                threads.append(th)

    for th in threads:
        th.join()

    # liveness proof: the executor must still admit and complete a
    # clean search AFTER the chaos
    say("post-chaos liveness probe")
    submit_one(0, 0, [], tag="liveness")

    snap = _telemetry.get_telemetry().snapshot()
    sess.stop()

    by_outcome: Dict[str, int] = {}
    for rec in ledger:
        by_outcome[rec["outcome"]] = by_outcome.get(rec["outcome"],
                                                    0) + 1
    p95 = {name: float(t.get("queue_wait_p95_s", 0.0) or 0.0)
           for name, t in (snap.get("tenants") or {}).items()}
    failures: List[str] = []
    for rec in ledger:
        if rec["outcome"] == "failed":
            failures.append(
                f"{rec['tenant']} seed={rec['seed']} tag={rec['tag']}: "
                f"{rec.get('error')}")
    live = [r for r in ledger if r["tag"] == "liveness"]
    if not live or live[-1]["outcome"] != "exact":
        failures.append("post-chaos liveness probe did not complete "
                        "bit-exact")
    worst_p95 = max(p95.values(), default=0.0)
    if worst_p95 > max_p95_s:
        failures.append(f"queue-wait p95 {worst_p95:.2f}s exceeds "
                        f"bound {max_p95_s:.2f}s")

    result = {
        "n_submissions": len(ledger),
        "by_outcome": by_outcome,
        "queue_wait_p95_s": p95,
        "protection_counters": snap.get("protection") or {},
        "failures": failures,
        "ledger": ledger,
    }
    say(f"outcomes: {by_outcome}; protection counters: "
        f"{result['protection_counters']}")
    if failures:
        for f in failures:
            say(f"FAILURE: {f}")
    else:
        say("SOAK GREEN: zero crashes, every search exact / "
            "cleanly-rejected / declared-partial")
    return result


#: the child half of the crash drill: journal + checkpoint a search
#: stretched by a brownout plan, then hang on the result until the
#: harness SIGKILLs the process mid-flight.  Slow launches make the
#: kill window wide; the scores they produce stay bit-exact.
_DRILL_CHILD_SRC = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {root!r})
sys.path.insert(0, {tools!r})
import jax
jax.config.update("jax_platforms", "cpu")
import spark_sklearn_tpu as sst
from sst_soak import _drill_data, _make_search
X, y = _drill_data()
cfg = sst.TpuConfig(
    tenant="drill", service_journal_dir={jdir!r},
    checkpoint_dir={cdir!r}, max_tasks_per_batch=4,
    telemetry_port=0,
    fault_plan=",".join("slow@%d:0.4" % i for i in range(1, 9)))
sess = sst.createLocalTpuSession("crash-drill-child", cfg)
search = _make_search(sst, cfg, 0)
fut = sess.submit(search, X, y)
print("SUBMITTED", flush=True)
fut.result()
print("FINISHED", flush=True)
"""


def _count_chunk_records(checkpoint_dir: str) -> int:
    """Completed-chunk records durably on disk across every search
    journal in ``checkpoint_dir`` (fault/meta lines don't count)."""
    import glob
    n = 0
    for path in glob.glob(os.path.join(checkpoint_dir,
                                       "search_*.jsonl")):
        try:
            with open(path, errors="replace") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if "chunk_id" in rec:
                        n += 1
        except OSError:
            continue
    return n


def run_crash_drill(verbose: bool = True,
                    kill_timeout_s: float = 90.0) -> Dict[str, Any]:
    """The crash-safety arc, end to end: a child process journals a
    search and dies by ``kill -9`` once at least one checkpoint chunk
    is durable; the harness then fences the dead owner's lease,
    recovers through :meth:`TpuSession.recover` / ``resubmit()``, and
    asserts bit-exactness against the uncrashed baseline plus the
    crash-marker bundle, recovery telemetry, and an empty non-terminal
    set afterwards."""
    import glob
    import signal
    import subprocess
    import tempfile

    import numpy as np
    import spark_sklearn_tpu as sst
    from spark_sklearn_tpu.obs import telemetry as _telemetry
    from spark_sklearn_tpu.serve.journal import ServiceJournal

    def say(msg: str) -> None:
        if verbose:
            print(f"[crash-drill] {msg}", flush=True)

    failures: List[str] = []
    workdir = tempfile.mkdtemp(prefix="sst-crash-drill-")
    jdir = os.path.join(workdir, "journal")
    cdir = os.path.join(workdir, "ckpt")
    log_path = os.path.join(workdir, "child.log")

    # 1. the uncrashed baseline: same search, same data, no journal,
    # no checkpoints, no faults
    say("uncrashed baseline fit")
    X, y = _drill_data()
    solo = _make_search(sst, None, 0)
    solo.fit(X, y)
    baseline = solo.cv_results_["mean_test_score"].copy()

    # 2. the victim: journal + checkpoint in a child process, then
    # SIGKILL it the moment one chunk record is durable — mid-search
    # by construction (the brownout plan stretches the remainder)
    say(f"spawning victim child (journal={jdir})")
    child_src = _DRILL_CHILD_SRC.format(
        root=_ROOT, tools=os.path.join(_ROOT, "tools"),
        jdir=jdir, cdir=cdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    with open(log_path, "w") as log:
        child = subprocess.Popen([sys.executable, "-c", child_src],
                                 stdout=log,
                                 stderr=subprocess.STDOUT, env=env)
        deadline = time.monotonic() + kill_timeout_s
        n_chunks = 0
        while time.monotonic() < deadline:
            if child.poll() is not None:
                break
            n_chunks = _count_chunk_records(cdir)
            if n_chunks >= 1:
                break
            time.sleep(0.05)
        if child.poll() is not None:
            with open(log_path, errors="replace") as f:
                tail = f.read()[-2000:]
            failures.append(
                f"victim exited rc={child.returncode} before the "
                f"kill landed; output tail: {tail!r}")
        elif n_chunks < 1:
            child.kill()
            failures.append(
                f"no durable chunk record within {kill_timeout_s}s "
                "— nothing to kill mid-flight")
        else:
            say(f"SIGKILL pid={child.pid} after {n_chunks} durable "
                "chunk record(s)")
            os.kill(child.pid, signal.SIGKILL)
        child.wait()

    if failures:
        return {"failures": failures, "workdir": workdir}

    # 3. the survivor: fence the dead owner's lease, recover the
    # journaled search, resubmit against the same data
    say("recovery session: fence + recover + resubmit")
    rcfg = sst.TpuConfig(tenant="drill", service_journal_dir=jdir,
                         checkpoint_dir=cdir, max_tasks_per_batch=4,
                         telemetry_port=0)
    t_recover0 = time.perf_counter()
    sess = sst.createLocalTpuSession("crash-drill-recover", rcfg)
    time_to_recover_s = None
    try:
        report = sess.recover()
        if not report.taken_over:
            failures.append("dead owner's lease was not fenced "
                            "(RecoveryReport.taken_over is False)")
        if report.n_nonterminal != 1:
            failures.append(
                f"expected exactly 1 non-terminal journal entry, "
                f"found {report.n_nonterminal}")
        else:
            entry = report.entries[0]
            say(f"recovering {entry.handle} "
                f"(state={entry.state}, ckpt={entry.checkpoint_dir})")
            search2 = _make_search(sst, rcfg, 0)
            fut = sess.resubmit(entry, search2, X, y)
            fut.result()
            time_to_recover_s = time.perf_counter() - t_recover0
            scores = search2.cv_results_["mean_test_score"]
            if not np.array_equal(scores, baseline):
                failures.append(
                    "recovered cv_results_ diverged from the "
                    f"uncrashed baseline: {scores.tolist()} vs "
                    f"{baseline.tolist()}")
            else:
                say(f"recovered bit-exact in {time_to_recover_s:.2f}s")
        markers = glob.glob(os.path.join(jdir,
                                         "flight-crash-marker-*.json"))
        if not markers:
            failures.append("no crash-marker flight bundle landed in "
                            "the journal directory")
        snap = _telemetry.get_telemetry().snapshot()
        rec_block = (snap or {}).get("recovery") or {}
        if not rec_block.get("recovered_total"):
            failures.append("telemetry recovery block shows zero "
                            f"recovered_total: {rec_block}")
        if not rec_block.get("lease_takeovers_total"):
            failures.append("telemetry recovery block shows zero "
                            f"lease_takeovers_total: {rec_block}")
    finally:
        sess.stop()

    # 4. the ledger after the dust settles: the journal owes nothing
    post = ServiceJournal(jdir).nonterminal()
    if post:
        failures.append(
            f"journal still owes {sorted(post)} after recovery")

    result = {
        "failures": failures,
        "workdir": workdir,
        "n_chunks_at_kill": n_chunks,
        "time_to_recover_s": (round(time_to_recover_s, 3)
                              if time_to_recover_s is not None
                              else None),
    }
    if failures:
        for f in failures:
            say(f"FAILURE: {f}")
    else:
        say("CRASH DRILL GREEN: killed mid-search, lease fenced, "
            "recovered bit-exact, journal owes nothing")
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tenants", type=int, default=2)
    ap.add_argument("--searches", type=int, default=3,
                    help="searches per tenant in the main wave")
    ap.add_argument("--plan", default=DEFAULT_PLAN,
                    help="chaos plan (fault tokens + session events)")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="per-search search_deadline_s")
    ap.add_argument("--max-p95", type=float, default=60.0,
                    help="queue-wait p95 bound (seconds)")
    ap.add_argument("--quarantine-k", type=int, default=2)
    ap.add_argument("--launch-timeout", type=float, default=20.0)
    ap.add_argument("--crash-drill", action="store_true",
                    help="run the kill -9 crash-recovery drill "
                         "instead of the chaos soak")
    ap.add_argument("--json", action="store_true",
                    help="emit the full soak ledger as JSON")
    args = ap.parse_args(argv)
    if args.crash_drill:
        result = run_crash_drill(verbose=not args.json)
        if args.json:
            print(json.dumps(result, indent=2, default=str))
        return 1 if result["failures"] else 0
    if args.tenants < 2:
        ap.error("a soak needs >= 2 tenants")
    result = run_soak(n_tenants=args.tenants,
                      n_searches=args.searches, plan=args.plan,
                      deadline_s=args.deadline,
                      max_p95_s=args.max_p95,
                      quarantine_k=args.quarantine_k,
                      launch_timeout_s=args.launch_timeout,
                      verbose=not args.json)
    if args.json:
        print(json.dumps(result, indent=2, default=str))
    return 1 if result["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
