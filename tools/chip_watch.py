"""Background chip watcher — probes the axon TPU claim until it unwedges.

The axon tunnel exposes one real TPU chip, but a dead client holding the
chip claim makes every later backend init hang forever (observed in
rounds 1-2: `jax.devices()` blocks >60s).  The claim has been seen to
clear spontaneously (round 2, ~11:30), so the winning move is to probe
cheaply on a loop and run the full benchmark the moment a probe
succeeds.

Probing is safe: the probe subprocess only performs backend init (no
compile in flight), so killing it on timeout cannot wedge the claim
further (round-1 postmortem: wedges come from killing mid-compile).

Usage: python tools/chip_watch.py [--interval 240] [--max-hours 11]
On success writes bench output to docs/BENCH_TPU_<stamp>.json and a log
to tools/chip_watch.log, then exits 0.  Exits 3 if the window closes
without a successful probe.
"""

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE_CODE = """
import json
import jax
ds = jax.devices()
print(json.dumps({"platform": ds[0].platform, "n_devices": len(ds)}))
"""


def log(msg):
    stamp = datetime.datetime.now().strftime("%H:%M:%S")
    line = f"[{stamp}] {msg}"
    print(line, flush=True)
    with open(os.path.join(REPO, "tools", "chip_watch.log"), "a") as f:
        f.write(line + "\n")


def probe(timeout_s):
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        return None
    try:
        info = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return None
    return info if info.get("platform") not in (None, "cpu") else None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=240)
    ap.add_argument("--probe-timeout", type=int, default=120)
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        info = probe(args.probe_timeout)
        if info is not None:
            log(f"probe #{attempt} SUCCESS: {info} — running bench.py")
            stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H%M")
            out_path = os.path.join(REPO, "docs", f"BENCH_TPU_{stamp}.json")
            env = dict(os.environ)
            # chip already probed healthy: skip the CPU smoke and let the
            # TPU child use (almost) the whole watcher window
            env["BENCH_SKIP_CPU_SMOKE"] = "1"
            env["BENCH_TOTAL_BUDGET_S"] = "6900"
            # stable compile cache: a window that closes mid-bench leaves
            # its compiles for the next attempt (bench labels the reuse)
            env["BENCH_CACHE_DIR"] = os.path.join(
                REPO, ".bench_jax_cache")
            r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                               capture_output=True, text=True, timeout=7200,
                               env=env)
            # bench emits one superseding JSON line per milestone; store
            # only the last parseable one so the .json file stays a
            # single valid document (raw stream kept alongside)
            sys.path.insert(0, REPO)
            from bench import _parse_last_json_line
            payload = _parse_last_json_line(r.stdout)
            with open(out_path + "l.raw", "w") as f:
                f.write(r.stdout)
            with open(out_path, "w") as f:
                json.dump(payload if payload is not None
                          else {"error": "no parseable bench output",
                                "rc": r.returncode}, f, indent=1)
            log(f"bench rc={r.returncode}; parsed={payload is not None}; "
                f"stdout tail: {r.stdout[-300:]}")
            log(f"stderr tail: {r.stderr[-500:]}")
            return 0
        log(f"probe #{attempt} failed/hung (chip still wedged); "
            f"sleeping {args.interval}s")
        time.sleep(args.interval)
    log("window closed without a successful probe")
    return 3


if __name__ == "__main__":
    sys.exit(main())
