"""Live digest of a running session's fleet-telemetry endpoint.

``top`` for the multi-tenant search service: tails the JSON snapshot a
:class:`~spark_sklearn_tpu.utils.session.TpuSession` serves when
``TpuConfig(telemetry_port)`` / ``SST_TELEMETRY_PORT`` is set, and
prints the per-tenant SLO table (queue-wait p50/p95, throughput,
share, data-plane resident bytes), device occupancy, scheduler queue
depth, data-plane and program-store traffic, the device-memory
ledger's pressure line (per-device HBM %, modeled peak, watermark),
the cross-search fusion line (fused dispatch counts, launches saved,
per-tenant lanes borrowed/donated), fault totals and flight-recorder
state:

    python tools/fleet_top.py --port 9090            # one shot
    python tools/fleet_top.py --port 9090 --watch 2  # refresh every 2s
    python tools/fleet_top.py --url http://127.0.0.1:9090 --json

stdlib-only (urllib): digesting a fleet never pays the jax import.
Exits nonzero when the endpoint is unreachable or telemetry is
disabled — the CI smoke leg uses that as its assertion.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

__all__ = ["fetch_snapshot", "format_snapshot", "main"]


def fetch_snapshot(url: str, timeout: float = 5.0) -> Dict[str, Any]:
    """GET ``<url>/snapshot.json`` and parse it.  Raises OSError /
    ValueError on unreachable endpoints or non-JSON payloads."""
    target = url.rstrip("/") + "/snapshot.json"
    with urllib.request.urlopen(target, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _fmt_bytes(n: Any) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GiB"


def format_snapshot(snap: Dict[str, Any]) -> str:
    out = []
    dev = snap.get("device") or {}
    sched = snap.get("scheduler") or {}
    out.append(
        f"fleet @ {time.strftime('%H:%M:%S')}  "
        f"window={snap.get('window_s', 0):.0f}s  "
        f"samples={snap.get('n_samples', 0)}  "
        f"device occupancy={100 * dev.get('occupancy_frac', 0.0):.1f}%  "
        f"dispatch-loop idle="
        f"{100 * sched.get('loop_idle_frac', 1.0):.1f}%")
    out.append(
        f"scheduler: {sched.get('dispatches_total', 0)} dispatches, "
        f"queue depth {sched.get('queue_depth', 0)}, "
        f"{sched.get('n_active', 0)} active / "
        f"{sched.get('n_pending', 0)} pending search(es)")
    tenants = snap.get("tenants") or {}
    if tenants:
        out.append("")
        out.append(f"  {'tenant':<16} {'disp':>6} {'tasks':>8} "
                   f"{'thr/s':>8} {'share':>6} {'p50 wait':>9} "
                   f"{'p95 wait':>9} {'resident':>10}")
        for name in sorted(tenants):
            t = tenants[name]
            out.append(
                f"  {name:<16} {t.get('dispatches_total', 0):>6} "
                f"{t.get('tasks_total', 0):>8} "
                f"{t.get('throughput_tasks_per_s', 0.0):>8.1f} "
                f"{100 * t.get('share_frac', 0.0):>5.1f}% "
                f"{1e3 * t.get('queue_wait_p50_s', 0.0):>7.1f}ms "
                f"{1e3 * t.get('queue_wait_p95_s', 0.0):>7.1f}ms "
                f"{_fmt_bytes(t.get('residency_bytes', 0)):>10}")
    else:
        out.append("  (no tenant traffic in the window)")
    dp = snap.get("dataplane") or {}
    if dp:
        out.append(
            f"dataplane: {_fmt_bytes(dp.get('h2d_bytes_total', 0))} "
            f"host->device total "
            f"({_fmt_bytes(dp.get('h2d_bytes_per_s', 0))}/s), "
            f"cache {dp.get('hits', 0)} hits / "
            f"{dp.get('misses', 0)} misses, "
            f"{_fmt_bytes(dp.get('bytes_in_cache', 0))} resident")
    mem = snap.get("memory") or {}
    if mem:
        devs = mem.get("devices") or {}
        line = (f"memory: modeled peak "
                f"{_fmt_bytes(mem.get('modeled_peak_bytes', 0))}, "
                f"watermark {_fmt_bytes(mem.get('watermark_bytes', 0))}, "
                f"safety margin {mem.get('safety_margin', 1.0)}x, "
                f"{mem.get('n_oom_observed', 0)} OOM(s) observed")
        if mem.get("measured") and devs:
            pres = ", ".join(
                f"dev{k}={100 * d.get('pressure_frac', 0.0):.1f}%"
                for k, d in sorted(devs.items()))
            line += f"; pressure {pres}"
        else:
            line += " (allocator unmeasured — ledger model only)"
        out.append(line)
    ps = snap.get("programstore") or {}
    if ps:
        out.append(
            "programstore: "
            f"{ps.get('hit_total', ps.get('hits', 0))} hits / "
            f"{ps.get('miss_total', ps.get('misses', 0))} misses, "
            f"{ps.get('publish_total', ps.get('publishes', 0))} "
            "publishes, "
            f"{ps.get('quarantine_total', ps.get('quarantined', 0))} "
            "quarantined")
    prot = snap.get("protection") or {}
    if any(prot.get(k) for k in ("admitted_total", "queued_total",
                                 "rejected_total", "shed_total",
                                 "quarantined_total",
                                 "deadline_hits_total")):
        by_reason = ", ".join(
            f"{k}={v}" for k, v in sorted(
                (prot.get("rejected_by_reason") or {}).items()))
        line = (f"protection: {prot.get('admitted_total', 0)} admitted "
                f"/ {prot.get('queued_total', 0)} deferred "
                f"/ {prot.get('rejected_total', 0)} shed at admission"
                + (f" ({by_reason})" if by_reason else ""))
        line += (f"; {prot.get('shed_total', 0)} candidate(s) shed, "
                 f"{prot.get('quarantined_total', 0)} quarantined, "
                 f"{prot.get('deadline_hits_total', 0)} deadline "
                 "hit(s)")
        out.append(line)
    fus = snap.get("fusion") or {}
    if fus.get("fused_total"):
        lanes_real = fus.get("lanes_real_total", 0)
        lanes_pad = fus.get("lanes_padded_total", 0)
        line = (f"fusion: {fus.get('fused_total', 0)} fused launch(es) "
                f"carrying {fus.get('members_total', 0)} chunk(s), "
                f"{fus.get('saved_launches_total', 0)} launch(es) "
                f"saved, {lanes_real}/{lanes_pad} real/padded lanes")
        exchange = ", ".join(
            f"{name} +{n}" for name, n in sorted(
                (fus.get("lanes_borrowed_by_tenant") or {}).items()))
        donated = ", ".join(
            f"{name} -{n}" for name, n in sorted(
                (fus.get("lanes_donated_by_tenant") or {}).items()))
        if exchange or donated:
            line += ("; lanes borrowed/donated: "
                     + "; ".join(x for x in (exchange, donated) if x))
        out.append(line)
    hb = snap.get("heartbeat") or {}
    searches = hb.get("searches") or {}
    if searches:
        out.append("")
        out.append(f"  {'search':<20} {'segs':>5} {'steps':>11} "
                   f"{'progress':>9} {'eta':>8} {'beats':>6}")
        for handle in sorted(searches):
            pr = searches[handle] or {}
            total = int(pr.get("steps_total", 0) or 0)
            done = int(pr.get("steps_done", 0) or 0)
            frac = pr.get("frac")
            eta = pr.get("eta_s")
            out.append(
                f"  {str(handle):<20} {pr.get('segments', 0):>5} "
                f"{f'{done}/{total}':>11} "
                f"{('-' if frac is None else f'{100 * frac:.1f}%'):>9} "
                f"{('-' if eta is None else f'{eta:.1f}s'):>8} "
                f"{pr.get('beats', 0):>6}")
        out.append(
            f"heartbeat: {hb.get('beats_total', 0)} beat(s) / "
            f"{hb.get('chunk_beats_total', 0)} chunk beat(s), "
            f"cadence p50 {1e3 * hb.get('cadence_p50_s', 0.0):.1f}ms "
            f"p95 {1e3 * hb.get('cadence_p95_s', 0.0):.1f}ms, "
            f"staleness max {1e3 * hb.get('staleness_max_s', 0.0):.1f}ms")
    elif hb.get("beats_total") or hb.get("chunk_beats_total"):
        out.append(
            f"heartbeat: {hb.get('beats_total', 0)} beat(s) / "
            f"{hb.get('chunk_beats_total', 0)} chunk beat(s), "
            "no live search")
    else:
        # heartbeats off (TpuConfig.heartbeat / SST_HEARTBEAT unset):
        # the column renders `-` rather than vanishing, so a one-shot
        # reading is unambiguous about why there is no progress row
        out.append("search progress: -  (heartbeat disabled — set "
                   "TpuConfig(heartbeat=True) or SST_HEARTBEAT=1)")
    rec = snap.get("recovery") or {}
    if any(rec.get(k) for k in ("journal_entries_total",
                                "nonterminal_found_total",
                                "recovered_total", "mismatch_total",
                                "lease_takeovers_total",
                                "lease_conflicts_total",
                                "unclean_shutdowns_total")):
        line = (f"recovery: {rec.get('journal_entries_total', 0)} "
                f"journal entr"
                f"{'y' if rec.get('journal_entries_total') == 1 else 'ies'}"
                f" scanned, {rec.get('nonterminal_found_total', 0)} "
                f"non-terminal found / "
                f"{rec.get('recovered_total', 0)} recovered, "
                f"{rec.get('mismatch_total', 0)} mismatch(es), "
                f"{rec.get('lease_takeovers_total', 0)} lease "
                f"takeover(s) / {rec.get('lease_conflicts_total', 0)} "
                f"conflict(s)")
        ttr = rec.get("time_to_recover_s", 0.0) or 0.0
        if ttr:
            line += f"; time to recover {ttr:.2f}s"
        out.append(line)
    faults = snap.get("faults") or {}
    if faults.get("total"):
        by_cls = ", ".join(f"{k}={v}" for k, v in sorted(
            (faults.get("by_class") or {}).items()))
        out.append(f"faults: {faults['total']} ({by_cls})")
    flight = snap.get("flight") or {}
    out.append(
        f"flight recorder: {flight.get('n_buffered', 0)} buffered / "
        f"{flight.get('n_records', 0)} total record(s), "
        f"{flight.get('n_dumps', 0)} bundle(s) dumped")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="endpoint base url (default built from --port)")
    ap.add_argument("--port", type=int, default=None,
                    help="localhost endpoint port "
                         "(TpuConfig.telemetry_port)")
    ap.add_argument("--watch", type=float, default=None, metavar="SECS",
                    help="refresh every SECS seconds until interrupted "
                         "(default: print once and exit)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw snapshot JSON instead of the "
                         "table")
    args = ap.parse_args(argv)
    if not args.url and args.port is None:
        ap.error("pass --port or --url")
    url = args.url or f"http://127.0.0.1:{args.port}"

    def once() -> int:
        try:
            snap = fetch_snapshot(url)
        except (OSError, ValueError) as exc:
            print(f"error: fleet endpoint {url} unreachable ({exc})",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(snap, indent=2))
        else:
            print(format_snapshot(snap))
        if not snap.get("enabled"):
            print("error: telemetry service reports disabled",
                  file=sys.stderr)
            return 3
        return 0

    if args.watch is None:
        return once()
    try:
        while True:
            rc = once()
            if rc:
                return rc
            time.sleep(max(0.1, args.watch))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
