"""Digest a search's critical-path attribution in a terminal.

The search doctor's offline half: point it at a saved artifact and it
prints the lane decomposition (where the wall went), the one-line
verdict, and the cross-run regression status:

    python tools/sst_doctor.py ARTIFACT.json [--json]

Three artifact shapes digest here, auto-detected:

  - a saved ``search_report`` (``json.dumps(search.search_report)``):
    the stored ``attribution`` block prints directly; a report saved
    WITHOUT one (``TpuConfig(attribution=False)``, or predating the
    doctor) is re-analyzed from its pipeline/geometry/memory blocks,
    reproducing the in-process decomposition bit-for-bit;
  - a flight-recorder bundle (``obs/telemetry.py``; ``flight_format``
    key) — including the sentinel's ``regression-*`` bundles: the
    dump context's verdict/regression print next to compile and
    fault-recovery walls distilled from the embedded ``traceEvents``;
  - a run-log record (``obs/runlog.py``; ``runlog_format`` key): the
    archived attribution, provenance and geometry of one historical
    run;
  - a service journal (``serve/journal.py``; ``.jsonl`` of
    ``service_journal_format`` records, or one such record): the
    crash-safe WAL's submission/transition history folded into
    per-state totals, the non-terminal searches a restart would owe,
    and any lease fence/shutdown events.  Crash-marker flight bundles
    (``reason: "crash-marker"``) print the dead owner and what it
    still owed.

Exit status: 0 healthy, 1 when the artifact carries a flagged
regression (CI legs assert on this), 2 on an unrecognized file.

Stdlib-only: the analyzer (``spark_sklearn_tpu/obs/attribution.py``)
is loaded by file path — same pattern as ``tools/trace_summary.py`` —
so digesting a report never pays the jax import.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

__all__ = ["digest", "format_digest", "load_analyzer", "main"]


def load_analyzer():
    """The attribution module, loaded directly by file path so the
    digest never pays the package (jax) import; None when the source
    tree is not alongside this tool."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir,
        "spark_sklearn_tpu", "obs", "attribution.py")
    if not os.path.isfile(path):
        return None
    import importlib.util

    spec = importlib.util.spec_from_file_location("_sst_attribution", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["_sst_attribution"] = module
    spec.loader.exec_module(module)
    return module


def _classify(data: Any) -> str:
    """Which artifact shape is this? report / bundle / runlog /
    journal / ?"""
    if isinstance(data, list):
        if data and all(isinstance(d, dict)
                        and "service_journal_format" in d
                        for d in data):
            return "journal"
        return "?"
    if not isinstance(data, dict):
        return "?"
    if "flight_format" in data:
        return "bundle"
    if "runlog_format" in data:
        return "runlog"
    if "service_journal_format" in data:
        return "journal"
    if "attribution" in data or "pipeline" in data:
        return "report"
    return "?"


def _digest_report(data: Dict[str, Any], mod) -> Dict[str, Any]:
    block = data.get("attribution")
    source = "stored"
    if not isinstance(block, dict) or not block:
        if mod is None:
            return {"kind": "report", "error":
                    "report carries no attribution block and the "
                    "analyzer source is not alongside this tool"}
        # re-analyze from the raw blocks: wall is the pipeline's when
        # the report predates the doctor (no tracer spans offline, so
        # compile falls back to the modeled estimate)
        wall = float((data.get("pipeline") or {}).get("wall_s", 0.0)
                     or 0.0)
        block = mod.attribution_block(data, wall)
        source = "re-analyzed"
    return {"kind": "report", "source": source, "attribution": block,
            "regression": block.get("regression") or {}}


def _digest_bundle(data: Dict[str, Any], mod) -> Dict[str, Any]:
    ctx = data.get("context") or {}
    reg = ctx.get("regression") or {}
    out: Dict[str, Any] = {
        "kind": "bundle",
        "reason": data.get("reason", ""),
        "ts_unix_s": data.get("ts_unix_s"),
        "verdict": ctx.get("verdict", ""),
        "family": ctx.get("family", ""),
        "regression": reg,
    }
    if ctx.get("crash_marker"):
        out["crash_marker"] = {
            "previous_pid": ctx.get("previous_pid"),
            "previous_owner": ctx.get("previous_owner", ""),
            "n_nonterminal": ctx.get("n_nonterminal", 0),
            "nonterminal": ctx.get("nonterminal") or [],
        }
    if ctx.get("watchdog_mode"):
        out["heartbeat"] = {
            "watchdog_mode": ctx.get("watchdog_mode"),
            "last_step": ctx.get("last_step"),
            "steps_total": ctx.get("steps_total"),
        }
    if mod is not None:
        spans = mod.spans_from_chrome(data.get("traceEvents") or [])
        compile_s, fault_s, n_compile = mod._span_walls(spans)
        out["trace"] = {"compile_s": round(compile_s, 6),
                        "fault_s": round(fault_s, 6),
                        "n_compile_spans": n_compile}
    return out


#: mirror of serve/journal.py TERMINAL_STATES (stdlib-only tool: the
#: digest must not pay the package import)
_JOURNAL_TERMINAL = frozenset({"finished", "cancelled", "failed",
                               "shed", "recovered"})


def _digest_journal(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a service journal's records the way a warm restart does:
    per-kind/state totals plus the non-terminal searches a restart
    would owe (submission states outranked by any later transition,
    whichever file order the append race produced)."""
    subs: Dict[str, Dict[str, Any]] = {}
    states: Dict[str, str] = {}
    by_kind: Dict[str, int] = {}
    lease_events: List[Dict[str, Any]] = []
    clean_shutdowns = 0
    for doc in docs:
        kind = str(doc.get("kind", ""))
        by_kind[kind] = by_kind.get(kind, 0) + 1
        rec = doc.get("record") or {}
        handle = str(rec.get("handle", "") or "")
        if kind == "submitted" and handle:
            subs[handle] = rec
            states.setdefault(handle, str(rec.get("state", "admitted")))
        elif kind == "state" and handle:
            states[handle] = str(rec.get("state", ""))
        elif kind == "lease":
            lease_events.append(dict(rec))
        elif kind == "shutdown" and rec.get("clean"):
            clean_shutdowns += 1
    nonterminal = [
        {"handle": h, "tenant": str(sub.get("tenant", "")),
         "family": str(sub.get("family", "")),
         "state": states.get(h, ""),
         "checkpoint_dir": str(sub.get("checkpoint_dir", ""))}
        for h, sub in sorted(subs.items())
        if states.get(h) not in _JOURNAL_TERMINAL]
    by_state: Dict[str, int] = {}
    for h in subs:
        s = states.get(h, "")
        by_state[s] = by_state.get(s, 0) + 1
    return {
        "kind": "journal",
        "n_records": len(docs),
        "by_kind": dict(sorted(by_kind.items())),
        "n_submissions": len(subs),
        "by_state": dict(sorted(by_state.items())),
        "nonterminal": nonterminal,
        "lease_events": lease_events,
        "clean_shutdowns": clean_shutdowns,
        "regression": {},
    }


def _digest_runlog(data: Dict[str, Any]) -> Dict[str, Any]:
    rec = data.get("record") or {}
    return {
        "kind": "runlog",
        "family": data.get("family", ""),
        "structure_digest": data.get("structure_digest", ""),
        "ts_unix_s": rec.get("ts_unix_s"),
        "provenance": rec.get("provenance") or {},
        "attribution": rec.get("attribution") or {},
        "regression": {"status": rec.get("regression_status", "")},
    }


def digest(data: Any, mod=None) -> Dict[str, Any]:
    """Distill one loaded artifact into the printed digest's data
    structure (``kind`` names the detected shape; ``?`` when none
    matched)."""
    kind = _classify(data)
    if kind == "report":
        return _digest_report(data, mod)
    if kind == "bundle":
        return _digest_bundle(data, mod)
    if kind == "runlog":
        return _digest_runlog(data)
    if kind == "journal":
        return _digest_journal(
            data if isinstance(data, list) else [data])
    return {"kind": "?",
            "error": "unrecognized artifact: expected a search report, "
                     "flight bundle, run-log record or service "
                     "journal"}


def _lane_table(block: Dict[str, Any], lanes) -> List[str]:
    wall = float(block.get("wall_s", 0.0) or 0.0)
    out = [f"  {'lane':<14} {'seconds':>10} {'share':>7}"]
    for name in lanes:
        v = float(block.get(name, 0.0) or 0.0)
        pct = 100.0 * v / wall if wall > 0 else 0.0
        mark = "  <- dominant" \
            if name[:-2] == block.get("dominant") else ""
        out.append(f"  {name[:-2]:<14} {v:>10.3f} {pct:>6.1f}%{mark}")
    return out


def _regression_lines(reg: Dict[str, Any]) -> List[str]:
    status = reg.get("status", "")
    out = [f"regression: {status or '?'}"]
    for f in reg.get("flags") or []:
        out.append(
            f"  {f.get('metric', '?'):<14} "
            f"{f.get('baseline_s', 0.0):>8.3f}s -> "
            f"{f.get('current_s', 0.0):>8.3f}s  "
            f"(+{f.get('delta_s', 0.0):.3f}s, "
            f"x{f.get('ratio', 0.0):.2f})")
    return out


def format_digest(d: Dict[str, Any], mod=None) -> str:
    lanes = mod.LANES if mod is not None else (
        "compile_s", "stage_s", "compute_s", "gather_s",
        "queue_wait_s", "fault_s", "padding_s", "narrowing_s",
        "other_s")
    out: List[str] = []
    if d["kind"] == "report":
        block = d["attribution"]
        out.append(f"search report ({d['source']} attribution): "
                   f"wall {block.get('wall_s', 0.0):.3f} s, "
                   f"{block.get('n_compiles', 0)} compile(s) "
                   f"[{block.get('compile_source', '?')}]")
        out.extend(_lane_table(block, lanes))
        out.append(f"verdict: {block.get('verdict', '')}")
        for r in block.get("rungs") or []:
            out.append(f"  rung {r.get('iter')}: "
                       f"wall {r.get('wall_s', 0.0):.3f} s, "
                       f"dominant {r.get('dominant', '?')}")
        out.extend(_regression_lines(d["regression"]))
    elif d["kind"] == "bundle":
        out.append(f"flight bundle: reason {d['reason']!r}"
                   + (f", family {d['family']!r}" if d["family"] else ""))
        if d.get("verdict"):
            out.append(f"verdict: {d['verdict']}")
        cm = d.get("crash_marker") or {}
        if cm:
            out.append(
                f"crash marker: previous owner "
                f"{cm.get('previous_owner') or '?'} "
                f"(pid {cm.get('previous_pid') or '?'}) died holding "
                f"{cm.get('n_nonterminal', 0)} non-terminal "
                f"search(es)")
            for e in cm.get("nonterminal") or []:
                out.append(
                    f"  {e.get('handle', '?'):<28} "
                    f"tenant {e.get('tenant', '?'):<12} "
                    f"state {e.get('state', '?'):<10} "
                    f"family {e.get('family', '?')}")
        hb = d.get("heartbeat") or {}
        if hb:
            last = hb.get("last_step")
            total = hb.get("steps_total")
            out.append(
                f"watchdog: {hb.get('watchdog_mode')} — last beat at "
                f"scan step {'?' if last is None else last}"
                + (f" of {total}" if total is not None else ""))
        tr = d.get("trace") or {}
        if tr:
            out.append(f"trace: compile {tr['compile_s']:.3f} s over "
                       f"{tr['n_compile_spans']} span(s), fault "
                       f"recovery {tr['fault_s']:.3f} s")
        out.extend(_regression_lines(d["regression"]))
    elif d["kind"] == "journal":
        out.append(
            f"service journal: {d['n_records']} record(s) "
            f"({', '.join(f'{k}={v}' for k, v in d['by_kind'].items())}), "
            f"{d['n_submissions']} submission(s)")
        if d["by_state"]:
            out.append("  states: " + ", ".join(
                f"{k or '?'}={v}" for k, v in d["by_state"].items()))
        if d["lease_events"]:
            for e in d["lease_events"]:
                out.append(
                    f"  lease {e.get('event', '?')}: pid "
                    f"{e.get('previous_pid', '?')} "
                    f"({e.get('previous_owner') or '?'}) fenced by "
                    f"{e.get('owner', '?')} after "
                    f"{e.get('stale_age_s', 0.0)}s")
        if d["clean_shutdowns"]:
            out.append(f"  {d['clean_shutdowns']} clean shutdown(s)")
        nt = d["nonterminal"]
        if nt:
            out.append(f"  {len(nt)} NON-TERMINAL search(es) — a warm "
                       "restart owes these:")
            for e in nt:
                out.append(
                    f"    {e['handle']:<28} tenant {e['tenant']:<12} "
                    f"state {e['state']:<10} family {e['family']}")
        else:
            out.append("  no non-terminal searches — nothing owed")
    elif d["kind"] == "runlog":
        prov = d.get("provenance") or {}
        out.append(f"run-log record: family {d['family']!r}, structure "
                   f"{d['structure_digest']}, env "
                   f"{prov.get('env_digest', '?')}")
        block = d.get("attribution") or {}
        if block:
            out.extend(_lane_table(block, lanes))
            out.append(f"verdict: {block.get('verdict', '')}")
        out.extend(_regression_lines(d["regression"]))
    else:
        out.append(f"error: {d.get('error', 'unrecognized artifact')}")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("artifact", help="search report, flight bundle or "
                                     "run-log record (JSON)")
    ap.add_argument("--json", action="store_true",
                    help="emit the digest as JSON instead of a table")
    args = ap.parse_args(argv)
    with open(args.artifact, errors="replace") as f:
        text = f.read()
    try:
        data: Any = json.loads(text)
    except ValueError:
        # jsonl (a service journal): one document per line, torn tail
        # lines skipped exactly as the journal's own scan skips them
        docs = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                docs.append(json.loads(line))
            except ValueError:
                continue
        data = docs
    mod = load_analyzer()
    d = digest(data, mod)
    try:
        if args.json:
            print(json.dumps(d, indent=2))
        else:
            print(format_digest(d, mod))
    except BrokenPipeError:      # `... | head` is a legitimate use
        pass
    if d["kind"] == "?":
        print(f"error: {d.get('error')}", file=sys.stderr)
        return 2
    if (d.get("regression") or {}).get("status") == "regressed":
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
