"""Tabulate the benchmark history and flag cross-round regressions.

The repo keeps one ``BENCH_rNN.json`` payload per benchmark round
(``bench.py`` writes them).  This tool reads them all and prints the
trend a reviewer wants at a glance — cold/warm walls, the halving
speedup ratio, the program-store hit rate — then compares the last
two *parsed* rounds and exits nonzero when a headline metric moved
the wrong way by more than the threshold:

    python tools/bench_trend.py [--dir REPO] [--threshold PCT] [--json]

Wall seconds regress UP; the halving speedup and the store hit rate
regress DOWN.  The default threshold is deliberately generous (50%):
the rounds run on shared CPU boxes where tens-of-percent noise is
normal, and the gate exists to catch step changes, not jitter.
Rounds whose payload carries no parsed detail (infra failures,
timeouts) are listed but skipped by the comparison.

Stdlib-only, like the other ``tools/`` CLIs: the CI trend leg and
``bench.py`` (which embeds :func:`trend` output in its payload) must
never pay the jax import for bookkeeping.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

__all__ = ["collect_rounds", "compare_last_two", "format_table",
           "trend", "main"]

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

#: headline metrics: (row key, direction) — "up" means an increase is
#: a regression, "down" means a decrease is
_WATCHED = (
    ("wall_s_cold", "up"),
    ("wall_s_warm", "up"),
    ("halving_speedup", "down"),
    ("store_hit_rate", "down"),
    # protection actuations in the contended serve leg: a healthy
    # uncontended-capacity bench admits everything and sheds nothing,
    # so any increase is a capacity or admission regression
    ("serve_shed", "up"),
    # aggregate searches/min at the deepest contended serve level —
    # the throughput cross-search launch fusion is accountable for
    ("serve_spm", "down"),
    # sparse-vs-dense upload ratio in the stream_sparse leg: the BCOO
    # tier's whole point is nnz-proportional h2d, so the ratio creeping
    # up means something started densifying on the upload path
    ("sparse_h2d_ratio", "up"),
    # streamed h2d volume at the leg's fixed shape: growth means the
    # stream tier re-uploads or pads more than its plan claims
    ("stream_h2d_bytes", "up"),
    # scan-arm launches per compile group in the chunkloop A/B: the
    # device-resident loop's whole point is ONE launch per group, so
    # this sits at 1.0 and any creep up means segments are splitting
    # (budget miscounts) or segments are falling back per-chunk
    ("launches_per_group", "up"),
    # heartbeat beacon host fraction in the chunkloop A/B: the hub's
    # own measured cost of in-flight beats, contractually <2% of the
    # scanned wall — a step change up means the beacon (or something
    # on the callback path) got expensive
    ("hb_overhead", "up"),
    # warm-restart latency in the serve leg (serve/journal.py):
    # journal scan at session construction to the first successful
    # re-admission of a journaled non-terminal search — creep up means
    # the recovery path (journal fold, lease fence, fingerprint
    # verify, admission) got slower
    ("time_to_recover_s", "up"),
    # prefix computations avoided in the pipeline_prefix A/B: the
    # shared-prefix scheduler's whole point is computing each distinct
    # transformer chain once — this sits at candidates-minus-distinct
    # for the fixed 4x24 shape, and any drop means candidates started
    # recomputing their chains (digest grouping or eligibility broke)
    ("prefix_saved", "down"),
)


def _round_row(path: str) -> Dict[str, Any]:
    """One trend-table row distilled from a bench payload; metric
    values are None when the round carries no parsed detail."""
    n = int(_ROUND_RE.search(os.path.basename(path)).group(1))
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        payload = {}
    det = (payload.get("parsed") or {}).get("detail") or {}
    ha = det.get("halving_adaptive") or {}
    store = (det.get("persistent_cache_probe") or {}).get("prewarmed") \
        or {}
    hits = store.get("store_hits")
    misses = store.get("store_misses")
    hit_rate = None
    if hits is not None and misses is not None and (hits + misses) > 0:
        hit_rate = round(hits / (hits + misses), 4)
    # deepest contended serve level: shed work (rejected submits +
    # shed/quarantined candidates) — 0 on a healthy round, None before
    # the leg recorded admission/protection ledgers
    serve = det.get("serve_contended") or {}
    shed = None
    spm = None
    for key in sorted(k for k in serve if k.startswith("contended_")):
        adm = serve[key].get("admission")
        prot = serve[key].get("protection")
        if adm is not None and prot is not None:
            shed = (adm.get("rejected", 0) + prot.get("shed", 0)
                    + prot.get("quarantined", 0))
        if serve[key].get("searches_per_min") is not None:
            spm = serve[key]["searches_per_min"]
    ss = det.get("stream_sparse") or {}
    cl = det.get("chunkloop_scan") or {}
    px = det.get("pipeline_prefix") or {}
    return {
        "round": n,
        "rc": payload.get("rc"),
        "wall_s_cold": det.get("wall_s_cold"),
        "wall_s_warm": det.get("wall_s_warm"),
        "halving_speedup": ha.get("wall_ratio_exhaustive_over_halving"),
        "store_hit_rate": hit_rate,
        "serve_shed": shed,
        "serve_spm": spm,
        "sparse_h2d_ratio": ss.get("sparse_over_dense_h2d"),
        "stream_h2d_bytes": ss.get("stream_block_h2d_bytes"),
        "stream_shards": ss.get("stream_n_shards"),
        "launches_per_group": cl.get("scan_launches_per_group"),
        "hb_overhead": cl.get("hb_overhead_frac"),
        "prefix_saved": px.get("prefix_saved"),
        "time_to_recover_s": (serve.get("recovery")
                              or {}).get("time_to_recover_s"),
        "parsed": bool(det),
    }


def collect_rounds(directory: str) -> List[Dict[str, Any]]:
    """All ``BENCH_rNN.json`` rows under ``directory``, in round
    order."""
    paths = [p for p in glob.glob(os.path.join(directory,
                                               "BENCH_r*.json"))
             if _ROUND_RE.search(os.path.basename(p))]
    return sorted((_round_row(p) for p in paths),
                  key=lambda r: r["round"])


def compare_last_two(rows: List[Dict[str, Any]],
                     threshold_pct: float) -> Dict[str, Any]:
    """The regression comparison over the last two parsed rounds:
    per-metric deltas plus the flagged subset.  ``{"status":
    "insufficient-data"}`` when fewer than two rounds parsed."""
    parsed = [r for r in rows if r["parsed"]]
    if len(parsed) < 2:
        return {"status": "insufficient-data",
                "threshold_pct": threshold_pct, "flags": []}
    prev, last = parsed[-2], parsed[-1]
    flags: List[Dict[str, Any]] = []
    deltas: Dict[str, Any] = {}
    for key, direction in _WATCHED:
        a, b = prev.get(key), last.get(key)
        if a is None or b is None:
            continue
        if key == "hb_overhead":
            # contract gauge, not a throughput ratio: healthy values
            # sit around 1e-4 where percentage deltas are pure noise.
            # The flag is a step change THROUGH the <2% overhead
            # contract (obs/heartbeat.py), recorded in percentage
            # points
            deltas[key] = round(100.0 * (b - a), 4)
            if b > 0.02 and b > a:
                flags.append({"metric": key, "prev": a, "last": b,
                              "change_pct": deltas[key],
                              "direction": direction})
            continue
        if a == 0:
            # absolute counters (serve_shed): the healthy value IS
            # zero, so any move off it in the regressing direction is
            # a step change, not a percentage
            if direction == "up" and b > 0:
                flags.append({"metric": key, "prev": a, "last": b,
                              "change_pct": float("inf"),
                              "direction": direction})
            continue
        change_pct = round(100.0 * (b - a) / abs(a), 2)
        deltas[key] = change_pct
        regressed = change_pct > threshold_pct if direction == "up" \
            else change_pct < -threshold_pct
        if regressed:
            flags.append({"metric": key, "prev": a, "last": b,
                          "change_pct": change_pct,
                          "direction": direction})
    return {
        "status": "regressed" if flags else "ok",
        "rounds_compared": [prev["round"], last["round"]],
        "threshold_pct": threshold_pct,
        "deltas": deltas,
        "flags": flags,
    }


def trend(directory: str,
          threshold_pct: float = 50.0) -> Dict[str, Any]:
    """The whole digest (rows + comparison) as one JSON-able dict —
    ``bench.py`` embeds this in its payload."""
    rows = collect_rounds(directory)
    return {"rows": rows,
            "comparison": compare_last_two(rows, threshold_pct)}


def _fmt(v: Any, nd: int = 2) -> str:
    return "-" if v is None else f"{v:.{nd}f}"


def format_table(digest: Dict[str, Any]) -> str:
    out = [f"  {'round':>5} {'rc':>4} {'cold s':>9} {'warm s':>9} "
           f"{'halving x':>10} {'hit rate':>9} {'shed':>6} "
           f"{'srch/min':>9} {'sp/dn h2d':>10} {'strm h2d':>9} "
           f"{'shards':>7} {'l/grp':>6} {'hb ovh':>8} {'ttr s':>7} "
           f"{'px svd':>7}"]
    for r in digest["rows"]:
        out.append(
            f"  {r['round']:>5} {str(r['rc']):>4} "
            f"{_fmt(r['wall_s_cold']):>9} {_fmt(r['wall_s_warm']):>9} "
            f"{_fmt(r['halving_speedup']):>10} "
            f"{_fmt(r['store_hit_rate']):>9} "
            f"{_fmt(r.get('serve_shed'), 0):>6} "
            f"{_fmt(r.get('serve_spm')):>9} "
            f"{_fmt(r.get('sparse_h2d_ratio'), 4):>10} "
            f"{_fmt(r.get('stream_h2d_bytes'), 0):>9} "
            f"{_fmt(r.get('stream_shards'), 0):>7} "
            f"{_fmt(r.get('launches_per_group')):>6} "
            f"{_fmt(r.get('hb_overhead'), 5):>8} "
            f"{_fmt(r.get('time_to_recover_s'), 3):>7} "
            f"{_fmt(r.get('prefix_saved'), 0):>7}"
            + ("" if r["parsed"] else "   (no parsed detail)"))
    cmp_ = digest["comparison"]
    out.append(f"comparison: {cmp_['status']} "
               f"(threshold {cmp_['threshold_pct']:.0f}%)")
    for k, pct in (cmp_.get("deltas") or {}).items():
        out.append(f"  {k:<18} {pct:+.1f}%")
    for f in cmp_["flags"]:
        out.append(f"  REGRESSED {f['metric']}: {f['prev']} -> "
                   f"{f['last']} ({f['change_pct']:+.1f}%)")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=None,
                    help="directory holding BENCH_rNN.json (default: "
                         "the repo root above this tool)")
    ap.add_argument("--threshold", type=float, default=50.0,
                    help="regression threshold in percent "
                         "(default 50; CPU rounds are noisy)")
    ap.add_argument("--json", action="store_true",
                    help="emit the digest as JSON instead of a table")
    args = ap.parse_args(argv)
    directory = args.dir or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir)
    digest = trend(directory, args.threshold)
    try:
        if args.json:
            print(json.dumps(digest, indent=2))
        else:
            print(format_table(digest))
    except BrokenPipeError:      # `... | head` is a legitimate use
        pass
    if not digest["rows"]:
        print("error: no BENCH_rNN.json rounds found", file=sys.stderr)
        return 2
    return 1 if digest["comparison"]["status"] == "regressed" else 0


if __name__ == "__main__":
    sys.exit(main())
