"""Config-knob registry audit.

``TpuConfig`` is the engine's whole configuration surface and the
``SST_*`` env vars are its process-wide spellings.  These rules keep
the three views consistent: every field is actually read by the code,
every field is documented, and every env knob has a config-field twin
(or a justified exception in the project map) plus a row in the README
knob table.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.sstlint import astutil
from tools.sstlint.core import Context, Finding, ModuleInfo, rule


def _find_config_class(ctx: Context) -> Optional[Tuple[ModuleInfo,
                                                       ast.ClassDef]]:
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name == "TpuConfig":
                return mod, node
    return None


def _config_fields(cls: ast.ClassDef) -> Dict[str, int]:
    """field name -> lineno, from the dataclass's annotated
    assignments."""
    out: Dict[str, int] = {}
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            out[node.target.id] = node.lineno
    return out


def _attribute_reads(ctx: Context) -> Set[str]:
    """Every attribute name read (``x.field``) plus every literal
    passed to getattr() anywhere in the target tree.  Field
    DEFINITIONS are AnnAssign Name targets, never Attribute loads, so
    the config class needs no special casing — its own methods reading
    ``self.field`` are legitimate reads."""
    reads: Set[str] = set()
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load):
                reads.add(node.attr)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in ("getattr", "hasattr") and \
                    len(node.args) >= 2:
                s = astutil.literal_str(node.args[1])
                if s is not None:
                    reads.add(s)
    return reads


@rule("config-knob-unread")
def check_fields_read(ctx: Context) -> Iterable[Finding]:
    """Every ``TpuConfig`` field must be read somewhere in the package
    — a field nothing consumes is a knob users can set with zero
    effect, the most confusing kind of API surface."""
    hit = _find_config_class(ctx)
    if hit is None:
        return
    mod, cls = hit
    reads = _attribute_reads(ctx)
    for field, line in _config_fields(cls).items():
        if field in reads:
            continue
        if mod.suppressed("config-knob-unread", line):
            continue
        yield Finding(
            "config-knob-unread", mod.relpath, line,
            f"TpuConfig.{field} is never read by the package",
            symbol=field)


@rule("config-knob-undocumented")
def check_fields_documented(ctx: Context) -> Iterable[Finding]:
    """Every ``TpuConfig`` field must appear in ``docs/API.md`` — the
    generated reference renders the constructor signature, so a
    missing name means the docs were not regenerated after the config
    surface changed."""
    hit = _find_config_class(ctx)
    if hit is None:
        return
    docs = ctx.project.docs_api
    if not docs or not docs.is_file():
        return          # docs-stale already reports the missing file
    text = docs.read_text()
    mod, cls = hit
    for field, line in _config_fields(cls).items():
        # word-boundary match: a common-word field name (`trace`,
        # `verbose`) must not pass on incidental prose, and prose must
        # not mask a removed signature entry
        if re.search(rf"\b{re.escape(field)}\b[=:]", text):
            continue
        if mod.suppressed("config-knob-undocumented", line):
            continue
        yield Finding(
            "config-knob-undocumented", mod.relpath, line,
            f"TpuConfig.{field} does not appear in docs/API.md; "
            "regenerate with `python dev/build_api_docs.py`",
            symbol=field)


def _env_reads(ctx: Context) -> Dict[str, Tuple[str, int]]:
    """env var name -> (relpath, line) of first read, for vars with
    the project's prefix."""
    prefix = ctx.project.env_prefix
    out: Dict[str, Tuple[str, int]] = {}
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            name = None
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("get", "getenv") and node.args:
                chain = astutil.attr_chain(node.func.value) or ""
                if chain.endswith("environ") or chain == "os":
                    name = astutil.literal_str(node.args[0])
            elif isinstance(node, ast.Subscript):
                chain = astutil.attr_chain(node.value) or ""
                if chain.endswith("environ"):
                    name = astutil.literal_str(node.slice)
            if name and name.startswith(prefix):
                out.setdefault(name, (mod.relpath, node.lineno))
    return out


@rule("env-knob-unregistered")
def check_env_knobs(ctx: Context) -> Iterable[Finding]:
    """Every ``SST_*`` env var the package reads must have a matching
    ``TpuConfig`` field (derived name: ``SST_FAULT_PLAN`` ->
    ``fault_plan``) or a justified exception in the project map, and a
    row in the README knob table — env-only switches that bypass the
    config system are how behavior becomes untestable and
    undocumented."""
    hit = _find_config_class(ctx)
    fields = _config_fields(hit[1]) if hit else {}
    readme_text = ""
    if ctx.project.readme and ctx.project.readme.is_file():
        readme_text = ctx.project.readme.read_text()
    exceptions = ctx.project.env_field_exceptions
    prefix = ctx.project.env_prefix
    for var, (rel, line) in sorted(_env_reads(ctx).items()):
        mod = ctx.module(rel)
        if mod is not None and mod.suppressed(
                "env-knob-unregistered", line):
            continue
        derived = var[len(prefix):].lower()
        if derived not in fields and var not in exceptions:
            yield Finding(
                "env-knob-unregistered", rel, line,
                f"env var {var} has no matching TpuConfig field "
                f"({derived!r}) and no justified exception in the "
                "project map",
                symbol=f"{var}:field")
        if readme_text and not re.search(
                rf"\|\s*`{re.escape(var)}`", readme_text):
            # exact `VAR` table-row match: prose mentions and prefix
            # overlaps (SST_LOCKCHECK_HOLD_S vs SST_LOCKCHECK) don't
            # satisfy the knob-table contract
            yield Finding(
                "env-knob-unregistered", rel, line,
                f"env var {var} is missing from the README knob table",
                symbol=f"{var}:readme")


# ---------------------------------------------------------------------------
# Repo hygiene
# ---------------------------------------------------------------------------


@rule("tracked-bytecode")
def check_tracked_bytecode(ctx: Context) -> Iterable[Finding]:
    """Compiled bytecode (``__pycache__``/``*.pyc``) must never be
    committed — it bloats diffs, leaks machine paths, and goes stale
    the moment the source changes."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", str(ctx.project.root), "ls-files"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return
    if out.returncode != 0:
        return
    for path in out.stdout.splitlines():
        if "__pycache__" in path or path.endswith(".pyc"):
            yield Finding(
                "tracked-bytecode", path, 1,
                "compiled bytecode is committed; `git rm -r --cached` "
                "it (the .gitignore rules keep it out)",
                symbol=path)


@rule("gitignore-bytecode")
def check_gitignore(ctx: Context) -> Iterable[Finding]:
    """``.gitignore`` must cover ``__pycache__/`` and ``*.pyc`` so
    bytecode cannot re-enter the tree."""
    gi = ctx.project.root / ".gitignore"
    if not gi.is_file():
        yield Finding("gitignore-bytecode", ".gitignore", 1,
                      ".gitignore is missing", symbol="missing")
        return
    lines = {ln.strip() for ln in gi.read_text().splitlines()}
    for pat in ("__pycache__/", "*.pyc"):
        if pat not in lines:
            yield Finding(
                "gitignore-bytecode", ".gitignore", 1,
                f".gitignore lacks the {pat!r} pattern",
                symbol=pat)
