"""Span-vocabulary and report-schema drift checkers.

The observability surfaces are contracts: ``tools/trace_summary.py``
digests span names, and users script against ``search_report`` keys.
These rules pin both to their single sources of truth —
``spark_sklearn_tpu/obs/spans.py`` (the span vocabulary) and
``spark_sklearn_tpu/obs/metrics.py`` (the ``*_BLOCK_SCHEMA``
constants) — and keep ``docs/API.md`` fresh against them.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from tools.sstlint import astutil
from tools.sstlint.core import Context, Finding, ModuleInfo, rule

#: tracer-recording call attribute names and which argument carries
#: the span name
_RECORDERS = {"span": 0, "instant": 0, "record_span": 0,
              "record_async": 0}


def _span_calls(mod: ModuleInfo):
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr not in _RECORDERS:
            continue
        # only tracer-ish receivers: get_tracer(), tracer, tr,
        # self._tracer — anything whose chain mentions trace(r)
        recv = node.func.value
        chain = (astutil.attr_chain(recv) or "").lower()
        if isinstance(recv, ast.Call):
            chain = (astutil.call_name(recv) or "").lower()
        if "trace" not in chain and chain not in ("tr",):
            continue
        yield node


def _span_name(node: ast.Call) -> Optional[str]:
    """The literal (or f-string constant prefix) name of a recorder
    call; None when the name is not statically known."""
    if not node.args:
        return None
    arg = node.args[0]
    s = astutil.literal_str(arg)
    if s is not None:
        return s
    if isinstance(arg, ast.JoinedStr) and arg.values:
        first = arg.values[0]
        prefix = astutil.literal_str(first)
        if prefix is not None:
            return prefix.rstrip()
    return None


def _load_spans(ctx: Context):
    path = ctx.project.spans_path
    if not path or not path.is_file():
        return None
    return astutil.load_module_by_path(path, "_sstlint_spans")


@rule("span-unknown-name")
def check_span_vocabulary(ctx: Context) -> Iterable[Finding]:
    """Every recorded span/instant/async name must be registered in
    the span vocabulary (``obs/spans.py``) — trace_summary groups and
    documents by those names, so an ad-hoc name silently falls out of
    every digest."""
    spans = _load_spans(ctx)
    if spans is None:
        return
    for mod in ctx.modules:
        if mod.relpath.endswith("obs/trace.py"):
            continue               # the recorder itself
        for node in _span_calls(mod):
            name = _span_name(node)
            if name is None:
                if mod.suppressed("span-unknown-name", node.lineno):
                    continue
                yield Finding(
                    "span-unknown-name", mod.relpath, node.lineno,
                    "span name is not a literal/f-string with a "
                    "registered constant prefix — sstlint cannot "
                    "check it against the vocabulary",
                    symbol=f"<dynamic>@{mod.qualname(node)}")
                continue
            ok = spans.is_known_span(name) or (
                node.func.attr == "record_async"
                and spans.async_prefix(name) is not None)
            if not ok:
                if mod.suppressed("span-unknown-name", node.lineno):
                    continue
                yield Finding(
                    "span-unknown-name", mod.relpath, node.lineno,
                    f"span name {name!r} is not registered in "
                    "obs/spans.py SPAN_VOCABULARY",
                    symbol=name)


@rule("span-not-context-managed")
def check_span_with(ctx: Context) -> Iterable[Finding]:
    """``tracer.span(...)`` must be opened via ``with`` — a manually
    entered span with no guaranteed ``__exit__`` leaks an unclosed
    event on any exception path and corrupts the nesting the exporter
    relies on.  (``record_span``/``record_async`` take explicit
    timestamps and are exempt.)"""
    for mod in ctx.modules:
        if mod.relpath.endswith("obs/trace.py"):
            continue
        for node in _span_calls(mod):
            if node.func.attr != "span":
                continue
            parent = mod.parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            # allow `with x.span(...) as s` via withitem, and direct
            # return of a span from helper wrappers is disallowed
            if mod.suppressed("span-not-context-managed", node.lineno):
                continue
            yield Finding(
                "span-not-context-managed", mod.relpath, node.lineno,
                "tracer.span(...) used outside a `with` statement — "
                "open spans via context manager so __exit__ always "
                "runs",
                symbol=f"{mod.qualname(node) or '<module>'}"
                       f":{_span_name(node) or '?'}")


def _schema_keys(metrics_mod, attr: str) -> Optional[Set[str]]:
    defs = getattr(metrics_mod, attr, None)
    if defs is None:
        return None
    return {d.name for d in defs}


@rule("schema-block-drift")
def check_schema_drift(ctx: Context) -> Iterable[Finding]:
    """Every key a producer renders into a pinned ``search_report``
    block must be declared in its ``*_BLOCK_SCHEMA`` — and every
    declared key must be produced somewhere — so the documented report
    schema can never drift from what fit() actually returns."""
    if not ctx.project.metrics_path or \
            not ctx.project.metrics_path.is_file():
        return
    metrics = astutil.load_module_by_path(
        ctx.project.metrics_path, "_sstlint_metrics")
    for spec in ctx.project.blocks:
        declared = _schema_keys(metrics, spec.schema_attr)
        if declared is None:
            yield Finding(
                "schema-block-drift",
                _rel(ctx, ctx.project.metrics_path), 1,
                f"schema constant {spec.schema_attr} not found in the "
                "metrics module",
                symbol=spec.schema_attr)
            continue
        produced: Set[str] = set()
        anchor_line = 1
        anchor_rel = _rel(ctx, ctx.project.metrics_path)
        for prod in spec.producers:
            mod = ctx.module(prod.relpath)
            if mod is None:
                continue
            anchor_rel = mod.relpath
            if prod.kind == "dict-keys":
                produced |= astutil.dict_literal_keys_in(mod, prod.target)
            elif prod.kind == "subscript-var":
                produced |= astutil.subscript_store_keys(mod, prod.target)
        for extra in sorted(produced - declared):
            yield Finding(
                "schema-block-drift", anchor_rel, anchor_line,
                f"search_report[{spec.block!r}] renders key {extra!r} "
                f"that is not declared in {spec.schema_attr}",
                symbol=f"{spec.block}:+{extra}")
        for missing in sorted(declared - produced):
            yield Finding(
                "schema-block-drift",
                _rel(ctx, ctx.project.metrics_path), 1,
                f"{spec.schema_attr} declares {missing!r} but no "
                f"producer of search_report[{spec.block!r}] writes it",
                symbol=f"{spec.block}:-{missing}")


#: registry-handle methods and the receivers we treat as registries
_REG_METHODS = frozenset({"counter", "gauge", "label", "histogram",
                          "series", "struct", "put"})
_REG_RECEIVERS = frozenset({"metrics", "reg", "registry"})


@rule("report-key-undeclared")
def check_report_keys(ctx: Context) -> Iterable[Finding]:
    """Every metric name the engine writes through the strict registry
    (``metrics.counter("...")`` etc.) must be declared in
    ``SEARCH_REPORT_SCHEMA``, and every declared top-level key must be
    written somewhere — the full ``search_report`` surface stays
    pinned in one table."""
    if not ctx.project.metrics_path or \
            not ctx.project.metrics_path.is_file():
        return
    metrics = astutil.load_module_by_path(
        ctx.project.metrics_path, "_sstlint_metrics")
    declared = _schema_keys(metrics, "SEARCH_REPORT_SCHEMA")
    if declared is None:
        return
    used: Set[str] = set()
    first_use = {}
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _REG_METHODS:
                continue
            recv = astutil.attr_chain(node.func.value) or ""
            if recv.split(".")[-1] not in _REG_RECEIVERS:
                continue
            name = astutil.literal_str(node.args[0])
            if name is None:
                continue
            used.add(name)
            first_use.setdefault(name, (mod.relpath, node.lineno))
    for extra in sorted(used - declared):
        rel, line = first_use[extra]
        mod = ctx.module(rel)
        if mod is not None and mod.suppressed(
                "report-key-undeclared", line):
            continue
        yield Finding(
            "report-key-undeclared", rel, line,
            f"registry metric {extra!r} is not declared in "
            "SEARCH_REPORT_SCHEMA",
            symbol=f"+{extra}")
    for missing in sorted(declared - used):
        yield Finding(
            "report-key-undeclared", _rel(ctx, ctx.project.metrics_path),
            1,
            f"SEARCH_REPORT_SCHEMA declares {missing!r} but nothing "
            "writes it through a registry handle",
            symbol=f"-{missing}")


@rule("docs-stale")
def check_docs_fresh(ctx: Context) -> Iterable[Finding]:
    """``docs/API.md`` must contain the exact generated sections that
    ``dev/build_api_docs.py`` renders today — the ``search_report``
    schema (``obs.metrics.schema_markdown()``), the span vocabulary
    (``obs.spans.vocabulary_markdown()``), and the sstlint rule catalog
    (``tools.sstlint.catalog_markdown()``) — so regenerating the docs
    is part of changing any of them."""
    if not ctx.project.metrics_path or \
            not ctx.project.metrics_path.is_file():
        return
    if not ctx.project.docs_api or not ctx.project.docs_api.is_file():
        yield Finding(
            "docs-stale", "docs/API.md", 1,
            "docs/API.md is missing; run `python dev/build_api_docs.py`",
            symbol="missing")
        return
    docs_text = ctx.project.docs_api.read_text()
    metrics = astutil.load_module_by_path(
        ctx.project.metrics_path, "_sstlint_metrics")
    sections = [("obs.metrics.schema_markdown()", "schema-section",
                 getattr(metrics, "schema_markdown", lambda: "")())]
    spans = _load_spans(ctx)
    if spans is not None:
        sections.append(
            ("obs.spans.vocabulary_markdown()", "spans-section",
             getattr(spans, "vocabulary_markdown", lambda: "")()))
    from tools.sstlint import catalog_markdown
    sections.append(("tools.sstlint.catalog_markdown()",
                     "catalog-section", catalog_markdown()))
    for oracle, symbol, rendered in sections:
        if rendered and rendered not in docs_text:
            yield Finding(
                "docs-stale", _rel(ctx, ctx.project.docs_api), 1,
                f"docs/API.md no longer matches {oracle}; run "
                "`python dev/build_api_docs.py`",
                symbol=symbol)


def _rel(ctx: Context, path) -> str:
    try:
        return str(path.resolve().relative_to(ctx.project.root)
                   ).replace("\\", "/")
    except ValueError:
        return str(path)
