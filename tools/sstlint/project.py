"""The project model — what sstlint knows about THIS codebase.

sstlint is project-native by design: instead of generic heuristics it
carries an explicit map of the engine's concurrency and interface
contracts — which named locks exist (discovered from the
``named_lock``/``named_rlock`` factory calls in the source), which
shared containers each lock owns, which ``search_report`` blocks are
produced where, and which env knobs are deliberately config-field-less.
Tests point a :class:`Project` at fixture trees with their own maps;
the CLI uses :meth:`Project.default` for the real repository layout.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["BlockSpec", "EscapeHatch", "Producer", "Project",
           "SharedState"]


@dataclasses.dataclass(frozen=True)
class SharedState:
    """A container mutated by more than one thread, and the lock that
    owns it.  ``name`` is a module-global variable; ``cls``/``attrs``
    cover instance attributes of a class; ``taint_key`` additionally
    guards local variables derived from a subscript/``setdefault`` of
    that literal key (e.g. the staged-chunk id set living inside a
    plan dict)."""

    relpath: str
    lock: str
    name: str = ""
    cls: str = ""
    attrs: Tuple[str, ...] = ()
    taint_key: str = ""


@dataclasses.dataclass(frozen=True)
class Producer:
    """One place a report block's keys are written.

    ``kind``:
      - "dict-keys": every string key of every dict literal inside the
        function ``qualname`` of ``relpath``;
      - "subscript-var": every literal key stored via
        ``<var>["key"] = ...`` anywhere in ``relpath``.
    """

    kind: str
    relpath: str
    target: str            # qualname (dict-keys) or var name (subscript-var)


@dataclasses.dataclass(frozen=True)
class BlockSpec:
    """One pinned ``search_report`` sub-block: the schema constant in
    the metrics module vs. the producers that render it."""

    block: str             # report key ("pipeline", "dataplane", ...)
    schema_attr: str       # constant name in the metrics module
    producers: Tuple[Producer, ...]


@dataclasses.dataclass(frozen=True)
class EscapeHatch:
    """One documented byte-parity escape hatch: a knob whose off/
    default state is CLAIMED (README/docstrings) to reproduce the
    pre-feature engine exactly.  The claim is only as good as the
    parity test that pins it, so every registered hatch names one:
    ``parity_test`` is ``"tests/test_x.py::test_name"`` and the
    ``escape-hatch-untested`` rule fails when it stops resolving.
    Claim lines in README/docstrings naming an unregistered knob are
    ``escape-hatch-unregistered`` findings."""

    name: str              # short registry name ("fusion", ...)
    knob: str              # TpuConfig field the claim is about
    parity_test: str       # "tests/test_x.py::test_name"
    claim: str = ""        # what "off" is claimed to reproduce


@dataclasses.dataclass
class Project:
    """Paths + contract map for one lintable tree."""

    root: Path                          # repo root
    package: Path                       # package dir to lint
    readme: Optional[Path] = None
    docs_api: Optional[Path] = None
    metrics_path: Optional[Path] = None   # obs/metrics.py (import-light)
    spans_path: Optional[Path] = None     # obs/spans.py (import-light)
    #: utils/keycheck.py — the cache-key surface registry + runtime
    #: recorder the keyflow rules load import-light
    keycheck_path: Optional[Path] = None
    #: utils/journalspec.py — the versioned journal record registry
    journalspec_path: Optional[Path] = None
    #: tests/ dir escape-hatch parity-test pointers resolve against
    tests_dir: Optional[Path] = None
    #: every documented byte-parity escape hatch, with its pinning test
    escape_hatches: Tuple["EscapeHatch", ...] = ()
    #: (lock-prefix, lock-prefix) pairs allowed to nest across modules
    allowed_cross_module: Tuple[Tuple[str, str], ...] = ()
    shared_state: Tuple[SharedState, ...] = ()
    blocks: Tuple[BlockSpec, ...] = ()
    #: modules/functions on the launch path, where broad handlers must
    #: stay taxonomy-aware (relpaths, or "relpath::funcname")
    launch_paths: Tuple[str, ...] = ()
    #: env vars deliberately WITHOUT a TpuConfig field, with the reason
    env_field_exceptions: Dict[str, str] = dataclasses.field(
        default_factory=dict)
    #: env var name prefix the knob audit owns
    env_prefix: str = "SST_"
    #: relpaths excluded from source rules (the lock shim itself, ...)
    exclude: Tuple[str, ...] = ()

    @classmethod
    def default(cls, root) -> "Project":
        """The real spark_sklearn_tpu layout and contract map."""
        root = Path(root).resolve()
        pkg = root / "spark_sklearn_tpu"
        return cls(
            root=root,
            package=pkg,
            readme=root / "README.md",
            docs_api=root / "docs" / "API.md",
            metrics_path=pkg / "obs" / "metrics.py",
            spans_path=pkg / "obs" / "spans.py",
            keycheck_path=pkg / "utils" / "keycheck.py",
            journalspec_path=pkg / "utils" / "journalspec.py",
            tests_dir=root / "tests",
            escape_hatches=(
                EscapeHatch(
                    "fusion", "fusion",
                    "tests/test_fusion.py::"
                    "test_fusion_off_block_shape_and_parity",
                    claim="`0` reproduces the pre-fusion engine "
                          "exactly"),
                EscapeHatch(
                    "prefix_reuse", "prefix_reuse",
                    "tests/test_prefix.py::"
                    "test_shared_matches_atomic_exact",
                    claim="`0` is the bit-exact atomic escape hatch"),
                EscapeHatch(
                    "heartbeat", "heartbeat",
                    "tests/test_heartbeat.py::"
                    "test_parity_and_cache_separation",
                    claim="default off = exact no-op (key and traced "
                          "program byte-identical)"),
                EscapeHatch(
                    "memory_ledger", "memory_ledger",
                    "tests/test_memory.py::test_ledger_off_exact_noop",
                    claim="False is the byte-identical pre-ledger "
                          "escape hatch"),
                EscapeHatch(
                    "attribution", "attribution",
                    "tests/test_doctor.py::"
                    "test_attribution_off_is_absent_and_byte_identical",
                    claim="attribution=False is a byte-identical "
                          "escape hatch"),
                EscapeHatch(
                    "runlog", "runlog",
                    "tests/test_doctor.py::"
                    "test_runlog_off_never_touches_disk",
                    claim="runlog=False is a byte-identical escape "
                          "hatch"),
                EscapeHatch(
                    "service_journal", "service_journal_dir",
                    "tests/test_service_journal.py::"
                    "test_default_off_is_exact_noop",
                    claim="unset = exact no-op (zero writes, zero "
                          "reads)"),
                EscapeHatch(
                    "protection", "partial_results",
                    "tests/test_protection.py::"
                    "test_no_block_and_exact_when_off",
                    claim="all-default = byte-identical "
                          "protection-off escape hatch"),
                EscapeHatch(
                    "chunk_loop", "chunk_loop",
                    "tests/test_chunkloop.py::"
                    "test_scan_matches_per_chunk_exact",
                    claim="per_chunk is the resumable/faultable "
                          "baseline scan must match exactly"),
                EscapeHatch(
                    "pipeline_depth", "pipeline_depth",
                    "tests/test_pipeline.py::test_family_matrix_parity",
                    claim="0 = fully synchronous, bit-for-bit the "
                          "pre-pipeline execution order"),
                EscapeHatch(
                    "fuse_fit_score", "fuse_fit_score",
                    "tests/test_score_parity.py::"
                    "test_logreg_multimetric_binary",
                    claim="False restores separate fit/score launches "
                          "everywhere"),
                EscapeHatch(
                    "sort_candidates", "sort_candidates",
                    "tests/test_sorted_chunking.py::"
                    "test_scores_match_and_iterations_drop",
                    claim="False restores single-width unsorted "
                          "chunking; same cv_results_ order either "
                          "way"),
                # surfaced by the escape-hatch audit itself: both were
                # long-standing README/docstring parity claims with
                # tests but no registration
                EscapeHatch(
                    "geometry_fixed", "geometry_mode",
                    "tests/test_geometry.py::"
                    "test_report_and_auto_vs_fixed_exact_parity",
                    claim='"fixed" restores the legacy width rule '
                          "bit-for-bit"),
                EscapeHatch(
                    "runlog_dir", "runlog_dir",
                    "tests/test_doctor.py::"
                    "test_runlog_off_never_touches_disk",
                    claim="no configured directory = exact no-op (no "
                          "store, no records, byte-identical reports)"),
            ),
            allowed_cross_module=(),
            shared_state=(
                # dataplane: process-wide transfer totals + the plane
                SharedState("parallel/dataplane.py",
                            "dataplane._TOTALS_LOCK", name="_TOTALS"),
                SharedState("parallel/dataplane.py",
                            "dataplane._PLANE_LOCK", name="_PLANE"),
                SharedState("parallel/dataplane.py",
                            "dataplane.DataPlane._lock", cls="DataPlane",
                            attrs=("_entries", "_bytes", "_tile_programs",
                                   "hits", "misses", "evictions",
                                   "bytes_uploaded", "bytes_tiled",
                                   "bytes_derived", "byte_budget")),
                SharedState("parallel/dataplane.py",
                            "dataplane.StagingRing._lock",
                            cls="StagingRing", attrs=("_rings",)),
                # pipeline: persistent-cache event counters
                SharedState("parallel/pipeline.py",
                            "pipeline._LISTENER_LOCK",
                            name="_CACHE_EVENTS"),
                # faults: the supervisor's recovery bookkeeping
                SharedState("parallel/faults.py",
                            "faults.LaunchSupervisor._lock",
                            cls="LaunchSupervisor",
                            attrs=("faults", "_retries_used",
                                   "_sticky_oom", "_oom_dumped",
                                   "_sticky_fatal", "_fatal_counts",
                                   "_fatal_dumped")),
                # taskgrid: the geometry plan cache + cost model
                SharedState("parallel/taskgrid.py",
                            "taskgrid._PLAN_CACHE_LOCK",
                            name="_PLAN_CACHE"),
                SharedState("parallel/taskgrid.py",
                            "taskgrid.GeometryCostModel._lock",
                            cls="GeometryCostModel",
                            attrs=("launch_overhead_s", "lane_cost_s",
                                   "compile_wall_s", "n_observations")),
                # grid: per-plan staged-chunk id sets
                SharedState("search/grid.py", "grid.stage_lock",
                            taint_key="staged_ids"),
                # grid: the cross-search program cache, hit by every
                # concurrent search's worker + compile threads
                SharedState("search/grid.py", "grid._PROGRAM_CACHE_LOCK",
                            name="_PROGRAM_CACHE"),
                SharedState("search/grid.py", "grid._PROGRAM_CACHE_LOCK",
                            name="_PROGRAM_CACHE_FAMILY_COUNTS"),
                # serve: the fair-share executor's scheduler state
                SharedState("serve/executor.py",
                            "serve.SearchExecutor._lock",
                            cls="SearchExecutor",
                            attrs=("_tenants", "_active", "_pending",
                                   "_workers", "_rr", "_seq",
                                   "_last_handle", "_cost_by_tenant",
                                   "_dispatch_log", "_recent_walls",
                                   "_fuse_defer")),
                # dataplane: per-tenant quota/usage accounting
                SharedState("parallel/dataplane.py",
                            "dataplane.DataPlane._lock", cls="DataPlane",
                            attrs=("_tenant_quotas", "_tenant_bytes")),
                # obs/log: the logger cache
                SharedState("obs/log.py", "log._LOGGERS_LOCK",
                            name="_LOGGERS"),
                # obs/telemetry: the fleet-telemetry aggregator, hit by
                # every note_* hook (dispatch loop, gather threads,
                # supervisor recovery) plus the sampler thread
                SharedState("obs/telemetry.py",
                            "telemetry.TelemetryService._lock",
                            cls="TelemetryService",
                            attrs=("enabled", "_enable_count",
                                   "window_s", "interval_s",
                                   "_t_enabled", "_we_enabled_tracer",
                                   "_thread", "_tenants", "_device_busy",
                                   "_sched_busy",
                                   "_sched_dispatches_total",
                                   "_faults_by_class",
                                   "_faults_by_action", "_h2d",
                                   "_h2d_window", "_ps_events",
                                   "_regression",
                                   "_admission", "_admission_reasons",
                                   "_protection",
                                   "_fusion", "_fusion_borrowed",
                                   "_fusion_donated", "_recovery",
                                   "_providers", "_polls",
                                   "_n_samples")),
                # obs/telemetry: the always-on flight-recorder ring
                SharedState("obs/telemetry.py",
                            "telemetry.FlightRecorder._lock",
                            cls="FlightRecorder",
                            attrs=("_ring", "_n_dumps", "_n_records")),
                # parallel/memledger: the device-memory ledger, hit by
                # the pipeline's launch-boundary hook (gather thread),
                # the telemetry sampler, the geometry planner and the
                # supervisor's OOM forensics
                SharedState("parallel/memledger.py",
                            "memledger.MemoryLedger._lock",
                            cls="MemoryLedger",
                            attrs=("_active", "_measured",
                                   "watermark_bytes",
                                   "peak_modeled_bytes",
                                   "safety_margin", "n_samples",
                                   "n_oom", "_devices", "_groups",
                                   "_compiled")),
                # faults: injected-stall bookkeeping for the heartbeat
                # watchdog drill
                SharedState("parallel/faults.py",
                            "faults.LaunchSupervisor._lock",
                            cls="LaunchSupervisor",
                            attrs=("_hb_stall_keys",)),
                # obs/heartbeat: the in-flight beacon hub, hit by the
                # device callback (runtime thread), the dispatch loop's
                # register/complete hooks, the watchdog's staleness
                # polls and every progress()/snapshot reader
                SharedState("obs/heartbeat.py",
                            "heartbeat.HeartbeatHub._lock",
                            cls="HeartbeatHub",
                            attrs=("_ring", "_next_token", "_by_token",
                                   "_live_by_key", "_done",
                                   "_beats_total", "_chunk_beats_total",
                                   "_segments_total",
                                   "_capped_dropped")),
                # obs/runlog: the persistent run-history store, hit by
                # the doctor's end-of-fit append and by any concurrent
                # session sharing the process-wide active log
                SharedState("obs/runlog.py",
                            "runlog.RunLog._lock",
                            cls="RunLog",
                            attrs=("_seq", "_counts")),
                # serve/journal: the durable service WAL, appended by
                # the submit path, worker threads and the shutdown
                # drain (always OUTSIDE the executor's lock)
                SharedState("serve/journal.py",
                            "journal.ServiceJournal._lock",
                            cls="ServiceJournal",
                            attrs=("_seq", "_counts")),
            ),
            blocks=(
                BlockSpec("pipeline", "PIPELINE_BLOCK_SCHEMA", (
                    Producer("dict-keys", "parallel/pipeline.py",
                             "ChunkPipeline.report"),
                    Producer("subscript-var", "search/grid.py", "pr"),
                )),
                BlockSpec("dataplane", "DATAPLANE_BLOCK_SCHEMA", (
                    Producer("dict-keys", "parallel/dataplane.py",
                             "report_block"),
                )),
                BlockSpec("geometry", "GEOMETRY_BLOCK_SCHEMA", (
                    Producer("dict-keys", "parallel/taskgrid.py",
                             "GeometryPlan.report_block"),
                )),
                BlockSpec("faults", "FAULTS_BLOCK_SCHEMA", (
                    Producer("dict-keys", "parallel/faults.py",
                             "LaunchSupervisor.__init__"),
                    Producer("subscript-var", "search/grid.py",
                             "faults"),
                )),
                BlockSpec("scheduler", "SCHEDULER_BLOCK_SCHEMA", (
                    Producer("dict-keys", "serve/executor.py",
                             "report_block"),
                    Producer("dict-keys", "serve/executor.py",
                             "SearchExecutor.search_block"),
                )),
                BlockSpec("halving", "HALVING_BLOCK_SCHEMA", (
                    Producer("dict-keys", "search/halving.py",
                             "_render_halving_block"),
                )),
                BlockSpec("memory", "MEMORY_BLOCK_SCHEMA", (
                    Producer("dict-keys", "parallel/memledger.py",
                             "report_block"),
                )),
                BlockSpec("attribution", "ATTRIBUTION_BLOCK_SCHEMA", (
                    Producer("dict-keys", "obs/attribution.py",
                             "attribution_block"),
                )),
                BlockSpec("streaming", "STREAMING_BLOCK_SCHEMA", (
                    Producer("dict-keys", "parallel/taskgrid.py",
                             "StreamPlan.report_block"),
                    Producer("dict-keys", "search/stream.py",
                             "_streaming_counters"),
                )),
                BlockSpec("telemetry", "TELEMETRY_SNAPSHOT_SCHEMA", (
                    Producer("dict-keys", "obs/telemetry.py",
                             "TelemetryService.snapshot"),
                )),
                BlockSpec("protection", "PROTECTION_BLOCK_SCHEMA", (
                    Producer("dict-keys", "parallel/faults.py",
                             "protection_block"),
                )),
                BlockSpec("chunkloop", "CHUNKLOOP_BLOCK_SCHEMA", (
                    Producer("dict-keys", "search/grid.py",
                             "chunkloop_block"),
                )),
                BlockSpec("prefix", "PREFIX_BLOCK_SCHEMA", (
                    Producer("dict-keys", "search/prefix.py",
                             "prefix_block"),
                )),
                BlockSpec("heartbeat", "HEARTBEAT_BLOCK_SCHEMA", (
                    Producer("dict-keys", "obs/heartbeat.py",
                             "heartbeat_block"),
                )),
                BlockSpec("recovery", "RECOVERY_BLOCK_SCHEMA", (
                    Producer("dict-keys", "obs/telemetry.py",
                             "TelemetryService._recovery_block"),
                )),
            ),
            launch_paths=(
                "parallel/faults.py",
                "parallel/pipeline.py",
                "serve/executor.py",
                "search/grid.py::_dispatch",
                "search/grid.py::submit_precompile",
                "search/grid.py::resolve_fused",
                "search/grid.py::exec_fused_range",
                "search/grid.py::attempt",
                "search/grid.py::guarded_launch",
                "search/grid.py::guarded_wait",
                "search/grid.py::host_eval",
            ),
            env_field_exceptions={
                "SST_LOCKCHECK": (
                    "process-wide test-harness toggle; the lock shim "
                    "must exist before any TpuConfig is constructed"),
                "SST_LOCKCHECK_HOLD_S": (
                    "tuning companion of SST_LOCKCHECK; same "
                    "pre-config lifetime"),
                "SST_KEYCHECK": (
                    "process-wide test-harness toggle (key-flow "
                    "recorder twin of SST_LOCKCHECK); read per note() "
                    "call, before any TpuConfig exists"),
            },
            exclude=(),
        )
