"""CLI: ``python -m tools.sstlint [path]``.

Exit status: 0 = clean (baselined findings allowed), 1 = new
findings, 2 = internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.sstlint import (DEFAULT_BASELINE, RULES, Project, run_lint,
                           save_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.sstlint",
        description="project-native static analysis for "
                    "spark_sklearn_tpu")
    ap.add_argument("path", nargs="?", default=None,
                    help="the ONE package dir to lint (default: "
                         "spark_sklearn_tpu/ next to tools/); the "
                         "project-level rules key off its repo root")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to grandfather every "
                         "current finding (justifications carried "
                         "forward; new entries get TODO markers)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name:<28} {RULES[name].rationale}")
        return 0

    repo_root = Path(__file__).resolve().parents[2]
    if args.path:
        pkg = Path(args.path).resolve()
        if not pkg.is_dir():
            print(f"sstlint: not a directory: {pkg}", file=sys.stderr)
            return 2
        # the package's repo root is its parent (project files like
        # README/.gitignore/docs live there)
        project = Project.default(pkg.parent)
        project.package = pkg
    else:
        project = Project.default(repo_root)

    rules = [r.strip() for r in args.rules.split(",")] \
        if args.rules else None
    baseline_path = Path(args.baseline) if args.baseline else None
    result = run_lint(project, rules=rules, baseline_path=baseline_path)

    if args.update_baseline:
        bpath = Path(result["_baseline_path"])
        save_baseline(bpath, result["_finding_objs"],
                      result["_baseline"])
        print(f"sstlint: wrote {len(result['_finding_objs'])} "
              f"finding(s) to {bpath}")
        return 0

    if args.format == "json":
        clean = {k: v for k, v in result.items()
                 if not k.startswith("_")}
        print(json.dumps(clean, indent=2))
    else:
        for f in result["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['rule']}] "
                  f"{f['message']}")
        for f in result["baselined"]:
            print(f"{f['path']}:{f['line']}: [{f['rule']}] (baselined) "
                  f"{f['message']}")
        print(f"sstlint: {result['n_rules']} rules, "
              f"{result['n_findings']} new finding(s), "
              f"{result['n_baselined']} baselined, "
              f"{result['duration_s']}s")
    return 1 if result["n_findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
