"""Lock-order / shared-state checkers.

The engine's threads (main dispatcher, sst-stage, sst-gather,
sst-compile, watchdog, recovery) contend on a small set of NAMED locks
created through ``spark_sklearn_tpu.utils.locks``.  These rules build
the static acquisition graph over those names and enforce the three
invariants the PR-review cycles kept re-checking by hand:

  1. the graph is acyclic (a cycle is the deadlock precondition);
  2. no lock is taken while holding another module's lock, unless the
     pair is explicitly allowed in the project map (cross-module
     nesting is how unrelated subsystems accidentally couple);
  3. every registered shared container (dataplane byte totals, plane
     LRU state, supervisor fault counters, stage bookkeeping sets,
     geometry caches, logger cache) is only mutated under its owning
     lock.

The companion RUNTIME recorder (``SST_LOCKCHECK=1``,
``spark_sklearn_tpu/utils/locks.py``) checks the same order property
against actual executions during the test suite.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from tools.sstlint import astutil
from tools.sstlint.astutil import LockTable
from tools.sstlint.core import Context, Finding, ModuleInfo, rule

_PKG_FALLBACK = "spark_sklearn_tpu"


def _package_name(ctx: Context) -> str:
    return ctx.project.package.name or _PKG_FALLBACK


class _Graph:
    """Static acquisition graph over lock ids."""

    def __init__(self):
        #: (held, acquired) -> (relpath, line, how)
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add(self, held: str, acquired: str, relpath: str, line: int,
            how: str) -> None:
        if held == acquired:      # reentrant RLock use: no self-edges
            return
        self.edges.setdefault((held, acquired), (relpath, line, how))

    def cycles(self) -> List[List[str]]:
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        out, state = [], {}

        def dfs(n, path):
            state[n] = 1
            for m in adj.get(n, ()):
                if state.get(m) == 1:
                    out.append(path[path.index(m):] + [m])
                elif state.get(m) is None:
                    dfs(m, path + [m])
            state[n] = 2

        for n in sorted(adj):
            if state.get(n) is None:
                dfs(n, [n])
        return out


def _walk_same_frame(root: ast.AST):
    """Yield nodes under `root` WITHOUT descending into nested
    function/lambda bodies — a callback defined under a lock runs in
    whatever frame later invokes it, so its acquisitions must not be
    attributed to this lock hold."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _build(ctx: Context):
    """(per-module LockTable, acquisition graph, per-function acquire
    sets) for the whole target tree — memoized on the Context, since
    three rules share the same derived data."""
    cached = getattr(ctx, "_lockorder_build", None)
    if cached is not None:
        return cached
    pkg = _package_name(ctx)
    tables: Dict[str, LockTable] = {}
    acquires: Dict[Tuple[str, str], Set[str]] = {}
    for mod in ctx.modules:
        tables[mod.relpath] = LockTable.build(mod)
    # pass 1: what each function acquires directly
    for mod in ctx.modules:
        table = tables[mod.relpath]
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            fn = mod.enclosing_function(node)
            qn = mod.qualname(fn) if fn is not None else ""
            for item in node.items:
                lock = table.resolve(mod, item.context_expr)
                if lock is not None:
                    acquires.setdefault((mod.relpath, qn), set()).add(lock)
    # flatten to lookup keys callees can be resolved against
    by_name: Dict[Tuple[str, str], Set[str]] = {}
    for (relpath, qn), locks in acquires.items():
        by_name[(relpath, qn)] = locks
        # also index by trailing name so `self.m()` / `mod.f()` resolve
        tail = qn.rsplit(".", 1)[-1] if qn else qn
        by_name.setdefault((relpath, "~" + tail), set()).update(locks)

    graph = _Graph()
    known_rels = {m.relpath for m in ctx.modules}
    for mod in ctx.modules:
        table = tables[mod.relpath]
        aliases = astutil.import_aliases(mod, pkg)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            held = [table.resolve(mod, i.context_expr)
                    for i in node.items]
            held = [h for h in held if h is not None]
            if not held:
                continue
            for inner in _walk_same_frame(node):
                # direct nested acquisition
                if isinstance(inner, ast.With):
                    for item in inner.items:
                        lock = table.resolve(mod, item.context_expr)
                        if lock is not None:
                            for h in held:
                                graph.add(h, lock, mod.relpath,
                                          inner.lineno, "with")
                # one-hop call-through to a project function
                elif isinstance(inner, ast.Call):
                    chain = astutil.call_name(inner)
                    if not chain:
                        continue
                    for lock in _callee_locks(chain, mod, aliases,
                                              by_name, known_rels):
                        for h in held:
                            graph.add(h, lock, mod.relpath,
                                      inner.lineno, f"call {chain}")
    ctx._lockorder_build = (tables, graph)
    return ctx._lockorder_build


def _callee_locks(chain: str, mod: ModuleInfo, aliases: Dict[str, str],
                  by_name: Dict[Tuple[str, str], Set[str]],
                  known_rels: Set[str]) -> Set[str]:
    parts = chain.split(".")
    if len(parts) == 1:
        hits = by_name.get((mod.relpath, "~" + parts[0]), set())
        if hits:
            return hits
        # `from pkg.mod import func`: the alias maps func to the
        # non-existent "mod/func.py" — re-split into (mod.py, func)
        rel = aliases.get(parts[0])
        if rel and rel not in known_rels and "/" in rel:
            base, leaf = rel.rsplit("/", 1)
            return by_name.get((base + ".py", "~" + leaf[:-3]), set())
        return set()
    if parts[0] == "self" and len(parts) == 2:
        return by_name.get((mod.relpath, "~" + parts[1]), set())
    rel = aliases.get(parts[0])
    if rel is not None and len(parts) == 2:
        return by_name.get((rel, "~" + parts[1]), set())
    return set()


@rule("lock-order-cycle")
def check_lock_order(ctx: Context) -> Iterable[Finding]:
    """The static lock-acquisition graph over the engine's named locks
    must be acyclic — a cycle means two threads can each hold the lock
    the other needs, the deadlock precondition.

    Edges come from lexically nested ``with`` acquisitions plus a
    one-hop call-through to project functions that acquire locks."""
    graph = _build(ctx)[1]
    for cyc in graph.cycles():
        first_edge = (cyc[0], cyc[1]) if len(cyc) > 1 else None
        rel, line = "", 1
        if first_edge and first_edge in graph.edges:
            rel, line, _ = graph.edges[first_edge]
        m = ctx.module(rel) if rel else None
        if m is not None and m.suppressed("lock-order-cycle", line):
            continue
        yield Finding(
            "lock-order-cycle", rel or "<graph>", line,
            "lock acquisition cycle: " + " -> ".join(cyc),
            symbol="->".join(sorted(set(cyc))))


@rule("cross-module-lock")
def check_cross_module(ctx: Context) -> Iterable[Finding]:
    """A lock must not be acquired while holding a DIFFERENT module's
    lock unless the pair is explicitly allowed in the project map —
    cross-module nesting silently couples subsystems into one ordering
    domain and is how independent changes start deadlocking."""
    graph = _build(ctx)[1]
    allowed = set(ctx.project.allowed_cross_module)
    for (a, b), (rel, line, how) in sorted(graph.edges.items()):
        mod_a, mod_b = a.split(".", 1)[0], b.split(".", 1)[0]
        if mod_a == mod_b:
            continue
        if (mod_a, mod_b) in allowed or (a, b) in allowed:
            continue
        m = ctx.module(rel)
        if m is not None and m.suppressed("cross-module-lock", line):
            continue
        yield Finding(
            "cross-module-lock", rel, line,
            f"{b} acquired while holding {a} (via {how}); allow the "
            "pair in the project map or restructure",
            symbol=f"{a}->{b}")


def _is_mutation_call(node: ast.Call) -> bool:
    return isinstance(node.func, ast.Attribute) and \
        node.func.attr in astutil.MUTATOR_METHODS


def _expr_mentions(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


@rule("unlocked-shared-mutation")
def check_shared_state(ctx: Context) -> Iterable[Finding]:
    """Every registered shared container (the data plane's byte totals
    and LRU state, the supervisor's fault counters, the stage
    bookkeeping sets, the geometry caches, the logger cache) must only
    be mutated under its owning lock — unlocked read-modify-write on
    these is exactly the double-upload / lost-count class of race the
    PR-4 review caught by hand.

    ``__init__`` bodies and module-level initialization are exempt
    (the object is not shared yet)."""
    tables = _build(ctx)[0]
    for spec in ctx.project.shared_state:
        mod = ctx.module(spec.relpath)
        if mod is None:
            continue
        table = tables[mod.relpath]
        for node, desc in _mutations(mod, spec):
            fn = mod.enclosing_function(node)
            if fn is None:
                continue                      # module-level init
            if fn.name == "__init__":
                continue
            held = astutil.with_lock_ids(mod, table, node)
            if spec.lock in held:
                continue
            line = getattr(node, "lineno", 1)
            if mod.suppressed("unlocked-shared-mutation", line):
                continue
            yield Finding(
                "unlocked-shared-mutation", mod.relpath, line,
                f"{desc} mutated outside its owning lock {spec.lock}",
                symbol=f"{desc}@{mod.qualname(fn) or '<module>'}")


def _mutations(mod: ModuleInfo, spec):
    """(node, description) pairs mutating the spec's container."""

    def base_is_guarded(n: ast.AST) -> bool:
        if spec.name and isinstance(n, ast.Name) and n.id == spec.name:
            return True
        if spec.attrs and isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Name) and n.value.id == "self" \
                and n.attr in spec.attrs:
            klass = mod.enclosing_class(n)
            return klass is not None and klass.name == spec.cls
        return False

    # light per-function taint: names assigned from guarded expressions
    tainted: Dict[Tuple[str, str], bool] = {}
    if spec.taint_key or spec.name or spec.attrs:
        for fn in astutil.iter_functions(mod.tree):
            qn = mod.qualname(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                src_guarded = _expr_mentions(node.value, base_is_guarded)
                if spec.taint_key and _expr_mentions(
                        node.value,
                        lambda n: astutil.literal_str(n)
                        == spec.taint_key):
                    src_guarded = True
                if not src_guarded:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        tainted[(qn, tgt.id)] = True

    def is_guarded(n: ast.AST) -> bool:
        if base_is_guarded(n):
            return True
        if isinstance(n, ast.Name):
            fn = mod.enclosing_function(n)
            if fn is not None and tainted.get(
                    (mod.qualname(fn), n.id)):
                return True
        if spec.taint_key and isinstance(n, ast.Subscript) and \
                astutil.literal_str(n.slice) == spec.taint_key:
            return True
        return False

    def describe(n: ast.AST) -> str:
        return astutil.attr_chain(n) or spec.name or spec.taint_key \
            or "<shared>"

    for node in ast.walk(mod.tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            base = tgt
            while isinstance(base, ast.Subscript):
                base = base.value
            if is_guarded(base):
                if isinstance(tgt, ast.Name):
                    # rebinding a NAME is only a shared mutation for
                    # the registered global itself (e.g. `_PLANE =
                    # DataPlane()`), and only inside a function —
                    # module-level init is the definition, and
                    # rebinding a TAINTED local is just a new local
                    # binding, not a container write
                    if not (spec.name and tgt.id == spec.name):
                        continue
                    if mod.enclosing_function(node) is None:
                        continue
                yield node, describe(base)
        if isinstance(node, ast.Call) and _is_mutation_call(node):
            recv = node.func.value
            if is_guarded(recv):
                yield node, describe(recv)


@rule("unnamed-lock")
def check_unnamed_locks(ctx: Context) -> Iterable[Finding]:
    """Package code must create locks through the
    ``utils.locks.named_lock``/``named_rlock`` factories, never raw
    ``threading.Lock()``/``RLock()`` — unnamed locks are invisible to
    both the static acquisition graph and the SST_LOCKCHECK runtime
    recorder, so their ordering bugs go unchecked."""
    for mod in ctx.modules:
        if mod.relpath.endswith("utils/locks.py"):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and astutil.call_name(node) in (
                    "threading.Lock", "threading.RLock"):
                if mod.suppressed("unnamed-lock", node.lineno):
                    continue
                yield Finding(
                    "unnamed-lock", mod.relpath, node.lineno,
                    "raw threading lock; use utils.locks.named_lock / "
                    "named_rlock so sstlint and SST_LOCKCHECK can see "
                    "it",
                    symbol=f"{astutil.call_name(node)}"
                           f"@{mod.qualname(node) or '<module>'}")
