"""sstlint core — findings, the rule registry, suppressions, baseline.

A *rule* is a function ``fn(ctx) -> iterable[Finding]`` registered with
the :func:`rule` decorator; its docstring's first paragraph is the
rationale rendered into ``docs/API.md`` by ``dev/build_api_docs.py``.

A *finding* identifies itself by a stable ``key`` (rule + file +
symbol), NOT by line number, so baselines survive unrelated edits.
Findings can be silenced two ways:

  - a suppression comment ``# sstlint: disable=<rule>[,<rule>...]`` on
    the flagged line or on one of the three lines above it (so the
    justification comment block sits naturally above the construct);
  - a committed baseline file (``tools/sstlint/baseline.json``) of
    grandfathered keys, each carrying a human justification — the
    escape hatch for findings that are understood and deliberate.
    An empty baseline is the goal; ``--update-baseline`` rewrites it.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import tokenize
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Finding",
    "Rule",
    "RULES",
    "rule",
    "ModuleInfo",
    "Context",
    "load_baseline",
    "save_baseline",
]


@dataclasses.dataclass
class Finding:
    """One reported violation."""

    rule: str
    path: str              # repo-relative, forward slashes
    line: int
    message: str
    #: stable identity token within (rule, path): the lock name, span
    #: name, config field, function qualname... — line numbers are NOT
    #: part of a finding's identity
    symbol: str = ""

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.symbol or self.message}"

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "key": self.key}

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    """A registered checker."""

    name: str
    fn: Callable[["Context"], Iterable[Finding]]
    rationale: str


#: the registry `python -m tools.sstlint --list-rules` and the docs
#: build render; populated by the @rule decorator at import time.
RULES: "Dict[str, Rule]" = {}


def rule(name: str):
    """Register a checker under `name` (kebab-case).  The decorated
    function's docstring first paragraph becomes the rule's documented
    rationale."""

    def deco(fn):
        doc = (fn.__doc__ or "").strip()
        rationale = re.split(r"\n\s*\n", doc)[0].replace("\n", " ")
        rationale = re.sub(r"\s+", " ", rationale)
        if name in RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        RULES[name] = Rule(name, fn, rationale)
        return fn

    return deco


# ---------------------------------------------------------------------------
# Parsed-module model
# ---------------------------------------------------------------------------


_SUPPRESS_RE = re.compile(r"#\s*sstlint:\s*disable=([\w\-, ]+)")


class ModuleInfo:
    """One parsed source file plus lint-relevant derived data."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        #: short module name ("dataplane" for .../parallel/dataplane.py)
        self.short = Path(relpath).stem
        self._suppressions: Optional[Dict[int, set]] = None
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    # -- suppression comments -------------------------------------------
    @property
    def suppressions(self) -> Dict[int, set]:
        """lineno -> set of rule names disabled on that line (from
        ``# sstlint: disable=...`` comments, found via tokenize so
        string literals can never fake one)."""
        if self._suppressions is None:
            sup: Dict[int, set] = {}
            try:
                tokens = tokenize.generate_tokens(
                    iter(self.source.splitlines(True)).__next__)
                for tok in tokens:
                    if tok.type == tokenize.COMMENT:
                        m = _SUPPRESS_RE.search(tok.string)
                        if m:
                            rules = {r.strip() for r in
                                     m.group(1).split(",") if r.strip()}
                            sup.setdefault(tok.start[0], set()).update(
                                rules)
            except tokenize.TokenError:
                pass
            self._suppressions = sup
        return self._suppressions

    def suppressed(self, rule_name: str, line: int) -> bool:
        """Is `rule_name` disabled at `line`?  The comment may sit on
        the line itself or up to three lines above (a justification
        block)."""
        for ln in range(max(1, line - 3), line + 1):
            if rule_name in self.suppressions.get(ln, ()):
                return True
        return False

    # -- AST helpers -----------------------------------------------------
    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            p: Dict[ast.AST, ast.AST] = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    p[child] = node
            self._parents = p
        return self._parents

    def qualname(self, node: ast.AST) -> str:
        """Dotted def/class path enclosing `node` (inclusive)."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_class(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None


class Context:
    """Everything a rule sees: the project map, parsed modules, and
    the target paths."""

    def __init__(self, project, modules: List[ModuleInfo]):
        self.project = project
        self.modules = modules
        self.by_relpath = {m.relpath: m for m in modules}

    def module(self, relpath: str) -> Optional[ModuleInfo]:
        return self.by_relpath.get(relpath)


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: Path) -> Dict[str, Dict[str, Any]]:
    """key -> entry (with its justification).  Missing file = empty."""
    if not path or not Path(path).is_file():
        return {}
    data = json.loads(Path(path).read_text())
    out = {}
    for entry in data.get("findings", []):
        out[entry["key"]] = entry
    return out


def save_baseline(path: Path, findings: List[Finding],
                  old: Optional[Dict[str, Dict[str, Any]]] = None) -> None:
    """Write the baseline for `findings`, carrying forward any existing
    justifications and defaulting new entries to TODO markers that a
    reviewer is expected to replace."""
    old = old or {}
    entries = []
    for f in sorted(findings, key=lambda f: f.key):
        prev = old.get(f.key, {})
        entries.append({
            "key": f.key,
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
            "justification": prev.get(
                "justification", "TODO: justify or fix"),
        })
    payload = {
        "comment": (
            "Grandfathered sstlint findings.  Every entry needs a "
            "justification; an empty list is the goal.  Regenerate "
            "with: python -m tools.sstlint --update-baseline"),
        "findings": entries,
    }
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")
