"""Escape-hatch audit — every byte-parity claim names its pinning test.

The engine's README and docstrings make strong promises: fusion off
"reproduces the pre-fusion engine exactly", heartbeat off is an "exact
no-op", pipeline depth 0 is "bit-for-bit" the synchronous order.  A
parity claim without a parity test is marketing, and a claim whose
test was renamed away is worse — it *looks* pinned.  The project map
registers every such hatch (:class:`~tools.sstlint.project.EscapeHatch`)
with its ``tests/...::test_name`` pointer; these rules audit both
directions: claims without a registration, and registrations whose
knob or test no longer resolves.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from tools.sstlint.core import Context, Finding, rule

#: a documentation line makes a byte-parity claim when it uses the
#: project's parity vocabulary
_CLAIM_RE = re.compile(
    r"exact no-?op|byte-?identical|bit-?exact|bit-?for-?bit"
    r"|escape hatch", re.IGNORECASE)

#: backticked knob tokens on a claim line: `fusion`, `pipeline_depth=0`
_TOKEN_RE = re.compile(r"`([A-Za-z_][A-Za-z0-9_]*)(?:=[^`]*)?`")


def _config_field_names(ctx: Context) -> Optional[Set[str]]:
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name == "TpuConfig":
                return {n.target.id for n in node.body
                        if isinstance(n, ast.AnnAssign)
                        and isinstance(n.target, ast.Name)}
    return None


def _claim_lines(ctx: Context) -> List[Tuple[str, int, str]]:
    """(path, lineno, text) of every parity-claim line in the README
    and in module docstrings — the two places the engine documents its
    escape hatches."""
    out: List[Tuple[str, int, str]] = []
    readme = getattr(ctx.project, "readme", None)
    if readme and readme.is_file():
        for i, line in enumerate(
                readme.read_text().splitlines(), start=1):
            if _CLAIM_RE.search(line):
                out.append((readme.name, i, line))
    for mod in ctx.modules:
        doc_node = None
        if mod.tree.body and isinstance(mod.tree.body[0], ast.Expr) \
                and isinstance(mod.tree.body[0].value, ast.Constant) \
                and isinstance(mod.tree.body[0].value.value, str):
            doc_node = mod.tree.body[0].value
        if doc_node is None:
            continue
        for off, line in enumerate(doc_node.value.splitlines()):
            if _CLAIM_RE.search(line):
                out.append((mod.relpath, doc_node.lineno + off, line))
    return out


@rule("escape-hatch-unregistered")
def check_escape_hatch_claims(ctx: Context) -> Iterable[Finding]:
    """Every README/docstring line claiming a knob is an "exact
    no-op"/"byte-identical" escape hatch must name a knob registered in
    the project map's ``escape_hatches`` — a registration carries the
    parity-test pointer that makes the claim checkable, so an
    unregistered claim is a promise nothing pins."""
    fields = _config_field_names(ctx)
    if fields is None:
        return
    registered = {h.knob for h in
                  getattr(ctx.project, "escape_hatches", ())}
    for path, lineno, text in _claim_lines(ctx):
        mod = ctx.module(path)
        if mod is not None and mod.suppressed(
                "escape-hatch-unregistered", lineno):
            continue
        # only claim lines ANCHORED to a real config knob are audited;
        # prose about the general philosophy has no knob to register
        knobs = {t for t in _TOKEN_RE.findall(text) if t in fields}
        for knob in sorted(knobs - registered):
            yield Finding(
                "escape-hatch-unregistered", path, lineno,
                f"parity claim about `{knob}` is not registered in "
                "the project map's escape_hatches — register it with "
                "its pinning parity test",
                symbol=f"{knob}:{path}")


@rule("escape-hatch-untested")
def check_escape_hatch_tests(ctx: Context) -> Iterable[Finding]:
    """Every registered escape hatch must point at a parity test that
    still resolves (file exists, test function defined) and at a real
    ``TpuConfig`` knob — a dangling pointer means the byte-parity
    promise is no longer pinned by anything that runs."""
    hatches = getattr(ctx.project, "escape_hatches", ())
    if not hatches:
        return
    fields = _config_field_names(ctx)
    root = ctx.project.root
    for hatch in hatches:
        if fields is not None and hatch.knob not in fields:
            yield Finding(
                "escape-hatch-untested", "tools/sstlint/project.py", 1,
                f"escape hatch {hatch.name!r} registers knob "
                f"{hatch.knob!r}, which is not a TpuConfig field",
                symbol=f"{hatch.name}:knob")
            continue
        pointer = hatch.parity_test
        relfile, sep, test_name = pointer.partition("::")
        test_path = root / relfile
        if not sep or not test_name or not test_path.is_file():
            yield Finding(
                "escape-hatch-untested", relfile or pointer, 1,
                f"escape hatch {hatch.name!r} points at parity test "
                f"{pointer!r}, whose file does not resolve",
                symbol=f"{hatch.name}:file")
            continue
        if not re.search(
                rf"^\s*def {re.escape(test_name)}\(",
                test_path.read_text(), re.MULTILINE):
            yield Finding(
                "escape-hatch-untested", relfile, 1,
                f"escape hatch {hatch.name!r} points at parity test "
                f"{pointer!r}, but {relfile} defines no such test — "
                "the byte-parity claim is unpinned",
                symbol=f"{hatch.name}:test")
