"""Key-flow analysis — prove every traced input reaches its cache key.

The engine's bit-exactness contract rests on one invariant nobody
checked mechanically before these rules: *everything that influences a
traced program must join the key that caches it*.  The cache-key
surfaces are declared once, in ``utils/keycheck.py``'s
``KEY_SURFACES`` (loaded import-light here); each rule holds the code
to that registry from a different direction:

  - ``key-part-missing``: a declared key-feeding field absent from the
    surface's key expressions; a config read reachable from a traced
    closure that does not flow into the paired key; or a store-key
    identifier with no in-memory-key counterpart;
  - ``key-part-dead``: a ``config.*`` key part the registry does not
    declare — dead weight or an undocumented dependency, both worth a
    finding;
  - ``key-surface-unregistered``: registry hygiene (stale relpaths/
    anchors/fields) and cache-key construction sites outside any
    registered surface;
  - ``keycheck-note-missing``: every surface must report to the
    ``SST_KEYCHECK=1`` runtime recorder, or the static pass has no
    runtime twin to catch what it cannot see.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from tools.sstlint import astutil
from tools.sstlint.core import Context, Finding, ModuleInfo, rule

#: resolved-closure BFS bound: deep enough for grid's
#: build -> jit(fn) -> helper chains, finite under name cycles
_CLOSURE_DEPTH = 5


def _load_surfaces(ctx: Context) -> Optional[Dict[str, Dict[str, Any]]]:
    """The project's KEY_SURFACES registry, or None when the project
    declares no keycheck module (fixture trees opt in by path)."""
    path = getattr(ctx.project, "keycheck_path", None)
    if not path or not path.is_file():
        return None
    mod = astutil.load_module_by_path(path, "sstlint_keycheck_registry")
    surfaces = getattr(mod, "KEY_SURFACES", None)
    if not isinstance(surfaces, dict):
        return None
    return surfaces


def _config_field_names(ctx: Context) -> Optional[Set[str]]:
    """TpuConfig's field names, or None when no config class is in the
    tree (fixture packages without one skip the field-existence
    checks)."""
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name == "TpuConfig":
                return {n.target.id for n in node.body
                        if isinstance(n, ast.AnnAssign)
                        and isinstance(n.target, ast.Name)}
    return None


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _scope_of(mod: ModuleInfo, node: ast.AST) -> Optional[ast.AST]:
    """The function/lambda scope enclosing ``node`` (None = module)."""
    cur = mod.parents.get(node)
    while cur is not None and not isinstance(cur, _SCOPE_NODES):
        cur = mod.parents.get(cur)
    return cur


def _scope_chain(mod: ModuleInfo, node: ast.AST) -> List[Any]:
    """Scopes visible from ``node``, innermost first, module (None)
    last — names must resolve lexically or closures nested in
    different builders that reuse helper names (``one_task``,
    ``one_fold``) contaminate each other's dataflow."""
    chain: List[Any] = []
    s = node if isinstance(node, _SCOPE_NODES) else _scope_of(mod, node)
    while s is not None:
        chain.append(s)
        s = _scope_of(mod, s)
    chain.append(None)
    return chain


def _scope_index(mod: ModuleInfo) -> Tuple[Dict, Dict]:
    """Per-scope name bindings: function defs and single-target
    assignments (``score_batch = wide if all_cores else nested``),
    keyed by ``id(scope)`` (module scope is ``None``)."""
    defs: Dict[int, Dict[str, List[ast.AST]]] = {}
    aliases: Dict[int, Dict[str, ast.AST]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            s = _scope_of(mod, node)
            defs.setdefault(id(s), {}).setdefault(
                node.name, []).append(node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            s = _scope_of(mod, node)
            aliases.setdefault(id(s), {})[node.targets[0].id] = \
                node.value
    return defs, aliases


def _resolve(index: Tuple[Dict, Dict], name: str,
             chain: List[Any], depth: int = 0) -> List[ast.AST]:
    """The def(s) ``name`` can lexically refer to from ``chain`` —
    the innermost binding shadows outer ones; alias assignments
    resolve their referenced names from the binding scope outward."""
    if depth > 3:
        return []
    defs, aliases = index
    for i, s in enumerate(chain):
        bound_defs = defs.get(id(s), {}).get(name)
        alias = aliases.get(id(s), {}).get(name)
        if bound_defs is None and alias is None:
            continue
        out: List[ast.AST] = list(bound_defs or ())
        if alias is not None:
            for ref in ast.walk(alias):
                if isinstance(ref, ast.Name) and ref.id != name:
                    out.extend(_resolve(index, ref.id, chain[i:],
                                        depth + 1))
        return out
    return []


def _config_reads(node: ast.AST) -> Set[str]:
    """``config.<field>`` attribute reads inside ``node`` (the
    conventional config receiver name; fixture packages follow it)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Name) and \
                n.value.id == "config":
            out.add(n.attr)
    return out


def _closure_config_reads(mod: ModuleInfo, build: ast.AST,
                          index: Tuple[Dict, Dict]) -> Set[str]:
    """Config reads reachable from a build callable: resolve
    ``lambda: jax.jit(<fn>, ...)`` / bare function references to their
    defs (lexically, via :func:`_resolve`) and BFS same-module callees
    collecting ``config.*`` reads from bodies AND default arguments
    (where grid threads ``__bf16__`` into the traced statics).
    Unresolvable references — e.g. a ``fused_body`` pulled out of
    another program dict — are skipped: the runtime twin covers what
    static resolution cannot see."""
    frontier: List[Tuple[ast.AST, int]] = []
    if isinstance(build, ast.Lambda):
        frontier.append((build, 0))
    elif isinstance(build, ast.Name):
        for d in _resolve(index, build.id, _scope_chain(mod, build)):
            frontier.append((d, 0))
    reads: Set[str] = set()
    seen: Set[int] = set()
    while frontier:
        node, depth = frontier.pop()
        if id(node) in seen or depth > _CLOSURE_DEPTH:
            continue
        seen.add(id(node))
        reads |= _config_reads(node)
        chain = _scope_chain(mod, node)
        for ref in ast.walk(node):
            if isinstance(ref, ast.Name):
                for d in _resolve(index, ref.id, chain):
                    if id(d) not in seen:
                        frontier.append((d, depth + 1))
    return reads


def _names_in(node: ast.AST, skip_call_funcs: bool = True) -> Set[str]:
    """Identifier tokens of a key/store-parts expression: bare Names
    plus the base Name of attribute chains (``family.name`` counts as
    ``family``), excluding names used purely as call targets
    (``bool``, ``repr``, ...)."""
    func_heads: Set[int] = set()
    if skip_call_funcs:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                head = n.func
                while isinstance(head, ast.Attribute):
                    head = head.value
                func_heads.add(id(head))
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and id(n) not in func_heads:
            out.add(n.id)
    return out


def _is_none(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _cached_program_calls(mod: ModuleInfo) -> List[ast.Call]:
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = astutil.call_name(node) or ""
            if name.split(".")[-1] == "_cached_program":
                out.append(node)
    return out


def _call_kwarg(call: ast.Call, name: str,
                pos: Optional[int] = None) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


def _anchor_scopes(mod: ModuleInfo, surface: Dict[str, Any],
                   name: str) -> List[ast.AST]:
    """The AST regions a surface's key parts live in: for the
    program-cache surface, the key argument of every
    ``_cached_program`` call; otherwise the args of every call to the
    anchor plus the body of a same-named def (whichever exist)."""
    anchor = surface["anchor"]
    scopes: List[ast.AST] = []
    if name == "program_cache" or (surface.get("dataflow")
                                   and anchor == "_cached_program"):
        for call in _cached_program_calls(mod):
            if call.args:
                scopes.append(call.args[0])
        return scopes
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            cname = astutil.call_name(node) or ""
            if cname.split(".")[-1] == anchor:
                scopes.extend(node.args)
                scopes.extend(kw.value for kw in node.keywords)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == anchor:
            scopes.append(node)
    return scopes


def _field_present(scopes: List[ast.AST], field: str,
                   token: str) -> bool:
    """Does the declared field reach the key expressions — as a
    ``<x>.<field>`` attribute, its local carrier token, or a same-named
    keyword argument?"""
    for scope in scopes:
        for n in ast.walk(scope):
            if isinstance(n, ast.Attribute) and n.attr == field:
                return True
            if isinstance(n, ast.Name) and n.id == token:
                return True
            if isinstance(n, ast.keyword) and n.arg == field:
                return True
    return False


@rule("key-part-missing")
def check_key_part_missing(ctx: Context) -> Iterable[Finding]:
    """Every input that can alter a traced/cached artifact must flow
    into the key that caches it: a declared key-feeding field absent
    from its surface's key expressions, a ``config.*`` read reachable
    from a program-cache build closure that the paired key omits, or a
    store-key identifier with no in-memory-key counterpart is exactly
    the aliasing bug class PRs 15/17/19 each fixed by hand."""
    surfaces = _load_surfaces(ctx)
    if not surfaces:
        return
    for name, spec in surfaces.items():
        mod = ctx.module(spec["relpath"])
        if mod is None:
            continue            # key-surface-unregistered reports this
        tokens = spec.get("key_tokens", {})
        scopes = _anchor_scopes(mod, spec, name)
        if not scopes:
            continue
        anchor_line = scopes[0].lineno if hasattr(
            scopes[0], "lineno") else 1
        for field in spec.get("config_fields", ()):
            if _field_present(scopes, field, tokens.get(field, field)):
                continue
            if mod.suppressed("key-part-missing", anchor_line):
                continue
            yield Finding(
                "key-part-missing", mod.relpath, anchor_line,
                f"declared key-feeding field {field!r} of surface "
                f"{name!r} does not reach any key expression at its "
                f"anchor {spec['anchor']!r}",
                symbol=f"{name}:{field}")
        if not spec.get("dataflow"):
            continue
        index = _scope_index(mod)
        aliases = spec.get("aliases", {})
        for call in _cached_program_calls(mod):
            if not call.args:
                continue
            key_expr = call.args[0]
            key_names = _names_in(key_expr)
            key_attr_reads = _config_reads(key_expr)
            # (a) closure reads must be keyed
            build = call.args[1] if len(call.args) > 1 else None
            if build is not None:
                for field in sorted(
                        _closure_config_reads(mod, build, index)):
                    tok = tokens.get(field, field)
                    if field in key_attr_reads or tok in key_names:
                        continue
                    if mod.suppressed("key-part-missing", call.lineno):
                        continue
                    yield Finding(
                        "key-part-missing", mod.relpath, call.lineno,
                        f"config.{field} is read by the traced closure "
                        f"of the {name!r} call at line {call.lineno} "
                        "but does not flow into its cache key",
                        symbol=f"{name}:closure:{field}:"
                               f"{mod.qualname(call) or 'module'}")
            # (b) store-parts identifiers must have in-memory twins
            store_parts = _call_kwarg(call, "store_parts", pos=2)
            if store_parts is None or _is_none(store_parts):
                continue
            for ident in sorted(_names_in(store_parts)):
                twin = aliases.get(ident, ident)
                if ident in key_names or twin in key_names:
                    continue
                if mod.suppressed("key-part-missing", call.lineno):
                    continue
                yield Finding(
                    "key-part-missing", mod.relpath, call.lineno,
                    f"store key part {ident!r} of the {name!r} call at "
                    f"line {call.lineno} has no in-memory-key "
                    "counterpart — the persistent and in-memory keys "
                    "have drifted",
                    symbol=f"{name}:store:{ident}:"
                           f"{mod.qualname(call) or 'module'}")


@rule("key-part-dead")
def check_key_part_dead(ctx: Context) -> Iterable[Finding]:
    """Every ``config.*`` token inside a key expression must be
    declared in the surface's ``config_fields`` — the registry is the
    single source of truth, so an undeclared key part is either dead
    weight no traced path reads or a real dependency the declaration
    (and its docs/runtime-twin coverage) silently omits."""
    surfaces = _load_surfaces(ctx)
    if not surfaces:
        return
    for name, spec in surfaces.items():
        mod = ctx.module(spec["relpath"])
        if mod is None:
            continue
        declared = set(spec.get("config_fields", ()))
        for scope in _anchor_scopes(mod, spec, name):
            for field in sorted(_config_reads(scope)):
                if field in declared:
                    continue
                line = getattr(scope, "lineno", 1)
                if mod.suppressed("key-part-dead", line):
                    continue
                yield Finding(
                    "key-part-dead", mod.relpath, line,
                    f"config.{field} joins a key expression of surface "
                    f"{name!r} but is not declared in its "
                    "config_fields — declare it (documenting the "
                    "dependency) or drop the dead key part",
                    symbol=f"{name}:{field}")


@rule("key-surface-unregistered")
def check_key_surface_registry(ctx: Context) -> Iterable[Finding]:
    """The key-surface registry must match the tree: every registered
    surface's module and anchor must exist, every declared field must
    be a real ``TpuConfig`` field, and every ``_cached_program`` call
    site must live in a module some registered surface covers — a new
    cache-key construction site outside the registry would silently
    escape the whole key-flow analysis."""
    surfaces = _load_surfaces(ctx)
    if surfaces is None:
        return
    config_fields = _config_field_names(ctx)
    covered: Set[str] = set()
    for name, spec in surfaces.items():
        rel = spec["relpath"]
        covered.add(rel)
        mod = ctx.module(rel)
        if mod is None:
            yield Finding(
                "key-surface-unregistered", rel, 1,
                f"surface {name!r} is registered at {rel!r} but that "
                "module is not in the linted tree",
                symbol=f"{name}:relpath")
            continue
        anchor = spec["anchor"]
        present = any(
            (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and n.name == anchor)
            or (isinstance(n, ast.Call)
                and (astutil.call_name(n) or "").split(".")[-1]
                == anchor)
            for n in ast.walk(mod.tree))
        if not present:
            yield Finding(
                "key-surface-unregistered", rel, 1,
                f"surface {name!r} anchors on {anchor!r}, which {rel!r}"
                " neither defines nor calls — the registry is stale",
                symbol=f"{name}:anchor")
        if config_fields is not None:
            for field in spec.get("config_fields", ()):
                if field not in config_fields:
                    yield Finding(
                        "key-surface-unregistered", rel, 1,
                        f"surface {name!r} declares key-feeding field "
                        f"{field!r}, which is not a TpuConfig field",
                        symbol=f"{name}:field:{field}")
    for mod in ctx.modules:
        if mod.relpath in covered:
            continue
        for call in _cached_program_calls(mod):
            if mod.suppressed("key-surface-unregistered", call.lineno):
                continue
            yield Finding(
                "key-surface-unregistered", mod.relpath, call.lineno,
                f"_cached_program call at line {call.lineno} is not "
                "covered by any registered key surface — register the "
                "module in KEY_SURFACES so key-flow analysis sees it",
                symbol=f"callsite:{mod.qualname(call) or 'module'}:"
                       f"{call.lineno}")


@rule("keycheck-note-missing")
def check_keycheck_notes(ctx: Context) -> Iterable[Finding]:
    """Every registered key surface must call
    ``keycheck.note("<surface>", ...)`` in its module — the
    ``SST_KEYCHECK=1`` runtime recorder is the static pass's twin, and
    a surface that never reports gives the collision/coverage checks a
    blind spot exactly where the declared map claims coverage."""
    surfaces = _load_surfaces(ctx)
    if not surfaces:
        return
    for name, spec in surfaces.items():
        mod = ctx.module(spec["relpath"])
        if mod is None:
            continue
        noted = any(
            isinstance(n, ast.Call)
            and (astutil.call_name(n) or "").split(".")[-1] == "note"
            and n.args
            and astutil.literal_str(n.args[0]) == name
            for n in ast.walk(mod.tree))
        if noted:
            continue
        yield Finding(
            "keycheck-note-missing", spec["relpath"], 1,
            f"surface {name!r} never reports to the runtime key "
            f"recorder: add keycheck.note({name!r}, <key>, ...) at its "
            "key construction site",
            symbol=name)
