"""sstlint — project-native static analysis for spark_sklearn_tpu.

Checkers (see ``docs/API.md`` for the full rule catalog, rendered from
the rule docstrings by ``dev/build_api_docs.py``):

  - **lock order / races**: static acquisition graph over the named
    locks (cycles, cross-module nesting, shared-state mutation outside
    the owning lock), paired with the ``SST_LOCKCHECK=1`` runtime
    recorder in ``spark_sklearn_tpu/utils/locks.py``;
  - **exception hygiene**: bare/BaseException swallows, silent broad
    handlers, cause-less re-raises, taxonomy-dropping launch handlers;
  - **span & schema drift**: span names pinned to ``obs/spans.py``,
    ``search_report`` keys pinned to the ``*_BLOCK_SCHEMA`` constants,
    ``docs/API.md`` freshness;
  - **config-knob audit**: every ``TpuConfig`` field read + documented,
    every ``SST_*`` env var config-backed + in the README knob table;
  - **jit purity**: no clocks, host RNG, uploads, or in-place host
    mutation inside traced functions;
  - **key flow**: every traced input provably reaches its cache key
    (declared surfaces in ``utils/keycheck.py``; closure dataflow,
    store-vs-memory key consistency, dead key parts), paired with the
    ``SST_KEYCHECK=1`` runtime key recorder;
  - **journal formats**: every durable checkpoint/WAL record kind
    declared + versioned + decodable in ``utils/journalspec.py``;
  - **escape hatches**: every byte-parity claim registered with a
    resolving parity test;
  - **repo hygiene**: no committed bytecode, ``.gitignore`` coverage.

Usage::

    python -m tools.sstlint [--format json] [path]
    python -m tools.sstlint --list-rules
    python -m tools.sstlint --update-baseline

Findings are suppressed inline with ``# sstlint: disable=<rule>`` (on
the line or a justification comment up to three lines above) or
grandfathered in ``tools/sstlint/baseline.json`` with a written
justification.  Exit status: 0 = clean (baselined findings allowed),
1 = new findings, 2 = internal error.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from tools.sstlint.core import (  # noqa: F401  (public API re-exports)
    Context, Finding, ModuleInfo, RULES, load_baseline, rule,
    save_baseline)
from tools.sstlint.project import Project

# rule modules register themselves on import
from tools.sstlint import excepts as _excepts          # noqa: F401
from tools.sstlint import hatches as _hatches          # noqa: F401
from tools.sstlint import journalrules as _journal     # noqa: F401
from tools.sstlint import keyflow as _keyflow          # noqa: F401
from tools.sstlint import knobs as _knobs              # noqa: F401
from tools.sstlint import lockorder as _lockorder      # noqa: F401
from tools.sstlint import purity as _purity            # noqa: F401
from tools.sstlint import spanrules as _spanrules      # noqa: F401

__all__ = ["Project", "RULES", "catalog_markdown", "collect_modules",
           "run_lint"]

DEFAULT_BASELINE = Path(__file__).parent / "baseline.json"


def catalog_markdown() -> str:
    """The rule-catalog table ``dev/build_api_docs.py`` renders into
    ``docs/API.md`` — defined here, next to the registry, so the
    ``docs-stale`` rule can hold the docs to the same definitions the
    gate runs."""
    out = [
        "## `tools.sstlint` rule catalog\n",
        "\nProject-native static analysis (`python -m tools.sstlint`),"
        " run as a tier-1 gate by `dev/run-tests.sh`.  Rendered from "
        "the rule registry docstrings.  Suppress inline with "
        "`# sstlint: disable=<rule>`; grandfather with a justified "
        "entry in `tools/sstlint/baseline.json`.\n",
        "\n| rule | rationale |\n|---|---|\n",
    ]
    for name in sorted(RULES):
        out.append(f"| `{name}` | {RULES[name].rationale} |\n")
    return "".join(out)


def collect_modules(project: Project) -> List[ModuleInfo]:
    mods: List[ModuleInfo] = []
    for path in sorted(project.package.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel_pkg = str(path.relative_to(project.package)).replace(
            "\\", "/")
        if rel_pkg in project.exclude:
            continue
        rel_repo = str(path.resolve().relative_to(project.root)
                       ).replace("\\", "/")
        try:
            mods.append(ModuleInfo(path, rel_pkg, path.read_text()))
        except (SyntaxError, UnicodeDecodeError) as exc:
            raise SystemExit(
                f"sstlint: cannot parse {rel_repo}: {exc}") from exc
    return mods


def run_lint(project: Optional[Project] = None,
             rules: Optional[Sequence[str]] = None,
             baseline_path: Optional[Path] = None,
             root: Optional[Path] = None) -> Dict[str, Any]:
    """Run the suite; returns the machine-readable result dict the
    CLI serializes with ``--format json``."""
    t0 = time.perf_counter()
    if project is None:
        project = Project.default(root or Path.cwd())
    ctx = Context(project, collect_modules(project))
    selected = list(rules) if rules else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise SystemExit(f"sstlint: unknown rule(s): {unknown}")
    findings: List[Finding] = []
    for name in selected:
        findings.extend(RULES[name].fn(ctx) or ())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    bpath = baseline_path if baseline_path is not None else \
        DEFAULT_BASELINE
    baseline = load_baseline(bpath)
    new = [f for f in findings if f.key not in baseline]
    grandfathered = [f for f in findings if f.key in baseline]
    return {
        "n_rules": len(selected),
        "rules": selected,
        "n_findings": len(new),
        "n_baselined": len(grandfathered),
        "findings": [f.to_dict() for f in new],
        "baselined": [f.to_dict() for f in grandfathered],
        "duration_s": round(time.perf_counter() - t0, 3),
        "_finding_objs": findings,
        "_baseline": baseline,
        "_baseline_path": str(bpath),
    }
