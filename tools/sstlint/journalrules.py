"""Journal-format rules — every durable record kind is declared once.

The checkpoint journal and the service WAL are formats a dead process
leaves behind for a future one, so an undeclared record kind is a
resume-time surprise waiting in a file nobody can re-run.  The
vocabulary lives in ``utils/journalspec.py`` (loaded import-light
here); these rules hold the write sites to it in both directions:

  - ``journal-format``: a ``put_meta``/``ServiceJournal.append`` call
    whose kind literal the registry does not declare;
  - ``journal-decoder-missing``: a declared kind without a versioned
    back-compat decoder, or a declared kind no write site produces
    (dead registry entries rot into wrong documentation).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tools.sstlint import astutil
from tools.sstlint.core import Context, Finding, ModuleInfo, rule


def _load_spec(ctx: Context):
    path = getattr(ctx.project, "journalspec_path", None)
    if not path or not path.is_file():
        return None
    return astutil.load_module_by_path(path, "sstlint_journalspec")


def _kind_literal(arg: ast.AST) -> Tuple[Optional[str], bool]:
    """The statically-known record kind of a write call's first arg:
    ``(kind, is_prefix)``.  A plain literal is exact; an f-string
    contributes its leading constant prefix (``f"prefix:{fp}"`` ->
    ``("prefix:", True)``); anything else is unresolvable ``(None,
    False)`` — runtime validation covers dynamic kinds."""
    lit = astutil.literal_str(arg)
    if lit is not None:
        return lit, False
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        if isinstance(head, ast.Constant) and \
                isinstance(head.value, str) and head.value:
            return head.value, True
    return None, False


def _meta_write_calls(
        mod: ModuleInfo) -> List[Tuple[ast.Call, str, bool]]:
    """Every ``put_meta(kind, ...)`` call with a resolvable kind."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = (astutil.call_name(node) or "").split(".")[-1]
        if name != "put_meta" or not node.args:
            continue
        kind, is_prefix = _kind_literal(node.args[0])
        if kind is not None:
            out.append((node, kind, is_prefix))
    return out


def _service_append_calls(
        mod: ModuleInfo) -> List[Tuple[ast.Call, str]]:
    """Every two-argument ``<journal>.append("<kind>", record)`` call —
    the arity plus the literal first argument distinguish the WAL's
    append from ``list.append``."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = (astutil.call_name(node) or "").split(".")[-1]
        if name != "append" or len(node.args) != 2:
            continue
        kind = astutil.literal_str(node.args[0])
        if kind is not None:
            out.append((node, kind))
    return out


def _meta_declared(spec, kind: str, is_prefix: bool) -> bool:
    kinds: Dict[str, Dict[str, Any]] = spec.CHECKPOINT_META_KINDS
    entry = kinds.get(kind)
    if entry is not None:
        # an f-string head that exactly names a non-prefix kind still
        # produces dynamic variants the registry does not declare
        return entry["prefix_match"] or not is_prefix
    return any(s["prefix_match"] and kind.startswith(k)
               for k, s in kinds.items())


@rule("journal-format")
def check_journal_format(ctx: Context) -> Iterable[Finding]:
    """Every checkpoint ``put_meta`` kind and every service-WAL
    ``append`` kind must be declared in ``utils/journalspec.py`` — an
    undeclared kind is a durable record with no owner, no version and
    no decoder, i.e. format drift that surfaces as a resume-time
    surprise instead of a lint finding."""
    spec = _load_spec(ctx)
    if spec is None:
        return
    for mod in ctx.modules:
        for call, kind, is_prefix in _meta_write_calls(mod):
            if _meta_declared(spec, kind, is_prefix):
                continue
            if mod.suppressed("journal-format", call.lineno):
                continue
            shown = f"{kind}<...>" if is_prefix else kind
            yield Finding(
                "journal-format", mod.relpath, call.lineno,
                f"put_meta kind {shown!r} is not declared in "
                "CHECKPOINT_META_KINDS (utils/journalspec.py) — add a "
                "versioned entry with a back-compat decoder",
                symbol=f"meta:{kind}")
        for call, kind in _service_append_calls(mod):
            if kind in spec.SERVICE_RECORD_KINDS:
                continue
            if mod.suppressed("journal-format", call.lineno):
                continue
            yield Finding(
                "journal-format", mod.relpath, call.lineno,
                f"service-journal record kind {kind!r} is not declared "
                "in SERVICE_RECORD_KINDS (utils/journalspec.py)",
                symbol=f"service:{kind}")


@rule("journal-decoder-missing")
def check_journal_decoders(ctx: Context) -> Iterable[Finding]:
    """Every declared journal record kind needs an int format version
    and a callable back-compat decoder — and a write site that actually
    produces it: a version-less kind cannot evolve safely, and a dead
    registry entry documents a record no journal contains."""
    spec = _load_spec(ctx)
    if spec is None:
        return
    rel = "utils/journalspec.py"
    tables = (
        ("CHECKPOINT_RECORD_KINDS", spec.CHECKPOINT_RECORD_KINDS),
        ("CHECKPOINT_META_KINDS", spec.CHECKPOINT_META_KINDS),
        ("SERVICE_RECORD_KINDS", spec.SERVICE_RECORD_KINDS),
    )
    for table_name, table in tables:
        for kind, entry in table.items():
            if not isinstance(entry.get("version"), int):
                yield Finding(
                    "journal-decoder-missing", rel, 1,
                    f"{table_name}[{kind!r}] has no int format "
                    "version",
                    symbol=f"{table_name}:{kind}:version")
            if not callable(entry.get("decode")):
                yield Finding(
                    "journal-decoder-missing", rel, 1,
                    f"{table_name}[{kind!r}] has no callable "
                    "back-compat decoder",
                    symbol=f"{table_name}:{kind}:decode")
    meta_written: List[Tuple[str, bool]] = []
    service_written: List[str] = []
    for mod in ctx.modules:
        meta_written.extend(
            (k, p) for _, k, p in _meta_write_calls(mod))
        service_written.extend(k for _, k in _service_append_calls(mod))
    for kind, entry in spec.CHECKPOINT_META_KINDS.items():
        if entry["prefix_match"]:
            produced = any(w.startswith(kind) for w, _ in meta_written)
        else:
            produced = any(w == kind and not p
                           for w, p in meta_written)
        if not produced:
            yield Finding(
                "journal-decoder-missing", rel, 1,
                f"declared meta kind {kind!r} has no put_meta write "
                "site in the tree — dead registry entry",
                symbol=f"meta-dead:{kind}")
    for kind in spec.SERVICE_RECORD_KINDS:
        if kind not in service_written:
            yield Finding(
                "journal-decoder-missing", rel, 1,
                f"declared service record kind {kind!r} has no append "
                "write site in the tree — dead registry entry",
                symbol=f"service-dead:{kind}")
