"""Shared AST plumbing for the sstlint checkers."""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from tools.sstlint.core import ModuleInfo

__all__ = [
    "attr_chain",
    "call_name",
    "dict_literal_keys_in",
    "import_aliases",
    "iter_functions",
    "literal_str",
    "mutator_methods",
    "subscript_store_keys",
    "with_lock_ids",
]

#: container methods that mutate their receiver
MUTATOR_METHODS = frozenset({
    "add", "append", "clear", "discard", "extend", "insert",
    "move_to_end", "pop", "popitem", "remove", "setdefault", "update",
})


def mutator_methods() -> frozenset:
    return MUTATOR_METHODS


def literal_str(node: ast.AST) -> Optional[str]:
    """The value of a string Constant, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name of a Name/Attribute chain ("jax.jit",
    "self._tracer.span"), or None for anything fancier."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return attr_chain(call.func)


def iter_functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def import_aliases(mod: ModuleInfo, package_name: str) -> Dict[str, str]:
    """Local name -> project-module relpath, for imports of project
    modules (``from pkg.parallel import dataplane as _dataplane`` maps
    ``_dataplane`` to ``parallel/dataplane.py``)."""
    out: Dict[str, str] = {}
    prefix = package_name + "."
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith(prefix):
                    rel = a.name[len(prefix):].replace(".", "/") + ".py"
                    out[a.asname or a.name.split(".")[0]] = rel
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module == package_name or \
                    node.module.startswith(prefix):
                base = node.module[len(package_name):].lstrip(".")
                for a in node.names:
                    cand = (base + "/" if base else "") + a.name
                    rel = cand.replace(".", "/") + ".py"
                    out[a.asname or a.name] = rel
    return out


# ---------------------------------------------------------------------------
# Named-lock discovery and resolution
# ---------------------------------------------------------------------------


class LockTable:
    """Lock aliases of one module, built from the
    ``named_lock``/``named_rlock`` factory calls."""

    def __init__(self):
        #: module-global var name -> lock id
        self.module: Dict[str, str] = {}
        #: (class name, attr) -> lock id, for self.<attr>
        self.cls: Dict[Tuple[str, str], str] = {}
        #: (enclosing function qualname, var) -> lock id
        self.local: Dict[Tuple[str, str], str] = {}

    @classmethod
    def build(cls, mod: ModuleInfo) -> "LockTable":
        table = cls()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if not (isinstance(v, ast.Call) and call_name(v) in (
                    "named_lock", "named_rlock",
                    "locks.named_lock", "locks.named_rlock",
                    "_locks.named_lock", "_locks.named_rlock")):
                continue
            if not v.args:
                continue
            lock_id = literal_str(v.args[0])
            if lock_id is None:
                continue
            fn = mod.enclosing_function(node)
            klass = mod.enclosing_class(node)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    if fn is None:
                        table.module[tgt.id] = lock_id
                    else:
                        table.local[(mod.qualname(fn), tgt.id)] = lock_id
                elif isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and klass is not None:
                    table.cls[(klass.name, tgt.attr)] = lock_id
        return table

    def resolve(self, mod: ModuleInfo, expr: ast.AST) -> Optional[str]:
        """Lock id of `expr` (a with-item / receiver), or None."""
        if isinstance(expr, ast.Name):
            fn = mod.enclosing_function(expr)
            qn = mod.qualname(fn) if fn is not None else ""
            # walk outward through enclosing function scopes
            while True:
                hit = self.local.get((qn, expr.id))
                if hit is not None:
                    return hit
                if "." not in qn:
                    break
                qn = qn.rsplit(".", 1)[0]
            return self.module.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            klass = mod.enclosing_class(expr)
            if klass is not None:
                return self.cls.get((klass.name, expr.attr))
        return None


def with_lock_ids(mod: ModuleInfo, table: LockTable,
                  node: ast.AST) -> List[str]:
    """Lock ids held at `node` by lexically-enclosing ``with``
    statements (innermost last)."""
    chain: List[str] = []
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            # frame boundary: a `with` outside this def is NOT held
            # when the def's body eventually runs
            break
        if isinstance(cur, ast.With):
            for item in cur.items:
                lock = table.resolve(mod, item.context_expr)
                if lock is not None:
                    chain.append(lock)
        cur = mod.parents.get(cur)
    chain.reverse()
    return chain


# ---------------------------------------------------------------------------
# Report-block key extraction
# ---------------------------------------------------------------------------


def dict_literal_keys_in(mod: ModuleInfo, qualname: str) -> Set[str]:
    """Every string key of every dict literal (and every literal
    ``.update({...})`` argument) inside the function `qualname`."""
    keys: Set[str] = set()
    for fn in iter_functions(mod.tree):
        if mod.qualname(fn) != qualname:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    s = literal_str(k) if k is not None else None
                    if s is not None:
                        keys.add(s)
    return keys


def subscript_store_keys(mod: ModuleInfo, var: str) -> Set[str]:
    """Every literal key K stored via ``<var>["K"] = ...`` (or
    augmented-assigned) anywhere in the module."""
    keys: Set[str] = set()
    for node in ast.walk(mod.tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Subscript) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == var:
                s = literal_str(tgt.slice)
                if s is not None:
                    keys.add(s)
    return keys


def load_module_by_path(path: Path, alias: str):
    """Import an import-light module directly by file path (no package
    __init__ chain — digesting schemas must never pay the jax
    import)."""
    import importlib.util
    import sys

    cached = sys.modules.get(alias)
    if cached is not None and getattr(
            cached, "__file__", None) == str(path):
        return cached
    spec = importlib.util.spec_from_file_location(alias, str(path))
    module = importlib.util.module_from_spec(spec)
    # register BEFORE exec: dataclass machinery looks itself up in
    # sys.modules while the module body runs
    sys.modules[alias] = module
    try:
        spec.loader.exec_module(module)
    except BaseException:
        sys.modules.pop(alias, None)
        raise
    return module
