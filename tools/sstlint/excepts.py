"""Exception-hygiene checkers.

PR 3's review caught a ``_dispatch`` guard that would have traded a
``KeyboardInterrupt`` for a silent whole-grid host re-run; these rules
make that class of bug mechanical:

  - handlers broad enough to catch ``KeyboardInterrupt``/``SystemExit``
    (bare ``except:`` / ``except BaseException``) must re-raise;
  - broad handlers must not swallow silently (no re-raise, no use of
    the exception, no logging) — a fallback is fine, an invisible one
    is not;
  - a new exception raised inside a handler must chain its cause
    (``raise X(...) from exc``) so ``LaunchTimeoutError``-style
    failures keep the original context;
  - on the launch path, broad handlers must stay taxonomy-aware
    (``classify_error``/``is_oom``/the supervisor's recovery funnel) —
    dropping the taxonomy turns a recoverable OOM into a dead search.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.sstlint import astutil
from tools.sstlint.core import Context, Finding, ModuleInfo, rule

#: calls that make a broad handler "visible" (the failure is recorded
#: somewhere a human or the fault journal can see)
_VISIBILITY_CALLS = frozenset({
    "warn", "warning", "debug", "info", "error", "exception", "print",
})

#: calls that make a launch-path handler taxonomy-aware
_TAXONOMY_CALLS = frozenset({
    "classify_error", "is_oom", "_recover", "_recover_oom",
    "_retry_gate", "register_classifier",
})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    """Catches Exception or wider?"""
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [astutil.attr_chain(e) or "" for e in t.elts]
    else:
        names = [astutil.attr_chain(t) or ""]
    return any(n.split(".")[-1] in ("Exception", "BaseException")
               for n in names)


def _catches_base(handler: ast.ExceptHandler) -> bool:
    """Catches KeyboardInterrupt/SystemExit (bare or BaseException)?"""
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [astutil.attr_chain(e) or "" for e in t.elts]
    else:
        names = [astutil.attr_chain(t) or ""]
    return any(n.split(".")[-1] == "BaseException" for n in names)


def _has_raise(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _uses_exc(handler: ast.ExceptHandler) -> bool:
    if handler.name is None:
        return False
    return any(isinstance(n, ast.Name) and n.id == handler.name
               for stmt in handler.body for n in ast.walk(stmt))


def _calls_any(node: ast.AST, names: frozenset) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            chain = astutil.call_name(n)
            if chain and chain.split(".")[-1] in names:
                return True
    return False


def _handlers(mod: ModuleInfo):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Try):
            for h in node.handlers:
                yield h


@rule("bare-except")
def check_bare_except(ctx: Context) -> Iterable[Finding]:
    """``except:`` catches ``KeyboardInterrupt`` and ``SystemExit`` —
    an interactive abort or interpreter shutdown silently becomes
    whatever the handler does.  Name the exceptions, or catch
    ``Exception``."""
    for mod in ctx.modules:
        for h in _handlers(mod):
            if h.type is not None:
                continue
            if mod.suppressed("bare-except", h.lineno):
                continue
            yield Finding(
                "bare-except", mod.relpath, h.lineno,
                "bare `except:` swallows KeyboardInterrupt/SystemExit; "
                "catch Exception (or narrower)",
                symbol=f"{mod.qualname(h) or '<module>'}")


@rule("broad-except-swallow")
def check_broad_swallow(ctx: Context) -> Iterable[Finding]:
    """``except BaseException`` (or bare) without a re-raise swallows
    ``KeyboardInterrupt``/``SystemExit`` — the PR-3 ``_dispatch`` bug
    class.  Handlers that wide must re-raise (directly, or by
    marshalling the exception to a caller that does)."""
    for mod in ctx.modules:
        for h in _handlers(mod):
            if not _catches_base(h):
                continue
            if _has_raise(h):
                continue
            if mod.suppressed("broad-except-swallow", h.lineno):
                continue
            yield Finding(
                "broad-except-swallow", mod.relpath, h.lineno,
                "handler catches BaseException but never re-raises — "
                "KeyboardInterrupt/SystemExit die here",
                symbol=f"{mod.qualname(h) or '<module>'}")


@rule("swallowed-exception")
def check_swallowed(ctx: Context) -> Iterable[Finding]:
    """A broad ``except Exception`` handler that neither re-raises,
    nor uses the caught exception, nor logs/warns, makes failures
    invisible — fallbacks are fine, silent ones hide real bugs (and
    can eat a ``LaunchTimeoutError`` meant to fail the search)."""
    for mod in ctx.modules:
        for h in _handlers(mod):
            if not _is_broad(h):
                continue
            if _has_raise(h) or _uses_exc(h):
                continue
            if _calls_any(h, _VISIBILITY_CALLS):
                continue
            if mod.suppressed("swallowed-exception", h.lineno):
                continue
            yield Finding(
                "swallowed-exception", mod.relpath, h.lineno,
                "broad handler swallows the exception with no re-raise,"
                " no use, and no log/warn — make the failure visible "
                "or narrow the except",
                symbol=f"{mod.qualname(h) or '<module>'}")


@rule("raise-without-cause")
def check_raise_cause(ctx: Context) -> Iterable[Finding]:
    """Raising a NEW exception inside an ``except E as exc`` handler
    without ``from exc`` discards the original traceback — recovery
    errors (LaunchTimeoutError, GeometryMismatchError) must keep the
    failure they translate."""
    for mod in ctx.modules:
        for h in _handlers(mod):
            if h.name is None:
                continue
            for node in ast.walk(h):
                if not isinstance(node, ast.Raise):
                    continue
                if node.exc is None:          # bare re-raise
                    continue
                if isinstance(node.exc, ast.Name):   # raise exc
                    continue
                if node.cause is not None:
                    continue
                # `raise X(...)` with no cause — unless X is the bound
                # exception passed through a call like raise exc.with_…
                if mod.suppressed("raise-without-cause", node.lineno):
                    continue
                yield Finding(
                    "raise-without-cause", mod.relpath, node.lineno,
                    "new exception raised in a handler without "
                    "`from " + h.name + "` — the original cause is "
                    "lost",
                    symbol=f"{mod.qualname(node) or '<module>'}")


@rule("launch-except-taxonomy")
def check_launch_taxonomy(ctx: Context) -> Iterable[Finding]:
    """On the launch path (the fault supervisor, the chunk pipeline,
    and grid.py's launch closures) a broad handler must re-raise or
    stay taxonomy-aware (``classify_error``/``is_oom``/the recovery
    funnel) — handling a device error without classifying it turns a
    retryable TRANSIENT or a bisectable OOM into a dead search."""
    scoped_mods = set()
    scoped_fns = {}
    for entry in ctx.project.launch_paths:
        if "::" in entry:
            rel, fn = entry.split("::", 1)
            scoped_fns.setdefault(rel, set()).add(fn)
        else:
            scoped_mods.add(entry)
    for mod in ctx.modules:
        whole = mod.relpath in scoped_mods
        fns = scoped_fns.get(mod.relpath, set())
        if not whole and not fns:
            continue
        for h in _handlers(mod):
            if not _is_broad(h):
                continue
            encl = mod.enclosing_function(h)
            if not whole:
                if encl is None or not (
                        {encl.name} | set(
                            mod.qualname(encl).split("."))) & fns:
                    continue
            if _has_raise(h):
                continue
            if _calls_any(h, _TAXONOMY_CALLS):
                continue
            if encl is not None and _calls_any(encl, _TAXONOMY_CALLS):
                continue     # the enclosing loop/function classifies
            if mod.suppressed("launch-except-taxonomy", h.lineno):
                continue
            yield Finding(
                "launch-except-taxonomy", mod.relpath, h.lineno,
                "broad handler on the launch path neither re-raises "
                "nor consults the fault taxonomy (classify_error / "
                "is_oom / supervisor recovery)",
                symbol=f"{mod.qualname(h) or '<module>'}")
