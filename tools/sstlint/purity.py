"""jit-purity checkers.

Functions handed to ``jax.jit`` (or AOT-compiled via
``precompile``/``submit_precompile``) trace ONCE and replay as XLA
programs: host-side effects inside them either burn at trace time only
(wall-clock reads, RNG draws — silently constant thereafter) or
corrupt the engine's accounting (a ``device_put`` inside a traced
function bypasses the data plane's byte counters and cache).  These
rules walk every jitted function (plus one hop into local helpers it
calls) and flag the host effects.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from tools.sstlint import astutil
from tools.sstlint.core import Context, Finding, ModuleInfo, rule


def _unwrap_transform(node: ast.AST) -> ast.AST:
    """Strip jax.vmap/pmap/grad wrappers: jax.jit(jax.vmap(f)) targets
    f."""
    while isinstance(node, ast.Call):
        chain = astutil.call_name(node) or ""
        if chain.split(".")[-1] in ("vmap", "pmap", "grad",
                                    "value_and_grad") and node.args:
            node = node.args[0]
        else:
            break
    return node


def _jit_targets(mod: ModuleInfo):
    """(node, kind) for every function object handed to jax.jit:
    lambdas, local function names, and decorated defs."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            chain = astutil.call_name(node) or ""
            is_jit = chain in ("jax.jit", "jit") or \
                chain.endswith(".jit")
            if is_jit and node.args:
                yield _unwrap_transform(node.args[0]), node.lineno
            # functools.partial(jax.jit, ...) used as decorator is
            # handled below via the decorator list
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                chain = None
                if isinstance(dec, ast.Call):
                    chain = astutil.call_name(dec)
                    if chain in ("partial", "functools.partial") and \
                            dec.args:
                        chain = astutil.attr_chain(dec.args[0])
                else:
                    chain = astutil.attr_chain(dec)
                if chain in ("jax.jit", "jit") or \
                        (chain or "").endswith(".jit"):
                    yield node, node.lineno


def _local_defs(mod: ModuleInfo) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}
    for fn in astutil.iter_functions(mod.tree):
        out.setdefault(fn.name, []).append(fn)
    return out


def _bound_names(fn: ast.AST) -> Set[str]:
    """Parameters + names assigned inside `fn` — everything else is a
    closure/global capture."""
    bound: Set[str] = set()
    if isinstance(fn, ast.Lambda):
        args = fn.args
    elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = fn.args
    else:
        return bound
    for a in list(args.args) + list(args.posonlyargs) \
            + list(args.kwonlyargs):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Store):
            bound.add(node.id)
    return bound


def _walk_jitted(mod: ModuleInfo, target: ast.AST,
                 defs: Dict[str, List[ast.AST]]):
    """The target function plus one hop into local helpers it calls
    by bare name."""
    seen: List[ast.AST] = []
    if isinstance(target, ast.Name):
        seen.extend(defs.get(target.id, ()))
    elif isinstance(target, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
        seen.append(target)
    hop: List[ast.AST] = []
    for fn in seen:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name):
                for helper in defs.get(node.func.id, ()):
                    if helper not in seen and helper not in hop:
                        hop.append(helper)
    return seen + hop


def _findings_in(mod: ModuleInfo, fn: ast.AST, jit_line: int):
    bound = _bound_names(fn)
    label = getattr(fn, "name", "<lambda>")
    for node in ast.walk(fn):
        chain = None
        if isinstance(node, (ast.Attribute, ast.Name)):
            chain = astutil.attr_chain(node)
        if chain:
            root = chain.split(".")[0]
            if root == "time" and root not in bound and \
                    isinstance(node, ast.Attribute):
                yield ("jit-impure-time", node.lineno,
                       f"wall-clock read ({chain}) inside jitted "
                       f"{label!r}: evaluated once at trace time, "
                       "constant forever after", label)
            if (chain.startswith("random.")
                    or ".random." in chain
                    or chain.endswith(".random")) and \
                    root not in bound and \
                    root in ("random", "np", "numpy"):
                yield ("jit-impure-random", node.lineno,
                       f"host RNG ({chain}) inside jitted {label!r}: "
                       "draws at trace time only; thread jax.random "
                       "keys instead", label)
        if isinstance(node, ast.Call):
            cchain = astutil.call_name(node) or ""
            tail = cchain.split(".")[-1]
            if tail == "device_put" or (
                    tail == "upload" and (
                        "dataplane" in cchain or cchain.startswith(
                            "_dataplane"))):
                yield ("jit-unplaned-upload", node.lineno,
                       f"{cchain} inside jitted {label!r}: transfers "
                       "must go through the data plane OUTSIDE traced "
                       "code (the plane is the only sanctioned upload "
                       "point)", label)
        # host-side in-place mutation of a captured array
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Subscript) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id not in bound:
                yield ("jit-host-mutation", node.lineno,
                       f"in-place subscript write to captured "
                       f"{tgt.value.id!r} inside jitted {label!r}: "
                       "traced code must be functional (use .at[].set)",
                       label)


_PURITY_RULES = ("jit-impure-time", "jit-impure-random",
                 "jit-unplaned-upload", "jit-host-mutation")


def _run_purity(ctx: Context, only_rule: str):
    for mod in ctx.modules:
        defs = _local_defs(mod)
        reported = set()
        for target, jit_line in _jit_targets(mod):
            for fn in _walk_jitted(mod, target, defs):
                for rname, line, msg, label in _findings_in(
                        mod, fn, jit_line):
                    if rname != only_rule:
                        continue
                    key = (rname, mod.relpath, line)
                    if key in reported:
                        continue
                    reported.add(key)
                    if mod.suppressed(rname, line):
                        continue
                    yield Finding(
                        rname, mod.relpath, line, msg,
                        symbol=f"{label}"
                               f"@{mod.qualname(fn) or '<module>'}")


@rule("jit-impure-time")
def check_jit_time(ctx: Context) -> Iterable[Finding]:
    """No ``time.*`` reads inside functions traced by ``jax.jit`` —
    the clock is read once at trace time and baked into the program as
    a constant."""
    return _run_purity(ctx, "jit-impure-time")


@rule("jit-impure-random")
def check_jit_random(ctx: Context) -> Iterable[Finding]:
    """No Python/NumPy RNG inside jitted functions — draws happen at
    trace time only; randomness must thread explicit ``jax.random``
    keys."""
    return _run_purity(ctx, "jit-impure-random")


@rule("jit-unplaned-upload")
def check_jit_upload(ctx: Context) -> Iterable[Finding]:
    """No ``device_put``/``dataplane.upload`` inside jitted functions
    — the data plane outside traced code is the only sanctioned
    host->device upload point (byte accounting and the broadcast cache
    both depend on it)."""
    return _run_purity(ctx, "jit-unplaned-upload")


@rule("jit-host-mutation")
def check_jit_mutation(ctx: Context) -> Iterable[Finding]:
    """No in-place writes to captured host arrays inside jitted
    functions — traced code must stay functional (``.at[].set`` is the
    jax spelling)."""
    return _run_purity(ctx, "jit-host-mutation")
