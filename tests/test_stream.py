"""Streaming-fold data plane (ISSUE PR 15 tentpole a).

The acceptance contract, pinned end to end:

  - a streamed search's `cv_results_` is BIT-EXACT against the in-core
    device path at pipeline depth 0 AND depth 2 (integer-statistics
    families; zero-row padding adds exactly nothing);
  - shard width is an analytic planning decision: a tiny HBM budget
    yields a capped >=3-shard plan and the search completes with ZERO
    OOM bisections;
  - a search killed mid-shard resumes from the per-shard accumulator
    journal and still matches bit-exactly;
  - resuming under a different shard geometry fails loudly
    (GeometryMismatchError), never silently mis-addresses journal
    entries.

Every search here runs `backend="tpu"` so a compiled-path failure
raises instead of silently re-running on the (f64, NOT bit-exact)
host tier."""

import os
import warnings

import numpy as np
import pytest
from sklearn.linear_model import Ridge
from sklearn.naive_bayes import MultinomialNB

import spark_sklearn_tpu as sst
from spark_sklearn_tpu.parallel.taskgrid import (
    GeometryMismatchError, StreamPlanError, plan_stream_shards)
from spark_sklearn_tpu.search import stream as stream_mod

ALPHAS = [0.1, 1.0, 10.0]


def _count_data(n=600, d=40, n_classes=3, seed=7):
    """Integer-valued X: NB's count statistics and the accuracy
    num/den are integers, exact in f32 -> streamed folds are
    bit-identical to the one-shot in-core reduction."""
    rng = np.random.default_rng(seed)
    X = rng.integers(0, 6, size=(n, d)).astype(np.float64)
    y = rng.integers(0, n_classes, size=n)
    return X, y


def _fit(X, y, est, grid, **cfg_kwargs):
    cfg = sst.TpuConfig(**cfg_kwargs)
    gs = sst.GridSearchCV(est, grid, cv=3, backend="tpu", refit=False,
                          config=cfg)
    with warnings.catch_warnings():
        # belt over the backend="tpu" suspenders: any fallback warning
        # (or accidental host tier) fails the test loudly
        warnings.simplefilter("error", UserWarning)
        gs.fit(X, y)
    return gs


def _split_scores(gs):
    r = gs.cv_results_
    return np.stack([r[f"split{i}_test_score"]
                     for i in range(gs.n_splits_)])


# 40 f64 X-cols/row is 320B; +8B y +12B masks = 340B/row ->
# shard_rows 188 at 64 KiB?  No: pick bytes for ~150 rows so 600
# samples stream as 4+ shards regardless of mask bookkeeping.
_SHARD_BYTES = 150 * (40 * 8 + 8 + 3 * 3 * 4)


class TestStreamParity:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_nb_bit_exact_vs_device(self, depth):
        X, y = _count_data()
        grid = {"alpha": ALPHAS}
        ref = _fit(X, y, MultinomialNB(), grid)
        got = _fit(X, y, MultinomialNB(), grid, data_mode="stream",
                   stream_shard_bytes=_SHARD_BYTES,
                   pipeline_depth=depth)
        blk = got.search_report["streaming"]
        assert blk["n_shards"] >= 3
        assert blk["fit_shards_streamed"] == blk["n_shards"]
        assert blk["score_shards_streamed"] == blk["n_shards"]
        assert blk["h2d_bytes"] > 0
        # THE tentpole claim: bit-exact, not allclose
        assert np.array_equal(_split_scores(got), _split_scores(ref))
        assert np.array_equal(got.cv_results_["mean_test_score"],
                              ref.cv_results_["mean_test_score"])

    def test_ridge_stream_matches_device(self, diabetes):
        X, y = diabetes
        grid = {"alpha": [0.01, 0.1, 1.0]}
        ref = _fit(X, y, Ridge(), grid)
        got = _fit(X, y, Ridge(), grid, data_mode="stream",
                   stream_shard_bytes=150 * X.shape[1] * 8)
        assert got.search_report["streaming"]["n_shards"] >= 2
        # r2's sufficient statistics reduce in a different association
        # order than the in-core scorer: allclose, not array_equal
        assert np.allclose(_split_scores(got), _split_scores(ref),
                           rtol=1e-4, atol=1e-5)

    def test_streaming_block_absent_in_device_mode(self):
        X, y = _count_data(n=120)
        gs = _fit(X, y, MultinomialNB(), {"alpha": [1.0]})
        assert "streaming" not in gs.search_report


class TestStreamBudget:
    def test_tiny_budget_caps_shards_no_oom_bisection(self):
        """A budget far below the dataset: the planner (not OOM
        trial-and-error) shrinks the shard; zero bisections."""
        X, y = _count_data()
        got = _fit(X, y, MultinomialNB(), {"alpha": ALPHAS},
                   data_mode="stream",
                   hbm_budget_bytes=64 << 10, memory_ledger=True)
        blk = got.search_report["streaming"]
        assert blk["capped"] is True
        assert blk["n_shards"] >= 3
        faults = got.search_report.get("faults", {})
        assert faults.get("bisections", 0) == 0
        assert faults.get("host_fallbacks", 0) == 0
        ref = _fit(X, y, MultinomialNB(), {"alpha": ALPHAS})
        assert np.array_equal(_split_scores(got), _split_scores(ref))

    def test_h2d_bytes_tracks_two_passes(self):
        """Streamed upload volume ~= fit pass + score pass (2x the
        dataset + masks + small change), never a dense blowup."""
        X, y = _count_data()
        got = _fit(X, y, MultinomialNB(), {"alpha": ALPHAS},
                   data_mode="stream", stream_shard_bytes=_SHARD_BYTES)
        blk = got.search_report["streaming"]
        dataset = X.astype(np.float32).nbytes
        assert blk["h2d_bytes"] <= 4 * dataset
        # padding waste is bounded by one shard per pass
        assert blk["shard_rows"] * blk["n_shards"] \
            < blk["n_samples"] + blk["shard_rows"]

    def test_impossible_budget_raises_plan_error(self):
        with pytest.raises(StreamPlanError, match="hbm_budget_bytes"):
            plan_stream_shards(1000, 4096, 1 << 20,
                               budget_bytes=8192, reserved_bytes=4096)


class TestStreamResume:
    def _kill_after(self, monkeypatch, n_fit_shards):
        """Arm the journal so the search dies right AFTER the
        n_fit_shards-th per-shard fit record is durable -- the
        mid-stream analog of test_checkpoint_kill's SIGKILL."""
        from spark_sklearn_tpu.utils.checkpoint import SearchCheckpoint
        real_put = SearchCheckpoint.put
        seen = {"n": 0}

        def dying_put(self, chunk_id, record):
            real_put(self, chunk_id, record)
            if chunk_id.startswith("st:fit:"):
                seen["n"] += 1
                if seen["n"] >= n_fit_shards:
                    raise RuntimeError("injected mid-stream kill")

        monkeypatch.setattr(SearchCheckpoint, "put", dying_put)
        return seen

    def test_kill_mid_shard_resume_bit_exact(self, tmp_path,
                                             monkeypatch):
        X, y = _count_data()
        grid = {"alpha": ALPHAS}
        ckpt_dir = str(tmp_path / "ckpt")
        seen = self._kill_after(monkeypatch, 2)
        with pytest.raises(RuntimeError, match="injected"):
            _fit(X, y, MultinomialNB(), grid, data_mode="stream",
                 stream_shard_bytes=_SHARD_BYTES,
                 checkpoint_dir=ckpt_dir)
        assert seen["n"] >= 2          # died with >=2 shards durable
        monkeypatch.undo()

        got = _fit(X, y, MultinomialNB(), grid, data_mode="stream",
                   stream_shard_bytes=_SHARD_BYTES,
                   checkpoint_dir=ckpt_dir)
        blk = got.search_report["streaming"]
        assert blk["fit_shards_resumed"] >= 1
        assert blk["fit_shards_streamed"] + blk["fit_shards_resumed"] \
            == blk["n_shards"]
        ref = _fit(X, y, MultinomialNB(), grid)
        assert np.array_equal(_split_scores(got), _split_scores(ref))

    def test_geometry_change_fails_loudly(self, tmp_path, monkeypatch):
        X, y = _count_data()
        grid = {"alpha": ALPHAS}
        ckpt_dir = str(tmp_path / "ckpt")
        self._kill_after(monkeypatch, 1)
        with pytest.raises(RuntimeError, match="injected"):
            _fit(X, y, MultinomialNB(), grid, data_mode="stream",
                 stream_shard_bytes=_SHARD_BYTES,
                 checkpoint_dir=ckpt_dir)
        monkeypatch.undo()
        with pytest.raises(GeometryMismatchError,
                           match="stream-shard geometry"):
            _fit(X, y, MultinomialNB(), grid, data_mode="stream",
                 stream_shard_bytes=_SHARD_BYTES * 2,
                 checkpoint_dir=ckpt_dir)

    def test_clean_rerun_resumes_whole_chunks(self, tmp_path):
        X, y = _count_data()
        grid = {"alpha": ALPHAS}
        ckpt_dir = str(tmp_path / "ckpt")
        kw = dict(data_mode="stream", stream_shard_bytes=_SHARD_BYTES,
                  checkpoint_dir=ckpt_dir)
        first = _fit(X, y, MultinomialNB(), grid, **kw)
        again = _fit(X, y, MultinomialNB(), grid, **kw)
        blk = again.search_report["streaming"]
        assert blk["n_live_chunks"] == 0
        assert blk["fit_shards_streamed"] == 0
        assert np.array_equal(_split_scores(again),
                              _split_scores(first))


class TestStreamKnobs:
    def test_resolve_data_mode_default_and_config(self):
        assert stream_mod.resolve_data_mode(sst.TpuConfig()) == "device"
        assert stream_mod.resolve_data_mode(
            sst.TpuConfig(data_mode="stream")) == "stream"

    def test_resolve_data_mode_env_mirror(self, monkeypatch):
        monkeypatch.setenv("SST_DATA_MODE", "stream")
        assert stream_mod.resolve_data_mode(sst.TpuConfig()) == "stream"
        # the config field wins over the env mirror
        assert stream_mod.resolve_data_mode(
            sst.TpuConfig(data_mode="device")) == "device"

    def test_resolve_data_mode_rejects_unknown(self):
        with pytest.raises(ValueError, match="data tier"):
            stream_mod.resolve_data_mode(
                sst.TpuConfig(data_mode="turbo"))

    def test_resolve_shard_bytes_chain(self, monkeypatch):
        assert stream_mod.resolve_shard_bytes(sst.TpuConfig()) \
            == stream_mod.DEFAULT_SHARD_BYTES
        monkeypatch.setenv("SST_STREAM_SHARD_BYTES", "12345")
        assert stream_mod.resolve_shard_bytes(sst.TpuConfig()) == 12345
        assert stream_mod.resolve_shard_bytes(
            sst.TpuConfig(stream_shard_bytes=99)) == 99
        with pytest.raises(ValueError, match="positive"):
            stream_mod.resolve_shard_bytes(
                sst.TpuConfig(stream_shard_bytes=0))

    def test_check_stream_supported_contract(self):
        import types
        cfg = sst.TpuConfig()
        ok = types.SimpleNamespace(supports_stream=True, name="ok",
                                   default_scorer=None)
        stream_mod.check_stream_supported(ok, None, cfg)
        no = types.SimpleNamespace(supports_stream=False, name="no",
                                   default_scorer=None)
        with pytest.raises(ValueError, match="streaming-fold protocol"):
            stream_mod.check_stream_supported(no, None, cfg)
        with pytest.raises(ValueError, match="default scorer only"):
            stream_mod.check_stream_supported(ok, "f1_macro", cfg)
        with pytest.raises(ValueError, match="n_data_shards"):
            stream_mod.check_stream_supported(
                ok, None, sst.TpuConfig(n_data_shards=2))

    def test_unsupported_family_fails_fast(self):
        """KNN has no streaming fold: the stream tier must refuse
        loudly instead of silently densifying."""
        from sklearn.neighbors import KNeighborsClassifier
        X, y = _count_data(n=80)
        with pytest.raises(ValueError,
                           match="streaming-fold protocol"):
            _fit(X, y, KNeighborsClassifier(), {"n_neighbors": [3]},
                 data_mode="stream")

    def test_plan_stream_shards_geometry(self):
        p = plan_stream_shards(1000, 100, 100 * 250)
        assert (p.shard_rows, p.n_shards, p.capped) == (250, 4, False)
        q = plan_stream_shards(1000, 100, 100 * 250,
                               budget_bytes=100 * 100 * 2 * 2,
                               reserved_bytes=0)
        assert q.capped and q.shard_rows < 250
        assert q.n_shards * q.shard_rows >= 1000
