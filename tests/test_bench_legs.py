"""Smoke-tests for bench.py: every measurement leg at toy shapes, plus
the orchestrator's always-emit guarantees.

VERDICT r3 weak #2: the TPU-only bench legs had never executed on any
platform — their first-ever run would have been inside the rare,
high-stakes chip-unwedge window.  These tests run each leg at toy size
on the 8-virtual-device CPU mesh and assert its detail dict carries
finite numbers, so the unwedge window runs pre-tested code.

VERDICT r3 next #1 done-criterion: a wedged chip (simulated with
BENCH_FAKE_WEDGE=1, which makes the probe child hang) must still yield
a parseable JSON line inside the hard budget, including under SIGTERM.
"""

import json
import math
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def _assert_finite(d, keys):
    for k in keys:
        assert k in d, f"missing {k} in {sorted(d)}"
        v = d[k]
        if isinstance(v, (int, float)):
            assert math.isfinite(v), f"{k} not finite: {v}"


class TestLegsToyShapes:
    """Each leg runs for real (compile + fit + score) at toy size."""

    def test_headline(self, tmp_path):
        detail, fps, vs = bench.leg_headline(
            cache_dir=None, n_candidates=4, n_folds=2, max_iter=10,
            serial_subsample=2)
        _assert_finite(detail, ["wall_s_cold", "wall_s_warm", "n_fits",
                                "best_mean_test_score",
                                "serial_sklearn_est_s",
                                "spark8_ideal_proxy_s"])
        assert detail["n_fits"] == 8
        assert math.isfinite(fps) and fps > 0
        assert math.isfinite(vs)
        # the device-memory ledger must be populated (ISSUE 10: the
        # headline leg asserts it, so an unpopulated ledger fails the
        # bench, not just the report)
        assert detail["memory_warm"]["peak_modeled_bytes"] > 0
        assert "n_capped_widths" in detail["memory_warm"]
        # the MFU record exists whenever the engine reported iterations
        if "headline_mfu" in detail:
            _assert_finite(detail["headline_mfu"],
                           ["achieved_gflops_per_s", "pct_of_bf16_peak"])
            assert "device_kind" in detail["headline_mfu"][
                "peak_denominator"]

    def test_svc_mxu(self):
        d = bench.leg_svc_mxu(n=96, d=16, folds=2, max_iter=10,
                              C_values=(1.0,), gamma_values=(0.01,))
        _assert_finite(d, ["wall_s", "fits_per_sec",
                           "kernel_tflops_total",
                           "achieved_gflops_per_s",
                           "pct_of_bf16_peak", "best_score"])
        assert d["kernel_tflops_total"] > 0

    def test_svc_digits(self):
        d = bench.leg_svc_digits(n_C=2, n_gamma=1, folds=2, n_rows=200)
        _assert_finite(d, ["wall_s", "fits_per_sec", "best_score"])

    def test_config3_rf(self):
        d = bench.leg_config3_rf(n=400, d=8, n_classes=3, n_iter=2,
                                 folds=2, est_lo=5, est_hi=8,
                                 depth_lo=2, depth_hi=4)
        _assert_finite(d, ["wall_s", "fits_per_sec"])
        assert d["backend"]

    def test_config4_gbr(self):
        d = bench.leg_config4_gbr(n=300, d=4, folds=2,
                                  learning_rates=(0.1,),
                                  n_estimators=(10,))
        _assert_finite(d, ["wall_s", "fits_per_sec"])
        assert d["backend"]

    def test_config5_mlp(self):
        d = bench.leg_config5_mlp(hidden=8, max_iter=5, folds=2,
                                  alphas=(1e-3,))
        _assert_finite(d, ["wall_s", "fits_per_sec"])
        assert d["backend"]

    def test_keyed(self):
        d = bench.leg_keyed(n_keys=8, rows=10, d=3)
        _assert_finite(d, ["wall_s", "models_per_sec"])
        assert d["backend"]

    def test_halving_adaptive(self):
        d = bench.leg_halving(n_rows=242, n_candidates=24, folds=2,
                              max_iter=5)
        _assert_finite(d, ["exhaustive_warm_wall_s",
                           "halving_warm_wall_s",
                           "halving_replan_off_warm_wall_s",
                           "wall_ratio_exhaustive_over_halving",
                           "lanes_reclaimed_total"])
        assert d["n_rungs"] >= 2
        assert len(d["rungs"]) == d["n_rungs"]
        # halving spends strictly fewer candidate x resource units
        # (its extra fits run at small resources; rung row-compaction
        # makes their compute proportional)
        assert d["resource_units_halving"] < \
            d["resource_units_exhaustive"]
        # lane reclamation is pure geometry: the control arm agrees
        assert d["replan_off_cv_results_identical"] is True
        assert d["best_params_agree"] is True
        assert d["memory"]["peak_modeled_bytes"] > 0

    def test_chunkloop_scan(self):
        d = bench.leg_chunkloop(n_rows=242, n_candidates=24, folds=2,
                                max_iter=10)
        _assert_finite(d, ["per_chunk_warm_wall_s", "scan_warm_wall_s",
                           "n_launches_per_chunk", "n_launches_scan",
                           "scan_launches_per_group",
                           "launch_collapse_ratio"])
        # the launch boundary actually melts: the scan arm runs ONE
        # launch per compile group while the per-chunk arm pays one
        # per chunk, and the collapse changes nothing numeric
        assert d["scan_launches_per_group"] == 1.0
        assert d["n_launches_scan"] == d["n_groups"]
        assert d["n_launches_per_chunk"] > d["n_launches_scan"]
        assert d["n_launches_saved"] == \
            d["n_chunks_scanned"] - d["n_segments"]
        assert d["scan_fallbacks"] == []
        assert d["scan_cv_results_identical"] is True
        assert d["memory"]["peak_modeled_bytes"] > 0

    def test_serve_contended(self):
        d = bench.leg_serve_contended(n_rows=96, n_candidates=16,
                                      folds=2, max_iter=5, levels=(2,))
        _assert_finite(d, ["solo_wall_s"])
        c2 = d["contended_2"]
        _assert_finite(c2, ["wall_s", "searches_per_min",
                            "queue_wait_p50_s", "queue_wait_p95_s"])
        assert len(c2["interleave_frac"]) == 2
        assert c2["queue_wait_p95_s"] >= c2["queue_wait_p50_s"]
        # per-tenant data-plane residency (ISSUE 10 bugfix: the SLO
        # view used to omit residency, hiding quota-pressure
        # starvation).  The content-deduped plane charges whichever
        # tenant uploaded first — here the solo warm-up ("default"),
        # or NOBODY when an earlier test in the process already left
        # the same digits rows resident unowned — so the contract is
        # the column's presence and truthful attribution, not a
        # particular owner.
        resid = c2["tenant_resident_bytes"]
        assert isinstance(resid, dict)
        assert set(resid) <= {"default", "tenant0", "tenant1"}, resid
        assert all(v > 0 for v in resid.values()), resid
        # tenant-stamped waits (ISSUE 8): the contended leg reports a
        # distinct per-tenant distribution, not just the aggregate
        # (a tenant whose dispatches all ran fastpath — e.g. the other
        # search already drained — legitimately has no wait samples)
        per_tenant = c2["per_tenant_queue_wait"]
        assert set(per_tenant) <= {"tenant0", "tenant1"}, per_tenant
        assert per_tenant, c2
        for t in per_tenant.values():
            assert t["p95_s"] >= t["p50_s"] >= 0.0
            assert t["n"] >= 1
        # warm-restart cost (serve/journal.py): the leg recovers a
        # journaled non-terminal submission and records the
        # time-to-recover gauge bench_trend watches
        rec = d["recovery"]
        assert rec["recovered_total"] >= 1
        assert rec["lease_takeovers_total"] >= 1
        assert rec["time_to_recover_s"] > 0.0


#: the legs appended to ``_BREADTH_LEGS`` after the rehearsal check was
#: written report their throughput under leg-specific names (the same
#: ones tools/bench_trend.py reads), not ``fits_per_sec`` — map each to
#: its headline rate so the "every leg produced a real figure" loop
#: covers the whole sequence instead of tripping on the first new leg
_LEG_RATES = {
    "serve_contended": lambda leg: max(
        (leg[k]["searches_per_min"] for k in leg
         if k.startswith("contended_")), default=None),
    "halving_adaptive": lambda leg: (
        leg["n_fits_halving"] / leg["halving_warm_wall_s"]
        if leg.get("halving_warm_wall_s") else None),
    "stream_sparse": lambda leg: (
        1.0 / leg["stream_wall_s"]
        if leg.get("stream_wall_s") else None),
    "chunkloop_scan": lambda leg: (
        1.0 / leg["scan_warm_wall_s"]
        if leg.get("scan_warm_wall_s") else None),
}


def _leg_rate(key, leg):
    if key in _LEG_RATES:
        return _LEG_RATES[key](leg)
    return leg.get("fits_per_sec", leg.get("models_per_sec"))


def _last_json_line(stdout):
    return bench._parse_last_json_line(stdout)


def _wedged_env(**extra):
    env = dict(os.environ)
    env.update({
        "BENCH_FAKE_WEDGE": "1",        # probe child hangs = wedge signature
        "BENCH_PROBE_TIMEOUT_S": "2",
        "BENCH_PROBE_RETRY_SLEEP_S": "1",
    })
    env.update(extra)
    return env


class TestOrchestratorAlwaysEmits:
    """The round-3 failure mode (rc=124, empty stdout) must be
    impossible: wedged chip, harness kill, and hard-budget expiry all
    still produce a parseable last JSON line."""

    def test_budget_expiry_flushes_fallback_line(self):
        # budget so small the CPU child cannot finish: SIGALRM fires,
        # the handler must flush a parseable payload and exit 0
        t0 = time.time()
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=60,
            env=_wedged_env(BENCH_TOTAL_BUDGET_S="8",
                            BENCH_CPU_CANDIDATES="2"))
        wall = time.time() - t0
        assert wall < 45, f"orchestrator overran its 8s budget: {wall:.0f}s"
        assert r.returncode == 0
        out = _last_json_line(r.stdout)
        assert out is not None, f"no parseable line in: {r.stdout!r}"
        assert "value" in out and "vs_baseline" in out

    def test_sigterm_flushes_line(self):
        # the driver's `timeout` sends SIGTERM — stdout must already
        # hold (or immediately receive) a parseable line.  Interpreter
        # startup is seconds here (sitecustomize imports jax), so wait
        # for the orchestrator's readiness marker before killing.
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "bench.py")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_wedged_env(BENCH_TOTAL_BUDGET_S="600",
                            BENCH_CPU_CANDIDATES="2"))
        # read past any import-time stderr noise until the marker
        # (sitecustomize's jax import may print warnings first)
        deadline = time.time() + 60
        while True:
            marker = proc.stderr.readline()
            if "signal handlers installed" in marker:
                break
            assert marker != "" and time.time() < deadline, \
                f"marker never appeared; last stderr line: {marker!r}"
        time.sleep(1.0)  # inside the probe/CPU-smoke phase
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
        payload = _last_json_line(out)
        assert payload is not None, f"no parseable line in: {out!r}"

    @pytest.mark.slow
    def test_wedged_chip_yields_cpu_fallback_within_budget(self):
        # the full done-criterion: fake-wedged probe, real scaled-down
        # CPU smoke, parseable cpu-fallback line, wall << driver budget
        t0 = time.time()
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=540,
            env=_wedged_env(BENCH_TOTAL_BUDGET_S="480",
                            BENCH_CPU_CANDIDATES="4"))
        wall = time.time() - t0
        assert r.returncode == 0
        out = _last_json_line(r.stdout)
        assert out is not None, f"no parseable line in: {r.stdout!r}"
        assert out["platform"] == "cpu-fallback"
        assert out["value"] > 0
        assert out["detail"]["n_fits"] == 20
        # probes were attempted and recorded the wedge signature
        assert any(a.get("status") == "probe-timeout"
                   for a in out["tpu_probe_attempts"])
        assert wall < 480 + 30


@pytest.mark.slow
class TestFullSequenceRehearsal:
    """VERDICT r4 next #1: the chip-unwedge window must run pre-rehearsed
    code end-to-end.  BENCH_FORCE_BREADTH=1 makes the CPU child execute
    the EXACT TPU sequence — headline, then every breadth leg, shared
    compile cache, a superseding milestone emission per leg — at scaled
    shapes; the final JSON line must carry every leg's numbers and no
    per-leg error."""

    def test_cpu_child_runs_all_breadth_legs(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=1500,
            env=_wedged_env(BENCH_TOTAL_BUDGET_S="1320",
                            BENCH_CPU_CANDIDATES="4",
                            BENCH_FORCE_BREADTH="1"))
        assert r.returncode == 0
        out = _last_json_line(r.stdout)
        assert out is not None, f"no parseable line in: {r.stdout!r}"
        detail = out["detail"]
        for key, _fn, _kw in bench._BREADTH_LEGS:
            assert f"{key}_error" not in detail, detail[f"{key}_error"]
            assert key in detail, f"{key} missing: breadth never ran"
        # every leg produced a real throughput figure
        for key, _fn, _kw in bench._BREADTH_LEGS:
            leg = detail[key]
            rate = _leg_rate(key, leg)
            assert rate and math.isfinite(rate) and rate > 0, (key, leg)
