"""Cross-search launch fusion tests (spark_sklearn_tpu/serve/ +
parallel/pipeline.py FusedLaunch).

Covers the fusion contract end to end: two- and three-tenant fused
launches bit-exact vs their solo runs, fault recovery at member
boundaries (an injected OOM bisects only the faulting member's range;
a failing fused launch scatters to EVERY member, each of which
recovers over only its own rows) with both journals independently
resumable, cancellation of one member leaving its peers' launch
intact, x64-exclusive families never fusing with f32 peers, DRR fair
share holding within tolerance with fusion on, and the ``fusion=False``
escape hatch reproducing the pre-fusion scheduler block exactly.
"""

import time

import numpy as np
import pytest

import spark_sklearn_tpu as sst
from spark_sklearn_tpu import serve
from spark_sklearn_tpu.obs.metrics import SCHEDULER_BLOCK_SCHEMA
from spark_sklearn_tpu.parallel.pipeline import FusedLaunch, FuseSpec, LaunchItem
from spark_sklearn_tpu.serve import executor as executor_mod
from spark_sklearn_tpu.serve.executor import (
    SearchCancelledError,
    SearchExecutor,
    SearchHandle,
    _Reply,
    _Request,
)

from sklearn.linear_model import LogisticRegression

rng = np.random.RandomState(0)
X = rng.randn(96, 6).astype(np.float32)
y = (X[:, 0] + 0.25 * rng.randn(96) > 0).astype(np.int64)

GRID_A = np.logspace(-2, 1, 40).tolist()
GRID_B = np.logspace(-3, 2, 40).tolist()
GRID_C = np.logspace(-1, 3, 40).tolist()

#: conditional scheduler-block keys — present only with fusion ON
FUSION_KEYS = {"n_fused", "lanes_donated", "lanes_borrowed",
               "fusion_saved_launches"}


def logreg_search(grid, config=None):
    return sst.GridSearchCV(LogisticRegression(max_iter=10),
                            {"C": grid}, cv=2, refit=False,
                            backend="tpu", config=config)


def scores(search):
    return search.cv_results_["mean_test_score"]


def fuse_cfg(**kw):
    """A config whose fusion window is wide enough that the two
    searches' chunk cadences always find each other in the queue."""
    kw.setdefault("max_tasks_per_batch", 16)
    kw.setdefault("fusion_window_ms", 200.0)
    return sst.TpuConfig(**kw)


def sched(search):
    return search.search_report["scheduler"]


def wait_for(cond, timeout=60.0, interval=0.005):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


def run_concurrent(sess, searches, timeout=300):
    """Submit every search with the dispatch loop paused so their first
    chunks co-queue, then resume — the deterministic contended start."""
    ex = sess.executor
    ex.pause()
    futs = [sess.submit(s, X, y) for s in searches]
    assert wait_for(lambda: ex.queued_count() >= len(searches)), \
        ex.stats()
    ex.resume()
    return [f.result(timeout=timeout) for f in futs]


# ---------------------------------------------------------------------------
# Parity: fused members bit-exact vs solo
# ---------------------------------------------------------------------------


class TestFusedParity:
    def test_two_tenants_fused_bit_exact(self):
        ref_a = logreg_search(GRID_A, fuse_cfg()).fit(X, y)
        ref_b = logreg_search(GRID_B, fuse_cfg()).fit(X, y)
        sess = sst.createLocalTpuSession("fuse-pair", config=fuse_cfg())
        try:
            a, b = run_concurrent(sess, [
                logreg_search(GRID_A, fuse_cfg(tenant="ta")),
                logreg_search(GRID_B, fuse_cfg(tenant="tb"))])
            np.testing.assert_array_equal(scores(a), scores(ref_a))
            np.testing.assert_array_equal(scores(b), scores(ref_b))
            sa, sb = sched(a), sched(b)
            # fused dispatches happened, and the lane exchange is
            # conserved: what heads donated is what peers borrowed
            assert sa["n_fused"] + sb["n_fused"] > 0, (sa, sb)
            assert sa["fusion_saved_launches"] + \
                sb["fusion_saved_launches"] > 0
            assert sa["lanes_donated"] + sb["lanes_donated"] == \
                sa["lanes_borrowed"] + sb["lanes_borrowed"]
        finally:
            sess.stop()

    def test_three_tenants_fused_bit_exact(self):
        from spark_sklearn_tpu.obs import telemetry as tel
        refs = [logreg_search(g, fuse_cfg()).fit(X, y)
                for g in (GRID_A, GRID_B, GRID_C)]
        sess = sst.createLocalTpuSession(
            "fuse-trio", config=fuse_cfg(telemetry_port=0))
        try:
            f0 = tel.get_telemetry().snapshot()["fusion"]
            got = run_concurrent(sess, [
                logreg_search(g, fuse_cfg(tenant=f"t{i}"))
                for i, g in enumerate((GRID_A, GRID_B, GRID_C))])
            for g, r in zip(got, refs):
                np.testing.assert_array_equal(scores(g), scores(r))
            blocks = [sched(g) for g in got]
            assert sum(s["n_fused"] for s in blocks) >= 2, blocks
            assert sum(s["fusion_saved_launches"]
                       for s in blocks) >= 1, blocks
            # the telemetry fusion family saw the same launches
            f1 = tel.get_telemetry().snapshot()["fusion"]
            assert f1["fused_total"] > f0["fused_total"]
            assert f1["members_total"] - f0["members_total"] >= \
                2 * (f1["fused_total"] - f0["fused_total"])
            assert f1["lanes_real_total"] <= f1["lanes_padded_total"]
        finally:
            sess.stop()


# ---------------------------------------------------------------------------
# Faults: member-boundary recovery + journals
# ---------------------------------------------------------------------------


class TestFusedFaults:
    def test_injected_oom_bisects_faulting_member_only(self, tmp_path):
        """``oom@3`` on tenant A under fusion: A recovers through its
        own bisection with exact scores, B records zero faults, and
        BOTH journals independently resume a fresh identical search."""
        cfg_a = fuse_cfg(tenant="faulty", fault_plan="oom@3",
                         retry_backoff_s=0.01,
                         checkpoint_dir=str(tmp_path / "a"))
        cfg_b = fuse_cfg(tenant="healthy",
                         checkpoint_dir=str(tmp_path / "b"))
        ref_a = logreg_search(GRID_A, fuse_cfg()).fit(X, y)
        ref_b = logreg_search(GRID_B, fuse_cfg()).fit(X, y)
        sess = sst.createLocalTpuSession("fuse-oom", config=fuse_cfg())
        try:
            a, b = run_concurrent(sess, [
                logreg_search(GRID_A, cfg_a),
                logreg_search(GRID_B, cfg_b)])
            np.testing.assert_array_equal(scores(a), scores(ref_a))
            np.testing.assert_array_equal(scores(b), scores(ref_b))
            assert a.search_report["faults"]["bisections"] >= 1, \
                a.search_report["faults"]
            fb = b.search_report["faults"]
            assert fb["bisections"] == 0 and fb["retries"] == 0, fb
        finally:
            sess.stop()
        # per-member journal lines: each checkpoint independently
        # resumes its own search — fused execution left both journals
        # exactly as their solo runs would have
        for grid, ref, sub in ((GRID_A, ref_a, "a"), (GRID_B, ref_b,
                                                      "b")):
            cfg = sst.TpuConfig(max_tasks_per_batch=16,
                                checkpoint_dir=str(tmp_path / sub))
            resumed = logreg_search(grid, cfg).fit(X, y)
            np.testing.assert_array_equal(scores(resumed), scores(ref))
            assert resumed.search_report["n_chunks_resumed"] > 0

    def test_fused_launch_failure_scatters_to_all_members(
            self, monkeypatch):
        """A fused launch that OOMs mid-flight is delivered to EVERY
        member; each member's supervisor bisects only its OWN candidate
        range, and both searches still land bit-exact."""
        state = {"failed": False, "fused": 0}
        real = FusedLaunch

        class FailOnce(real):
            def run(self):
                state["fused"] += 1
                if not state["failed"]:
                    state["failed"] = True
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: injected fused-launch OOM")
                return super().run()

        monkeypatch.setattr(executor_mod, "FusedLaunch", FailOnce)
        ref_a = logreg_search(GRID_A, fuse_cfg()).fit(X, y)
        ref_b = logreg_search(GRID_B, fuse_cfg()).fit(X, y)
        sess = sst.createLocalTpuSession(
            "fuse-scatter",
            config=fuse_cfg(retry_backoff_s=0.01))
        try:
            a, b = run_concurrent(sess, [
                logreg_search(GRID_A,
                              fuse_cfg(tenant="ta",
                                       retry_backoff_s=0.01)),
                logreg_search(GRID_B,
                              fuse_cfg(tenant="tb",
                                       retry_backoff_s=0.01))])
            assert state["failed"] and state["fused"] >= 1
            np.testing.assert_array_equal(scores(a), scores(ref_a))
            np.testing.assert_array_equal(scores(b), scores(ref_b))
            # the shared failure bisected at the member boundary: each
            # search recovered through its OWN hook
            assert a.search_report["faults"]["bisections"] >= 1
            assert b.search_report["faults"]["bisections"] >= 1
        finally:
            sess.stop()


# ---------------------------------------------------------------------------
# Cancellation: a member dropping out never touches its peers
# ---------------------------------------------------------------------------


def _synth_request(handle, key, n, cost=4, out_tag=""):
    """A queueable request whose item carries a synthetic FuseSpec —
    the executor-level unit-test stand-in (no device work)."""
    spec = FuseSpec(
        key=key, n=n, shard=1, max_width=0,
        rows=lambda: {},
        run=lambda specs: [f"{out_tag}{i}" for i in
                           range(sum(int(s.n) for s in specs))],
        slice_out=lambda out, off, m: out[off:off + m])
    item = LaunchItem(key=f"{handle.id}:{key}", kind="fused",
                      n_tasks=cost, launch=lambda p: f"solo:{out_tag}",
                      fuse=spec)
    now = time.perf_counter()
    return _Request(handle=handle, item=item,
                    launch=lambda p: f"solo:{out_tag}", payload=None,
                    cost=cost, state={"counted": False},
                    t_enqueued=now, reply=_Reply())


class TestFusedCancellation:
    def test_cancelled_member_drops_out_peer_runs_solo(self):
        """_run_fused with one member cancelled between claim and
        launch: the cancelled reply fails, the survivor dispatches solo
        on its own staged payload with NO fusion accounting."""
        ex = SearchExecutor()
        try:
            h_live = SearchHandle("t1/s1", "t1", 1.0)
            h_dead = SearchHandle("t2/s1", "t2", 1.0)
            r_live = _synth_request(h_live, ("k",), 4, out_tag="live")
            r_dead = _synth_request(h_dead, ("k",), 4, out_tag="dead")
            for r in (r_live, r_dead):
                r.t_dequeued = time.perf_counter()
            h_dead.cancelled = True
            ex._run_fused([r_live, r_dead])
            assert r_live.reply.result() == "solo:live"
            with pytest.raises(SearchCancelledError):
                r_dead.reply.result()
            assert h_live.n_fused == 0 and h_live.lanes_donated == 0
            assert h_dead.n_fused == 0
        finally:
            ex.shutdown()

    def test_two_live_members_fuse_and_scatter_exactly(self):
        """The synthetic happy path pins the scatter math: each member
        reply gets exactly its [off, off+n) slice and the counters
        split head-donates / peer-borrows."""
        ex = SearchExecutor()
        try:
            h1 = SearchHandle("t1/s1", "t1", 1.0)
            h2 = SearchHandle("t2/s1", "t2", 1.0)
            r1 = _synth_request(h1, ("k",), 3, out_tag="w")
            r2 = _synth_request(h2, ("k",), 2, out_tag="w")
            for r in (r1, r2):
                r.t_dequeued = time.perf_counter()
            ex._run_fused([r1, r2])
            assert r1.reply.result() == ["w0", "w1", "w2"]
            assert r2.reply.result() == ["w3", "w4"]
            assert h1.n_fused == 1 and h1.lanes_donated == 2 \
                and h1.fusion_saved_launches == 1
            assert h2.n_fused == 1 and h2.lanes_borrowed == 2 \
                and h2.fusion_saved_launches == 0
        finally:
            ex.shutdown()

    def test_cancel_one_search_leaves_peer_bit_exact(self):
        ref_a = logreg_search(GRID_A, fuse_cfg()).fit(X, y)
        sess = sst.createLocalTpuSession("fuse-cancel",
                                         config=fuse_cfg())
        try:
            ex = sess.executor
            ex.pause()
            fa = sess.submit(logreg_search(GRID_A,
                                           fuse_cfg(tenant="keep")),
                             X, y)
            fb = sess.submit(logreg_search(GRID_B,
                                           fuse_cfg(tenant="drop")),
                             X, y)
            assert wait_for(lambda: ex.queued_count() >= 2), ex.stats()
            won = fb.cancel()
            ex.resume()
            a = fa.result(timeout=300)
            np.testing.assert_array_equal(scores(a), scores(ref_a))
            if won:
                with pytest.raises(SearchCancelledError):
                    fb.result(timeout=60)
            else:
                fb.result(timeout=300)
        finally:
            sess.stop()


# ---------------------------------------------------------------------------
# Exclusion: x64 families never fuse with f32 peers
# ---------------------------------------------------------------------------


class TestFusionExclusion:
    def test_x64_exclusive_family_never_fuses(self):
        from sklearn.linear_model import Ridge
        yr = (X @ np.arange(6, dtype=np.float32)
              + 0.1 * rng.randn(96)).astype(np.float32)

        def ridge_search(config=None):
            return sst.GridSearchCV(
                Ridge(), {"alpha": np.logspace(-3, 2, 12).tolist()},
                cv=2, refit=False, backend="tpu", config=config)

        ref_r = ridge_search(fuse_cfg()).fit(X, yr)
        ref_l = logreg_search(GRID_A, fuse_cfg()).fit(X, y)
        sess = sst.createLocalTpuSession("fuse-x64", config=fuse_cfg())
        try:
            fr = sess.submit(ridge_search(fuse_cfg(tenant="tr")), X, yr)
            fl = sess.submit(
                logreg_search(GRID_A, fuse_cfg(tenant="tl")), X, y)
            assert fr._handle.exclusive and not fl._handle.exclusive
            r = fr.result(timeout=300)
            lo = fl.result(timeout=300)
            np.testing.assert_array_equal(scores(r), scores(ref_r))
            np.testing.assert_array_equal(scores(lo), scores(ref_l))
            # exclusive scheduling means the x64 search ran alone: it
            # can never have shared a launch with the f32 peer
            sr = sched(r)
            assert sr["n_fused"] == 0 and sr["lanes_borrowed"] == 0 \
                and sr["lanes_donated"] == 0, sr
        finally:
            sess.stop()


# ---------------------------------------------------------------------------
# Fair share: DRR ratios hold with fusion on
# ---------------------------------------------------------------------------


class TestFairShareFused:
    @staticmethod
    def _drive(ex, handle, n, cost, work_s=0.005):
        replies = []
        for i in range(n):
            spec = FuseSpec(
                key=("synth-fair",), n=cost, shard=1, max_width=0,
                rows=lambda: {},
                run=lambda specs, w=work_s: (
                    time.sleep(w),
                    list(range(sum(int(s.n) for s in specs))))[1],
                slice_out=lambda out, off, m: out[off:off + m])
            item = LaunchItem(key=f"{handle.id}:{i}", kind="fused",
                              n_tasks=cost, fuse=spec,
                              launch=lambda p: time.sleep(0.0))
            req = _Request(
                handle=handle, item=item,
                launch=lambda p, w=work_s: time.sleep(w),
                payload=None, cost=cost, state={"counted": False},
                t_enqueued=time.perf_counter(), reply=_Reply())
            ex._enqueue(req)
            replies.append(req.reply)
        return replies

    def test_drr_shares_track_weights_with_fusion_on(self):
        """Deep fusable queues for two tenants with weights 1:3 — the
        claim pass charges every claimed peer to its own tenant's
        deficit under the same credit law as _pop_next, so the
        dispatch-stream shares still land within 10% of 0.25/0.75."""
        ex = SearchExecutor(sst.TpuConfig(scheduler_quantum=8))
        h_light = SearchHandle("light/s1", "light", 1.0)
        h_heavy = SearchHandle("heavy/s1", "heavy", 3.0)
        ex.pause()
        n = 40
        self._drive(ex, h_light, n, cost=8)
        heavy_replies = self._drive(ex, h_heavy, n, cost=8)
        ex.resume()
        for r in heavy_replies:
            r.result()
        ex.pause()    # freeze the light backlog's drain at this instant
        block = ex.search_block(h_heavy)
        shares = block["tenant_shares"]
        assert abs(shares["heavy"] - 0.75) <= 0.10, block
        assert abs(shares["light"] - 0.25) <= 0.10, block
        # fusion genuinely engaged while fairness held
        assert h_heavy.n_fused + h_light.n_fused > 0, block
        ex.resume()
        ex.shutdown()


# ---------------------------------------------------------------------------
# fusion=False: the exact escape hatch
# ---------------------------------------------------------------------------


class TestFusionOff:
    def test_fusion_off_block_shape_and_parity(self):
        """``fusion=False`` reproduces the pre-fusion engine: no fused
        dispatches, no fusion keys in the scheduler block, and
        bit-exact scores under the same contended start."""
        cfg = sst.TpuConfig(max_tasks_per_batch=16, fusion=False)
        ref_a = logreg_search(GRID_A, cfg).fit(X, y)
        ref_b = logreg_search(GRID_B, cfg).fit(X, y)
        sess = sst.createLocalTpuSession("fuse-off", config=cfg)
        try:
            a, b = run_concurrent(sess, [
                logreg_search(GRID_A, cfg), logreg_search(GRID_B, cfg)])
            np.testing.assert_array_equal(scores(a), scores(ref_a))
            np.testing.assert_array_equal(scores(b), scores(ref_b))
            for s in (sched(a), sched(b)):
                assert set(s) == \
                    {d.name for d in SCHEDULER_BLOCK_SCHEMA} \
                    - FUSION_KEYS
                assert s["enabled"] is True
        finally:
            sess.stop()

    def test_fusion_on_default_block_matches_full_schema(self):
        sess = sst.createLocalTpuSession(
            "fuse-on", config=sst.TpuConfig(max_tasks_per_batch=16))
        try:
            fut = sess.submit(logreg_search(GRID_A), X, y)
            got = fut.result(timeout=180)
            s = sched(got)
            assert set(s) == {d.name for d in SCHEDULER_BLOCK_SCHEMA}
            # a solo search has no peers: the counters exist but zero
            assert s["n_fused"] == 0 and s["lanes_donated"] == 0
        finally:
            sess.stop()

    def test_env_escape_hatch_disables_fusion(self, monkeypatch):
        monkeypatch.setenv("SST_FUSION", "0")
        assert serve.resolve_fusion(None) is False
        monkeypatch.setenv("SST_FUSION", "1")
        assert serve.resolve_fusion(None) is True
        monkeypatch.delenv("SST_FUSION")
        assert serve.resolve_fusion(None) is True
        assert serve.resolve_fusion(
            sst.TpuConfig(fusion=False)) is False
