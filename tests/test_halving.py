"""Adaptive search: successive halving (search/halving.py).

Contracts under test:

  - **sklearn parity, byte-exact**: on the host tier (which runs
    sklearn's own `_fit_and_score`), `HalvingGridSearchCV` /
    `HalvingRandomSearchCV` pin `cv_results_` (every non-timing
    column, `iter`/`n_resources` included), `best_params_` and all
    `n_*` halving attributes against sklearn's own estimators for
    three families, covering both the `n_samples` resource
    (`_SubsampleMetaSplitter` fold subsampling) and a masked-prefix
    estimator resource (`n_estimators` on a forest);
  - **compiled tier**: rung structure matches sklearn exactly, scores
    match to fp tolerance, `halving_replan` on vs off produces
    IDENTICAL `cv_results_` (re-planning is purely a geometry
    optimization) while reclaiming lanes, `min_rung_width` floors the
    re-planned widths, and the geometry cost model demonstrably
    learns ACROSS rungs of one search;
  - **resume/fault exactness**: a search killed mid-rung resumes from
    the journal bit-exact; a kill landing BETWEEN a rung's score
    gather and its elimination decision replays the journalled rungs
    with zero launches and re-decides identically; `oom@k` during
    rung 1 bisects and stays exact;
  - **the serving/data-plane seams**: `SearchExecutor.note_rung`
    shrinks the tenant's effective in-flight cap with the surviving
    fraction, and `DataPlane.demote` un-charges a tenant's stale mask
    bytes while keeping the entries servable.
"""

import warnings

import numpy as np
import pytest

from sklearn.ensemble import RandomForestClassifier
from sklearn.experimental import enable_halving_search_cv  # noqa: F401
from sklearn.linear_model import LogisticRegression
from sklearn.model_selection import (
    HalvingGridSearchCV as SkHalvingGrid,
    HalvingRandomSearchCV as SkHalvingRandom,
)
from sklearn.naive_bayes import GaussianNB

import spark_sklearn_tpu as sst
from spark_sklearn_tpu.obs.metrics import HALVING_BLOCK_SCHEMA


def _data(n=96, d=6, seed=0, dtype=np.float64):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(dtype)
    y = (X[:, 0] + 0.25 * rng.randn(n) > 0).astype(np.int64)
    return X, y


def _assert_results_equal(ra, rb, rtol=None):
    """Every non-timing cv_results_ column equal (exact by default)."""
    assert set(ra) == set(rb), (sorted(ra), sorted(rb))
    for k in ra:
        if "time" in k:
            continue
        if k == "params":
            assert list(ra[k]) == list(rb[k])
            continue
        a, b = np.asarray(ra[k]), np.asarray(rb[k])
        if rtol is not None and a.dtype.kind == "f":
            np.testing.assert_allclose(a, b, rtol=rtol, atol=1e-7,
                                       err_msg=k)
        else:
            np.testing.assert_array_equal(a, b, err_msg=k)


def _assert_halving_attrs_equal(ours, ref):
    assert ours.n_resources_ == ref.n_resources_
    assert ours.n_candidates_ == ref.n_candidates_
    assert ours.n_remaining_candidates_ == ref.n_remaining_candidates_
    assert ours.n_iterations_ == ref.n_iterations_
    assert ours.n_possible_iterations_ == ref.n_possible_iterations_
    assert ours.n_required_iterations_ == ref.n_required_iterations_
    assert ours.min_resources_ == ref.min_resources_
    assert ours.max_resources_ == ref.max_resources_
    assert ours.best_index_ == ref.best_index_
    assert ours.best_params_ == ref.best_params_


# ---------------------------------------------------------------------------
# Host-tier byte-exact parity against sklearn (>= 3 families)
# ---------------------------------------------------------------------------


class TestSklearnParityHost:
    """backend='host' runs sklearn's own _fit_and_score, so every
    score — and therefore every elimination decision — must be
    byte-for-byte sklearn's."""

    def _pin(self, est, grid, sk_cls=SkHalvingGrid,
             our_cls=sst.HalvingGridSearchCV, X=None, y=None, **kw):
        if X is None:
            X, y = _data()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ref = sk_cls(est, grid, **kw).fit(X, y)
            ours = our_cls(est, grid, backend="host", **kw).fit(X, y)
        _assert_halving_attrs_equal(ours, ref)
        _assert_results_equal(ref.cv_results_, ours.cv_results_)
        assert ours.best_score_ == ref.best_score_
        return ours, ref

    def test_logreg_n_samples_resource(self):
        ours, ref = self._pin(
            LogisticRegression(max_iter=50),
            {"C": [0.01, 0.1, 1.0, 10.0, 100.0, 1000.0]},
            cv=2, factor=3, random_state=7)
        # the rung columns exist and are integer-valued like sklearn's
        assert ours.cv_results_["iter"].tolist() == \
            ref.cv_results_["iter"].tolist()
        assert ours.cv_results_["n_resources"].tolist() == \
            ref.cv_results_["n_resources"].tolist()

    def test_forest_masked_prefix_resource(self):
        # resource = n_estimators: the masked-prefix trick's rung axis
        ours, _ = self._pin(
            RandomForestClassifier(random_state=3),
            {"max_depth": [2, 3, 4, 5]},
            X=_data(80, 5)[0], y=_data(80, 5)[1],
            cv=2, factor=2, resource="n_estimators", max_resources=12,
            min_resources=3, random_state=7)
        # the resource value landed in the candidates themselves
        assert ours.cv_results_["param_n_estimators"].tolist() == \
            ours.cv_results_["n_resources"].tolist()

    def test_gnb_aggressive_elimination(self):
        self._pin(
            GaussianNB(),
            {"var_smoothing": np.logspace(-9, -4, 18).tolist()},
            cv=2, factor=3, random_state=5,
            aggressive_elimination=True, max_resources=40)

    def test_random_search_sampler_parity(self):
        import scipy.stats as stats
        self._pin(
            LogisticRegression(max_iter=30),
            {"C": stats.loguniform(1e-3, 1e2)},
            sk_cls=SkHalvingRandom, our_cls=sst.HalvingRandomSearchCV,
            cv=2, factor=2, random_state=11, n_candidates=9,
            min_resources=20)

    def test_input_validation_parity(self):
        X, y = _data()
        with pytest.raises(ValueError, match="not supported by"):
            sst.HalvingGridSearchCV(
                GaussianNB(), {"var_smoothing": [1e-9]},
                resource="nope", max_resources=8).fit(X, y)
        with pytest.raises(ValueError, match="part of the searched"):
            sst.HalvingGridSearchCV(
                RandomForestClassifier(), {"n_estimators": [5, 8]},
                resource="n_estimators", max_resources=10,
                backend="host").fit(X, y)
        with pytest.raises(ValueError, match="Multimetric"):
            sst.HalvingGridSearchCV(
                GaussianNB(), {"var_smoothing": [1e-9]},
                scoring=["accuracy", "f1"]).fit(X, y)
        with pytest.raises(ValueError, match="n_samples"):
            sst.HalvingGridSearchCV(
                RandomForestClassifier(), {"max_depth": [2]},
                resource="n_estimators").fit(X, y)  # max_resources=auto


# ---------------------------------------------------------------------------
# Compiled tier: rung structure, lane reclamation, cost-model feedback
# ---------------------------------------------------------------------------

#: deterministic geometry for the compiled tests: manual cost
#: overrides pin the planner (and zero the width-affinity allowance),
#: so rung widths — and the lanes reclaimed — are reproducible
_GEO = dict(geometry_overhead_s=0.05, geometry_lane_cost_s=0.001)


def _fit_compiled_gnb(**cfg_kw):
    X, y = _data(dtype=np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return sst.HalvingGridSearchCV(
            GaussianNB(),
            {"var_smoothing": np.logspace(-9, -5, 24).tolist()},
            cv=2, factor=3, random_state=7, backend="tpu",
            config=sst.TpuConfig(**cfg_kw)).fit(X, y)


@pytest.fixture(scope="module")
def compiled_on():
    return _fit_compiled_gnb(**_GEO)


@pytest.fixture(scope="module")
def compiled_off():
    return _fit_compiled_gnb(halving_replan=False, **_GEO)


class TestCompiledHalving:
    def test_structure_matches_sklearn(self, compiled_on):
        X, y = _data(dtype=np.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ref = SkHalvingGrid(
                GaussianNB(),
                {"var_smoothing": np.logspace(-9, -5, 24).tolist()},
                cv=2, factor=3, random_state=7).fit(X, y)
        ours = compiled_on
        assert ours.search_report["backend"] == "tpu"
        _assert_halving_attrs_equal(ours, ref)
        _assert_results_equal(ref.cv_results_, ours.cv_results_,
                              rtol=1e-5)

    def test_replan_off_is_pure_geometry(self, compiled_on,
                                         compiled_off):
        # the acceptance pin: halving_replan only changes launch
        # geometry, never a single cv_results_ cell
        _assert_results_equal(compiled_on.cv_results_,
                              compiled_off.cv_results_)
        hb_on = compiled_on.search_report["halving"]
        hb_off = compiled_off.search_report["halving"]
        assert hb_on["replan"] is True and hb_off["replan"] is False
        # replanning reclaimed lanes; the pinned run by definition
        # kept every survivor padded to the rung-0 width
        assert hb_on["lanes_reclaimed_total"] > 0
        assert hb_off["lanes_reclaimed_total"] == 0
        for rec in hb_off["rungs"][1:]:
            assert rec["widths"] == hb_off["rungs"][0]["widths"]
        # replanned widths shrink with the survivors
        assert hb_on["rungs"][1]["widths"][0] < \
            hb_on["rungs"][0]["widths"][0]

    def test_halving_block_schema_pin(self, compiled_on):
        block = compiled_on.search_report["halving"]
        declared = {d.name for d in HALVING_BLOCK_SCHEMA}
        assert set(block) == declared
        assert block["enabled"] is True
        assert block["n_rungs"] == compiled_on.n_iterations_
        assert len(block["rungs"]) == block["n_rungs"]
        for rec, n_cand, n_res in zip(block["rungs"],
                                      compiled_on.n_candidates_,
                                      compiled_on.n_resources_):
            assert rec["n_candidates"] == n_cand
            assert rec["n_resources"] == n_res
            assert rec["wall_s"] >= 0.0
            assert rec["widths"]

    def test_cost_model_learns_mid_search(self, compiled_on):
        # ISSUE 9 satellite: rung k+1's re-plan prices widths from
        # rung k's measured timeline — the observation count embedded
        # in each rung's plan strictly increases within ONE search
        obs = [r["cost_observations"]
               for r in compiled_on.search_report["halving"]["rungs"]]
        assert obs == sorted(obs)
        assert obs[-1] > obs[0]

    def test_min_rung_width_floor(self):
        gs = _fit_compiled_gnb(min_rung_width=16, **_GEO)
        rungs = gs.search_report["halving"]["rungs"]
        assert all(w >= 16 for rec in rungs[1:] for w in rec["widths"])

    def test_report_counters_cover_all_rungs(self, compiled_on):
        rep = compiled_on.search_report
        # one shared registry across rungs: the launch counter and the
        # pipeline timeline cover the WHOLE search, not the last rung
        assert rep["n_launches"] >= rep["halving"]["n_rungs"]
        assert rep["pipeline"]["n_launches"] >= rep["halving"]["n_rungs"]
        per_group_keys = list(rep["per_group"])
        assert any(str(k).startswith("r1:") for k in per_group_keys), \
            per_group_keys


# ---------------------------------------------------------------------------
# Resume and fault exactness
# ---------------------------------------------------------------------------


def _mk_logreg_halving(**cfg_kw):
    # max_tasks_per_batch=16 -> width 8 on the 8-device mesh: rung 0
    # runs 5 chunks and rung 1 (14 survivors) runs 2, so launch index
    # 3 is a bisectable FUSED chunk in both rungs
    cfg = sst.TpuConfig(max_tasks_per_batch=16, sort_candidates=False,
                        geometry_overhead_s=0.02,
                        geometry_lane_cost_s=0.001, **cfg_kw)
    return sst.HalvingGridSearchCV(
        LogisticRegression(max_iter=10),
        {"C": np.logspace(-2, 1, 40).tolist()},
        cv=2, factor=3, random_state=7, backend="tpu", config=cfg)


@pytest.fixture(scope="module")
def logreg_base():
    X, y = _data(dtype=np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return _mk_logreg_halving().fit(X, y)


class TestResumeAndFaults:
    def test_oom_during_rung_1_exact(self, logreg_base):
        # launch index 3 is a fused steady-state chunk in BOTH rung 0
        # and rung 1 under this geometry: the bisection recovery runs
        # mid-rung, per-lane bit-identical
        X, y = _data(dtype=np.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            gs = _mk_logreg_halving(
                fault_plan="oom@3", retry_backoff_s=0.01).fit(X, y)
        f = gs.search_report["faults"]
        assert f["bisections"] >= 1, f
        # the shared faults struct accumulated across rungs, and at
        # least one recovery event names a rung-1 chunk
        keys = [e["key"] for e in f["events"]]
        assert any(k.startswith("r1:") for k in keys), keys
        _assert_results_equal(logreg_base.cv_results_, gs.cv_results_)

    def test_killed_mid_rung_resumes_exact(self, logreg_base, tmp_path):
        X, y = _data(dtype=np.float32)
        ckpt = str(tmp_path / "ckpt")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(Exception, match="[Ii]njected"):
                _mk_logreg_halving(
                    checkpoint_dir=ckpt,
                    fault_plan="fatal@3").fit(X, y)
            resumed = _mk_logreg_halving(checkpoint_dir=ckpt).fit(X, y)
        assert resumed.search_report["n_chunks_resumed"] > 0
        _assert_results_equal(logreg_base.cv_results_,
                              resumed.cv_results_)

    def test_kill_between_gather_and_elimination(self, logreg_base,
                                                 tmp_path,
                                                 monkeypatch):
        # the acceptance corner: the kill lands AFTER rung 1's scores
        # are journaled but BEFORE its elimination decision — the
        # restarted search replays both rungs from the journal and
        # re-decides identically
        from spark_sklearn_tpu.search import halving as halving_mod
        X, y = _data(dtype=np.float32)
        ckpt = str(tmp_path / "ckpt")
        real_top_k = halving_mod._top_k

        def killing_top_k(results, k, itr):
            if itr == 1:
                raise RuntimeError("simulated kill before elimination")
            return real_top_k(results, k, itr)

        monkeypatch.setattr(halving_mod, "_top_k", killing_top_k)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(RuntimeError, match="simulated kill"):
                _mk_logreg_halving(checkpoint_dir=ckpt).fit(X, y)
        monkeypatch.setattr(halving_mod, "_top_k", real_top_k)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            resumed = _mk_logreg_halving(checkpoint_dir=ckpt).fit(X, y)
        # rungs 0 and 1 were fully journalled: they replay without a
        # single launch of their own
        rungs = resumed.search_report["halving"]["rungs"]
        assert rungs[0]["n_chunks_resumed"] > 0
        assert rungs[1]["n_chunks_resumed"] > 0
        _assert_results_equal(logreg_base.cv_results_,
                              resumed.cv_results_)

    def test_full_journal_replays_with_zero_launches(self, logreg_base,
                                                     tmp_path):
        X, y = _data(dtype=np.float32)
        ckpt = str(tmp_path / "ckpt")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            first = _mk_logreg_halving(checkpoint_dir=ckpt).fit(X, y)
            second = _mk_logreg_halving(checkpoint_dir=ckpt).fit(X, y)
        assert second.search_report["n_launches"] == 0
        assert second.search_report["n_chunks_resumed"] > 0
        _assert_results_equal(first.cv_results_, second.cv_results_)


# ---------------------------------------------------------------------------
# Serving + data-plane seams
# ---------------------------------------------------------------------------


class TestServeSeams:
    def test_effective_cap_shrinks_with_rung_frac(self):
        from spark_sklearn_tpu.serve.executor import (
            SearchExecutor, SearchHandle)
        ex = SearchExecutor(sst.TpuConfig(tenant_max_inflight=6))
        h = SearchHandle("t/s1", "t", 1.0)
        ex._active.append(h)
        assert ex._effective_cap("t") == 6          # not a halving search
        ex.note_rung(h, 0, 24, 1.0)
        assert ex._effective_cap("t") == 6
        ex.note_rung(h, 1, 8, 8 / 24)
        assert ex._effective_cap("t") == 2
        ex.note_rung(h, 2, 3, 3 / 24)
        assert ex._effective_cap("t") == 1          # never below 1
        assert ex.progress(h)["rung"] == 2
        # a concurrent NON-halving search of the same tenant pins the
        # fraction: the shared cap must never starve it
        h2 = SearchHandle("t/s2", "t", 1.0)
        ex._active.append(h2)
        assert ex._effective_cap("t") == 6
        # other tenants are untouched by this tenant's rungs
        assert ex._effective_cap("other") == 6
        ex.shutdown(wait=False)

    def test_dataplane_demote_uncharges_but_still_hits(self):
        from spark_sklearn_tpu.parallel.dataplane import DataPlane
        plane = DataPlane(byte_budget=1 << 20)
        masks = np.ones((2, 64), np.float32)
        sibling = np.full((2, 64), 2.0, np.float32)
        data = np.ones((64, 4), np.float32)
        plane.put(masks, None, label="mask.r0.fit", tenant="t")
        plane.put(sibling, None, label="mask.fit", tenant="t")
        plane.put(data, None, label="data.X", tenant="t")
        before = plane.tenant_usage("t")
        assert before == masks.nbytes + sibling.nbytes + data.nbytes
        # the rung barrier's scoped prefix: only rung 0's masks demote
        # — a sibling search's live "mask.fit" under the SAME tenant
        # keeps its charge and its LRU position
        freed = plane.demote("mask.r0.", "t")
        assert freed == masks.nbytes
        assert plane.tenant_usage("t") == sibling.nbytes + data.nbytes
        hits0 = plane.stats()["hits"]
        plane.put(masks, None, label="mask.r0.fit", tenant="t")
        assert plane.stats()["hits"] == hits0 + 1   # still resident
        # a demoted entry does not re-charge on hit
        assert plane.tenant_usage("t") == sibling.nbytes + data.nbytes

    @pytest.mark.slow
    def test_submitted_halving_search_parity(self, logreg_base):
        X, y = _data(dtype=np.float32)
        sess = sst.createLocalTpuSession("halving-serve-test")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                fut = sess.submit(_mk_logreg_halving(), X, y)
                got = fut.result(timeout=600)
        finally:
            sess.stop()
        sch = got.search_report["scheduler"]
        assert sch["enabled"] is True
        _assert_results_equal(logreg_base.cv_results_, got.cv_results_)
        assert got.search_report["halving"]["n_rungs"] == \
            logreg_base.n_iterations_
