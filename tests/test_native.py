"""Native runtime (native/libtpusk.so) vs numpy-fallback oracles.

These tests pass with or without the built .so — when it is absent they
exercise the fallbacks; when present (`make -C native`) they verify the
native outputs are bit-identical to the fallbacks.
"""

import numpy as np
import pytest

from spark_sklearn_tpu.parallel.taskgrid import build_fold_masks
from spark_sklearn_tpu.utils import native


@pytest.fixture(scope="module")
def splits():
    n = 997
    out = []
    for f in range(4):
        te = np.arange(f * 200, min(n, (f + 1) * 200))
        tr = np.setdiff1d(np.arange(n), te)
        out.append((tr, te))
    return n, out


def test_fold_masks_matches_fallback(splits):
    n, sp = splits
    t1, s1 = native.fold_masks(sp, n)
    t2, s2 = build_fold_masks(sp, n)
    np.testing.assert_array_equal(t1, t2)
    np.testing.assert_array_equal(s1, s2)


def test_csr_to_dense_matches_scipy():
    import scipy.sparse as sp
    m = sp.random(500, 300, density=0.1, format="csr",
                  random_state=0).astype(np.float32)
    d = native.csr_to_dense(m.data, m.indices, m.indptr, m.shape)
    np.testing.assert_allclose(d, m.toarray())


def test_quantile_bin_monotone_and_bounded():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 8)).astype(np.float32)
    edges, codes = native.quantile_bin(X, 32)
    assert edges.shape == (8, 31)
    assert codes.shape == (2000, 8)
    assert codes.max() <= 31
    for f in range(8):
        order = np.argsort(X[:, f])
        assert np.all(np.diff(codes[order, f].astype(int)) >= 0)


def test_quantile_bin_rejects_out_of_range_n_bins():
    X = np.zeros((4, 2), np.float32)
    with pytest.raises(ValueError, match="n_bins"):
        native.quantile_bin(X, 257)
    with pytest.raises(ValueError, match="n_bins"):
        native.quantile_bin(X, 1)


def test_quantile_bin_roughly_balanced():
    rng = np.random.default_rng(1)
    X = rng.uniform(size=(4096, 1)).astype(np.float32)
    _, codes = native.quantile_bin(X, 16)
    counts = np.bincount(codes[:, 0], minlength=16)
    assert counts.min() > 4096 // 16 * 0.5


def test_native_flag_is_bool():
    assert native.native_available() in (True, False)
