"""Device-memory ledger (ISSUE 10).

Contracts under test:
  - `search_report["memory"]` renders exactly the pinned
    MEMORY_BLOCK_SCHEMA keys; with the ledger disabled
    (`TpuConfig(memory_ledger=False)`) the block is ABSENT, the rest
    of the report and `cv_results_` are byte-identical, and the
    process-global ledger is never touched (exact no-op);
  - the footprint model prices per-chunk bytes from abstract shapes
    (task-batched tiled masks, per-candidate dyn params, score
    outputs) and `width_cap` turns the HBM budget into a shard-
    multiple chunk-width ceiling;
  - a small `hbm_budget_bytes` makes `plan_geometry` plan narrower
    widths (capped flag set), the search completes with ZERO OOM
    bisections, and scores stay bit-exact vs the unconstrained run
    (widths are pure geometry);
  - injected OOMs stamp modeled-vs-budget bytes onto the fault events,
    dump a flight bundle carrying the full ledger snapshot, and train
    the ledger's safety margin;
  - the telemetry snapshot / Prometheus exposition carry per-device
    memory series that agree with the searches' memory blocks;
  - tools: trace_summary digests the per-group `memory.footprint`
    instants and the ledger section of flight bundles; fleet_top
    prints the pressure line.
"""

import glob
import json
import os

import numpy as np
import pytest

import spark_sklearn_tpu as sst
from spark_sklearn_tpu.obs import memory as obs_memory
from spark_sklearn_tpu.obs.metrics import MEMORY_BLOCK_SCHEMA, schema_markdown
from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.parallel import memledger
from spark_sklearn_tpu.parallel.taskgrid import plan_geometry

from sklearn.linear_model import LogisticRegression
from sklearn.naive_bayes import GaussianNB

rng = np.random.RandomState(0)
X = rng.randn(96, 6).astype(np.float32)
y = (X[:, 0] + 0.25 * rng.randn(96) > 0).astype(np.int64)
GRID = {"C": np.logspace(-2, 1, 24).tolist()}
#: wide enough to chunk into several fused launches, so "oom@4" lands
#: on a steady-state fused chunk on any device count
GRID40 = {"C": np.logspace(-2, 1, 40).tolist()}


def small_search(param_grid=GRID, **cfg_kw):
    cfg = sst.TpuConfig(**cfg_kw)
    return sst.GridSearchCV(LogisticRegression(max_iter=10), param_grid,
                            cv=2, refit=False, backend="tpu", config=cfg)


@pytest.fixture(autouse=True)
def clean_ledger():
    """Every test starts and ends with a fresh process-global ledger —
    the safety margin is trained by OOM tests and must not leak into
    the width-ceiling assertions of later tests."""
    memledger.get_ledger().reset()
    yield memledger.get_ledger()
    memledger.get_ledger().reset()


# ---------------------------------------------------------------------------
# Footprint model + width cap units
# ---------------------------------------------------------------------------

class TestFootprintModel:
    def test_task_batched_breakdown(self):
        dyn = {"C": np.asarray([0.1, 1.0, 10.0], np.float32)}
        fp = memledger.model_group_footprint(
            dyn, width=8, n_folds=2, task_batched=True, n_samples=100,
            mask_itemsize=4, n_scorers=1, return_train=False,
            dtype_itemsize=4)
        # dyn: f32 repeated per fold = 8 bytes/candidate
        assert fp["dyn_bytes"] == 8 * 8
        # tiled masks: 2 folds x 100 samples x 4 bytes per candidate
        assert fp["mask_bytes"] == 8 * 2 * 100 * 4
        # outputs: per fold, one f32 score cell + one health byte
        assert fp["out_bytes"] == 8 * 2 * (4 + 1)
        assert fp["chunk_bytes"] == \
            fp["dyn_bytes"] + fp["mask_bytes"] + fp["out_bytes"]
        assert fp["per_candidate_bytes"] * 8 == fp["chunk_bytes"]

    def test_nested_family_no_mask_tile(self):
        dyn = {"var_smoothing": np.asarray([1e-9, 1e-8], np.float64)}
        fp = memledger.model_group_footprint(
            dyn, width=4, n_folds=3, task_batched=False, n_samples=50,
            n_scorers=2, return_train=True)
        assert fp["mask_bytes"] == 0          # base masks are resident
        assert fp["dyn_bytes"] == 4 * 8       # f64, no per-fold repeat
        # 3 folds x (2 scorers x 2 (train+test) x 4B + 1 health byte)
        assert fp["out_bytes"] == 4 * 3 * (2 * 2 * 4 + 1)

    def test_all_static_group_models_pad_operand(self):
        fp = memledger.model_group_footprint(
            {}, width=16, n_folds=2, task_batched=False, n_samples=10,
            dtype_itemsize=4)
        assert fp["dyn_bytes"] == 16 * 4      # the `_pad` axis operand

    def test_width_cap_math(self):
        # 10_000 budget, 1_000 resident, 100 B/candidate -> 90 -> 88
        # at shard multiple 8
        assert memledger.width_cap(10_000, 1_000, 100, 8, 512) == 88
        # no budget -> no cap; zero slope -> no cap
        assert memledger.width_cap(0, 0, 100, 8, 512) is None
        assert memledger.width_cap(10_000, 0, 0, 8, 512) is None
        # never below the shard count, never above the task cap
        assert memledger.width_cap(100, 0, 1_000, 8, 512) == 8
        assert memledger.width_cap(10 ** 12, 0, 1, 8, 512) == 512
        # the margin scales BOTH resident and slope down
        assert memledger.width_cap(10_000, 1_000, 100, 8, 512,
                                   margin=2.0) == 40

    def test_observe_oom_trains_margin(self, clean_ledger):
        ledger = clean_ledger
        assert ledger.safety_margin == 1.0
        # model said 8_000 fits in 10_000 and it OOMed: margin covers
        # at least the implied underestimate
        m = ledger.observe_oom(8_000, 10_000)
        assert m == pytest.approx(1.25 * 10_000 / 8_000)
        # budget-less OOM: multiplicative nudge, bounded
        for _ in range(20):
            m = ledger.observe_oom(0, 0)
        assert m <= 8.0
        assert ledger.counters()["n_oom"] == 21


class TestPlanGeometryCaps:
    def test_auto_mode_caps_and_flags(self):
        geo = plan_geometry([100], [None], 2, 1, 512,
                            overhead_override=0.05,
                            lane_cost_override=1e-3,
                            width_caps=[16])
        assert geo.groups[0].width == 16 and geo.groups[0].capped
        free = plan_geometry([100], [None], 2, 1, 512,
                             overhead_override=0.05,
                             lane_cost_override=1e-3)
        assert free.groups[0].width > 16 and not free.groups[0].capped

    def test_fixed_and_sorted_modes_respect_cap(self):
        fixed = plan_geometry([100], [None], 2, 1, 512, mode="fixed",
                              width_caps=[32])
        assert fixed.groups[0].width == 32
        graded = plan_geometry([100], [64], 2, 1, 512, width_caps=[16])
        assert graded.groups[0].width == 16 and graded.groups[0].sorted

    def test_cap_normalizes_to_shard_multiple(self):
        geo = plan_geometry([100], [None], 2, 8, 512,
                            overhead_override=0.05,
                            lane_cost_override=1e-3,
                            width_caps=[21])
        assert geo.groups[0].width == 16       # 21 -> 16 at multiple 8

    def test_preferred_width_respects_cap(self):
        geo = plan_geometry([100], [None], 2, 1, 512,
                            cost_model=None, width_caps=[16],
                            preferred=[64])
        assert geo.groups[0].width <= 16

    def test_cap_joins_plan_cache_key(self):
        kw = dict(sizes=[48], sorted_caps=[None], n_folds=2,
                  n_task_shards=1, max_width=512,
                  overhead_override=0.05, lane_cost_override=1e-3)
        a = plan_geometry(reuse=True, **kw)
        b = plan_geometry(reuse=True, width_caps=[8], **kw)
        assert a.widths() != b.widths()


# ---------------------------------------------------------------------------
# search_report["memory"]: schema pin + ledger-off parity
# ---------------------------------------------------------------------------

class TestMemoryBlock:
    def test_block_keys_match_pinned_schema(self):
        gs = small_search().fit(X, y)
        mem = gs.search_report["memory"]
        assert list(mem) == [d.name for d in MEMORY_BLOCK_SCHEMA]
        assert mem["enabled"] is True
        assert mem["peak_modeled_bytes"] > mem["resident_bytes"] > 0
        g0 = mem["groups"][0]
        for k in ("group", "width", "capped", "resident_bytes",
                  "dyn_bytes", "mask_bytes", "out_bytes",
                  "per_candidate_bytes", "chunk_bytes"):
            assert k in g0, g0
        assert mem["n_samples"] >= 1

    def test_schema_markdown_documents_memory_block(self):
        md = schema_markdown()
        assert 'search_report["memory"]' in md
        for d in MEMORY_BLOCK_SCHEMA:
            assert f"`{d.name}`" in md

    def test_ledger_off_is_absent_and_byte_identical(self):
        on = small_search().fit(X, y)
        off = small_search(memory_ledger=False).fit(X, y)
        assert "memory" in on.search_report
        assert "memory" not in off.search_report
        # the rest of the report keeps the same shape, and scores are
        # byte-identical (the ledger never touches math)
        assert set(on.search_report) - set(off.search_report) == \
            {"memory"}
        for k in on.cv_results_:
            if "time" in k or k == "params":
                continue
            np.testing.assert_array_equal(
                np.asarray(on.cv_results_[k]),
                np.asarray(off.cv_results_[k]), err_msg=k)

    def test_ledger_off_exact_noop(self, clean_ledger):
        before = clean_ledger.counters()
        small_search(memory_ledger=False).fit(X, y)
        assert clean_ledger.counters() == before
        assert not clean_ledger.active
        assert clean_ledger.snapshot()["groups"] == []

    def test_halving_memory_block_namespaces_rungs(self):
        hs = sst.HalvingGridSearchCV(
            GaussianNB(),
            {"var_smoothing": np.logspace(-9, -5, 24).tolist()},
            cv=2, factor=3, random_state=7, backend="tpu")
        hs.fit(X, y)
        mem = hs.search_report["memory"]
        rungs = {str(g["group"]).split(":")[0]
                 for g in mem["groups"] if ":" in str(g["group"])}
        assert {"r0", "r1", "r2"} <= rungs, mem["groups"]


# ---------------------------------------------------------------------------
# The HBM width ceiling
# ---------------------------------------------------------------------------

class TestWidthCeiling:
    def test_low_budget_narrows_widths_exact_parity(self):
        base = small_search().fit(X, y)
        capped = small_search(hbm_budget_bytes=12_000).fit(X, y)
        wb = [g["width"] for g in
              base.search_report["geometry"]["groups"]]
        wc = [g["width"] for g in
              capped.search_report["geometry"]["groups"]]
        assert wc < wb
        assert any(g["capped"] for g in
                   capped.search_report["geometry"]["groups"])
        mem = capped.search_report["memory"]
        assert mem["budget_bytes"] == 12_000
        # every planned chunk's modeled footprint fits the budget
        assert all(g["chunk_bytes"] + g["resident_bytes"] <= 12_000
                   for g in mem["groups"])
        # the ceiling made bisection unnecessary, and scores are exact
        f = capped.search_report["faults"]
        assert f["bisections"] == 0 and \
            f["by_class"].get("oom", 0) == 0
        for k in base.cv_results_:
            if "time" in k or k == "params":
                continue
            np.testing.assert_array_equal(
                np.asarray(base.cv_results_[k]),
                np.asarray(capped.cv_results_[k]), err_msg=k)

    def test_budget_resolution_precedence(self, monkeypatch):
        monkeypatch.setenv("SST_HBM_BUDGET_BYTES", "5000")
        assert obs_memory.resolve_hbm_budget(None) == 5000
        assert obs_memory.resolve_hbm_budget(
            sst.TpuConfig(hbm_budget_bytes=7000)) == 7000
        assert obs_memory.resolve_hbm_budget(
            sst.TpuConfig(hbm_budget_bytes=0)) == 0
        monkeypatch.setenv("SST_HBM_BUDGET_BYTES", "junk")
        assert obs_memory.resolve_hbm_budget(None) == \
            obs_memory.resolve_hbm_budget(sst.TpuConfig())

    def test_detected_memory_fraction_default(self):
        stats = [{"measured": True, "bytes_limit": 10 ** 9,
                  "bytes_in_use": 0},
                 {"measured": True, "bytes_limit": 2 * 10 ** 9,
                  "bytes_in_use": 0}]
        assert obs_memory.detect_device_memory_bytes(stats) == 10 ** 9
        assert obs_memory.resolve_hbm_budget(
            sst.TpuConfig(), stats=stats) == int(
                10 ** 9 * obs_memory.DEFAULT_HBM_FRACTION)
        # unmeasured fleet (XLA:CPU): no ceiling by default
        assert obs_memory.resolve_hbm_budget(
            sst.TpuConfig(), stats=[{"measured": False,
                                     "bytes_limit": 0}]) == 0


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

class TestOomForensics:
    def test_oom_events_and_bundle_carry_ledger(self, tmp_path,
                                                clean_ledger):
        base = small_search(GRID40).fit(X, y)
        gs = small_search(GRID40, fault_plan="oom@4",
                          retry_backoff_s=0.01,
                          flight_dir=str(tmp_path)).fit(X, y)
        np.testing.assert_array_equal(
            base.cv_results_["mean_test_score"],
            gs.cv_results_["mean_test_score"])
        ev = [e for e in gs.search_report["faults"]["events"]
              if e["class"] == "oom"]
        assert ev
        for e in ev:
            assert e["modeled_bytes"] > 0 and "budget_bytes" in e, e
        # the first OOM trained the safety margin once (dedup per
        # chunk: the bisect/host actions share the recover's training)
        assert gs.search_report["memory"]["safety_margin"] > 1.0
        assert clean_ledger.counters()["n_oom"] == 1
        bundles = glob.glob(str(tmp_path / "flight-oom-*.json"))
        assert bundles
        bundle = json.load(open(bundles[0]))
        assert bundle["memory"]["groups"], sorted(bundle)
        assert bundle["memory"]["modeled_peak_bytes"] > 0
        assert bundle["context"]["modeled_bytes"] > 0


# ---------------------------------------------------------------------------
# Telemetry agreement + exposition
# ---------------------------------------------------------------------------

class TestTelemetryMemory:
    def test_snapshot_agrees_with_search_block(self):
        from spark_sklearn_tpu.obs import telemetry as tel
        svc = tel.get_telemetry()
        cfg = sst.TpuConfig(telemetry_port=0, telemetry_interval_s=0.05)
        sess = sst.createLocalTpuSession("mem-tel-test", config=cfg)
        try:
            fut = sess.submit(small_search(telemetry_port=0), X, y)
            res = fut.result(timeout=300)
            sess.telemetry.sample_once()
            snap = sess.telemetry_snapshot()
            mem = snap["memory"]
            assert mem["modeled_peak_bytes"] >= \
                res.search_report["memory"]["peak_modeled_bytes"]
            assert mem["safety_margin"] == \
                res.search_report["memory"]["safety_margin"]
            assert mem["measured"] == \
                res.search_report["memory"]["measured"]
            assert "devices" in mem and "pressure_frac_max" in mem
            assert mem["pressure_window"], mem
        finally:
            sess.stop()
        assert not svc.enabled

    def test_prometheus_memory_families(self):
        snap = {
            "enabled": True, "window_s": 120.0, "n_samples": 3,
            "memory": {
                "measured": True, "watermark_bytes": 123,
                "modeled_peak_bytes": 456, "safety_margin": 1.5,
                "n_oom_observed": 2, "pressure_frac_max": 0.5,
                "devices": {"0": {"bytes_in_use": 100,
                                  "bytes_limit": 200,
                                  "pressure_frac": 0.5}}},
        }
        from spark_sklearn_tpu.obs.fleet import (
            METRIC_LINE_RE, prometheus_text)
        body = prometheus_text(snap)
        assert 'sst_memory_device_bytes_in_use{device="0"} 100' in body
        assert "sst_memory_modeled_peak_bytes 456" in body
        assert "sst_memory_safety_margin 1.5" in body
        assert "sst_memory_oom_observed_total 2" in body
        bad = [ln for ln in body.splitlines()
               if ln and not ln.startswith("#")
               and not METRIC_LINE_RE.match(ln)]
        assert not bad, bad[:5]


# ---------------------------------------------------------------------------
# Tools: trace digest + fleet_top
# ---------------------------------------------------------------------------

class TestTools:
    def test_trace_summary_memory_digest(self, tmp_path):
        from tools import trace_summary
        path = str(tmp_path / "trace.json")
        small_search(trace=path).fit(X, y)
        events = trace_summary.load_events(path)
        s = trace_summary.summarize(events)
        mem = s["memory"]
        assert mem["per_group_peak_modeled_bytes"], mem
        assert mem["n_samples"] >= 1
        text = trace_summary.format_summary(s)
        assert "memory: peak modeled footprint per compile group" \
            in text
        # no unknown-name warnings for the new span vocabulary
        assert not [n for n in s["unknown_names"]
                    if n.startswith("memory")]

    def test_trace_summary_digests_bundle_ledger(self, tmp_path):
        from tools import trace_summary
        gs = small_search(GRID40, fault_plan="oom@4",
                          retry_backoff_s=0.01,
                          flight_dir=str(tmp_path), trace=True)
        gs.fit(X, y)
        bundle = glob.glob(str(tmp_path / "flight-oom-*.json"))[0]
        assert trace_summary.load_bundle_memory(bundle)["groups"]
        rc = trace_summary.main([bundle])
        assert rc == 0

    def test_fleet_top_memory_line(self):
        from tools.fleet_top import format_snapshot
        snap = {
            "enabled": True, "window_s": 120.0, "n_samples": 1,
            "tenants": {"alpha": {"dispatches_total": 1,
                                  "tasks_total": 4,
                                  "residency_bytes": 2048}},
            "memory": {"measured": True, "modeled_peak_bytes": 10 ** 6,
                       "watermark_bytes": 5 * 10 ** 5,
                       "safety_margin": 1.25, "n_oom_observed": 1,
                       "devices": {"0": {"pressure_frac": 0.42}}},
        }
        text = format_snapshot(snap)
        assert "memory: modeled peak" in text
        assert "dev0=42.0%" in text
        assert "2.0 KiB" in text      # the tenant residency column

    def test_memory_sample_span_registered(self, tmp_path):
        from spark_sklearn_tpu.obs import spans
        assert spans.is_known_span("memory.sample")
        assert spans.is_known_span("memory.footprint")
        tracer = get_tracer()
        was = tracer.enabled
        if not was:
            tracer.enable()
        try:
            memledger.get_ledger().activate()
            memledger.note_launch_boundary()
            names = [e[1] for e in tracer.events()]
            assert "memory.sample" in names
        finally:
            memledger.get_ledger().deactivate()
            if not was:
                tracer.disable()
