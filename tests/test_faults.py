"""Fault-tolerant launch supervisor (parallel/faults.py).

The contract under test (ISSUE 3): transient device errors retry with
backoff and leave `cv_results_` EXACT-equal to a fault-free run; OOM
chunks bisect (re-padded relaunch, still exact) and bottom out into
per-candidate host execution; hung launches fail the search with a
clean TimeoutError naming the chunk/compile group while completed
chunks stay resumable; fatal errors propagate unchanged.  All of it is
driven by the deterministic fault-injection plan on CPU — identical at
every pipeline depth — and every recovery is visible in
`search_report["faults"]`.
"""

import json
import os
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import spark_sklearn_tpu as sst
from spark_sklearn_tpu.parallel import faults
from spark_sklearn_tpu.parallel.faults import (
    FATAL,
    HUNG,
    OOM,
    TRANSIENT,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    LaunchSupervisor,
    LaunchTimeoutError,
    classify_error,
    register_classifier,
)


def _data():
    rng = np.random.RandomState(0)
    X = rng.randn(96, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    return X, y


def _grid():
    return {"C": np.logspace(-2, 1, 40).tolist()}


def _fit(X, y, config=None, scoring=None, return_train_score=False,
         backend="tpu"):
    from sklearn.linear_model import LogisticRegression
    return sst.GridSearchCV(
        LogisticRegression(max_iter=10), _grid(), cv=2, refit=False,
        backend=backend, scoring=scoring,
        return_train_score=return_train_score, config=config).fit(X, y)


def _non_time_results(gs):
    return {k: v for k, v in gs.cv_results_.items()
            if "time" not in k and k != "params"}


def _assert_exact_equal(ra, rb):
    assert set(ra) == set(rb)
    for k in ra:
        np.testing.assert_array_equal(
            np.asarray(ra[k]), np.asarray(rb[k]), err_msg=k)


@pytest.fixture(scope="module")
def baseline():
    X, y = _data()
    return _fit(X, y)


# ---------------------------------------------------------------------------
# Plan parsing + taxonomy
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_parse_string(self):
        plan = FaultPlan.parse("transient@3, OOM@5x2, hung@7")
        assert plan.specs == (
            FaultSpec(3, "transient", 1), FaultSpec(5, "oom", 2),
            FaultSpec(7, "hung", 1))
        assert plan.match(5, 0).fault_class == "oom"
        assert plan.match(5, 1).fault_class == "oom"
        assert plan.match(5, 2) is None
        assert plan.match(4, 0) is None

    def test_parse_structured(self):
        plan = FaultPlan.parse([(1, "transient"), (2, "fatal", 3),
                                {"index": 4, "class": "oom"}])
        assert len(plan) == 3
        assert plan.match(2, 2).count == 3

    def test_bad_tokens(self):
        with pytest.raises(ValueError, match="bad fault-plan token"):
            FaultPlan.parse("bogus@1")
        with pytest.raises(ValueError, match="bad fault-plan token"):
            FaultPlan.parse("transient#1")
        with pytest.raises(ValueError, match="duplicate"):
            FaultPlan.parse("transient@1,oom@1")
        with pytest.raises(ValueError, match="unknown fault class"):
            FaultPlan.parse([(1, "sideways")])

    def test_env_resolution(self, monkeypatch):
        monkeypatch.setenv("SST_FAULT_PLAN", "transient@9")
        plan = FaultPlan.resolve(None)
        assert plan.match(9, 0).fault_class == "transient"
        # an explicit config plan wins over the env
        plan = FaultPlan.resolve(sst.TpuConfig(fault_plan="oom@2"))
        assert plan.match(2, 0).fault_class == "oom"
        assert plan.match(9, 0) is None

    def test_session_validates_plan_early(self):
        with pytest.raises(ValueError, match="bad fault-plan token"):
            sst.TpuSession(sst.TpuConfig(fault_plan="garbage"))


class TestTaxonomy:
    def test_marker_classification(self):
        assert classify_error(
            RuntimeError("RESOURCE_EXHAUSTED: out of HBM")) == OOM
        assert classify_error(MemoryError()) == OOM
        assert classify_error(
            RuntimeError("UNAVAILABLE: socket closed")) == TRANSIENT
        assert classify_error(RuntimeError("ABORTED: retry")) == TRANSIENT
        assert classify_error(TypeError("bad arg")) == FATAL
        assert classify_error(ValueError("nope")) == FATAL

    def test_injected_and_timeout(self):
        assert classify_error(InjectedFault("transient", "x")) == TRANSIENT
        assert classify_error(InjectedFault("oom_deep", "x")) == OOM
        err = LaunchTimeoutError("0:0:8", 0, 1.5)
        assert classify_error(err) == HUNG
        assert isinstance(err, TimeoutError)
        assert "0:0:8" in str(err) and "compile group 0" in str(err)
        # no silent host re-run for a hung device
        assert err._sst_no_fallback

    def test_custom_classifier_extension(self):
        class WeirdBackendError(Exception):
            pass

        def classify(exc):
            return TRANSIENT if isinstance(exc, WeirdBackendError) else None

        register_classifier(classify)
        try:
            assert classify_error(WeirdBackendError()) == TRANSIENT
            # other errors still hit the built-in rules
            assert classify_error(TypeError()) == FATAL
        finally:
            faults._CUSTOM_CLASSIFIERS.remove(classify)


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------


class TestWatchdog:
    def test_timeout_raises_named_error(self, monkeypatch):
        monkeypatch.setattr(faults, "_block_until_ready",
                            lambda out: time.sleep(5.0) or out)
        sup = LaunchSupervisor(sst.TpuConfig(launch_timeout_s=0.2))
        t0 = time.perf_counter()
        with pytest.raises(LaunchTimeoutError) as ei:
            sup.wait_ready(object(), key="2:0:8", group=2)
        assert time.perf_counter() - t0 < 2.0
        assert "2:0:8" in str(ei.value)
        assert "compile group 2" in str(ei.value)

    def test_fast_wait_passes_through(self):
        sup = LaunchSupervisor(sst.TpuConfig(launch_timeout_s=5.0))
        obj = (1, "x")
        assert sup.wait_ready(obj, key="k") == obj

    def test_blocker_exception_reraised(self, monkeypatch):
        def boom(out):
            raise RuntimeError("UNAVAILABLE: flaky")
        monkeypatch.setattr(faults, "_block_until_ready", boom)
        sup = LaunchSupervisor(sst.TpuConfig(launch_timeout_s=5.0))
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            sup.wait_ready(object(), key="k")

    def test_no_timeout_is_plain_wait(self):
        sup = LaunchSupervisor(sst.TpuConfig())
        obj = object()
        assert sup.wait_ready(obj, key="k") is obj


# ---------------------------------------------------------------------------
# End-to-end injection: the acceptance drills
# ---------------------------------------------------------------------------

# launch order for the 40-candidate sorted logreg grid: fit(0),
# score(1), calibrate(2), then fused steady-state chunks (3+) on any
# device count — so 4 and 6 always name fused chunks
_PLAN = "transient@4,oom@6"


class TestInjectionRecovery:
    @pytest.mark.parametrize("depth", [2, 0])
    def test_transient_and_oom_recover_exact(self, baseline, depth):
        """The acceptance criterion: one TRANSIENT + one OOM injected at
        fixed launch indices; fit completes, faults counters show the
        recovery, and cv_results_ is EXACT-equal to the fault-free run
        — in the pipelined mode AND the synchronous escape hatch."""
        X, y = _data()
        cfg = sst.TpuConfig(fault_plan=_PLAN, retry_backoff_s=0.01,
                            pipeline_depth=depth)
        gs = _fit(X, y, config=cfg)
        f = gs.search_report["faults"]
        assert f["retries"] >= 1, f
        assert f["bisections"] >= 1, f
        assert f["injected"] >= 2, f
        assert f["by_class"].get("transient", 0) >= 1
        assert f["by_class"].get("oom", 0) >= 1
        _assert_exact_equal(_non_time_results(baseline),
                            _non_time_results(gs))

    def test_multimetric_train_scores_recover_exact(self):
        """Bisection must merge multi-scorer test AND train cells."""
        X, y = _data()
        kw = dict(scoring=["accuracy", "neg_log_loss"],
                  return_train_score=True)
        clean = _fit(X, y, **kw)
        cfg = sst.TpuConfig(fault_plan=_PLAN, retry_backoff_s=0.01)
        gs = _fit(X, y, config=cfg, **kw)
        assert gs.search_report["faults"]["bisections"] >= 1
        _assert_exact_equal(_non_time_results(clean),
                            _non_time_results(gs))

    def test_first_chunk_oom_goes_to_host(self, baseline):
        """OOM on the fit launch (no bisect hook): the whole chunk
        degrades to per-candidate host execution; the score launch
        consumes the stashed cells instead of launching."""
        X, y = _data()
        cfg = sst.TpuConfig(fault_plan="oom@0", retry_backoff_s=0.01)
        gs = _fit(X, y, config=cfg)
        f = gs.search_report["faults"]
        assert f["host_fallbacks"] >= 1, f
        assert np.all(np.isfinite(gs.cv_results_["mean_test_score"]))
        # host cells are sklearn's own float64 answers — tolerance, not
        # bitwise, against the compiled fault-free run
        np.testing.assert_allclose(
            baseline.cv_results_["mean_test_score"],
            gs.cv_results_["mean_test_score"], atol=1e-4)

    def test_oom_deep_bottoms_out_to_host(self, baseline):
        """Sticky OOM re-fails every bisected sub-range: the recursion
        deterministically reaches single candidates and runs them on
        the host with sklearn error_score semantics."""
        X, y = _data()
        cfg = sst.TpuConfig(fault_plan="oom_deep@5", retry_backoff_s=0.01)
        gs = _fit(X, y, config=cfg)
        f = gs.search_report["faults"]
        assert f["bisections"] >= 1, f
        assert f["host_fallbacks"] >= 2, f
        np.testing.assert_allclose(
            baseline.cv_results_["mean_test_score"],
            gs.cv_results_["mean_test_score"], atol=1e-4)

    def test_retry_budget_exhaustion_raises(self):
        X, y = _data()
        cfg = sst.TpuConfig(fault_plan="transient@4x5",
                            max_launch_retries=2, retry_backoff_s=0.01)
        with pytest.raises(InjectedFault):
            _fit(X, y, config=cfg)

    def test_fatal_propagates_compiled(self):
        X, y = _data()
        cfg = sst.TpuConfig(fault_plan="fatal@1")
        with pytest.raises(InjectedFault):
            _fit(X, y, config=cfg)

    def test_fatal_falls_back_to_host_and_records_cause(self):
        """backend=None keeps today's compiled->host fallback for fatal
        errors; the host report's faults block names the cause."""
        X, y = _data()
        cfg = sst.TpuConfig(fault_plan="fatal@1")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            gs = _fit(X, y, config=cfg, backend=None)
        assert gs.search_report["backend"] == "host"
        assert "InjectedFault" in \
            gs.search_report["faults"]["fallback_exception"]

    def test_clean_run_reports_zeroed_faults(self, baseline):
        f = baseline.search_report["faults"]
        assert f["retries"] == 0 and f["bisections"] == 0
        assert f["host_fallbacks"] == 0 and f["timeouts"] == 0
        assert f["injected"] == 0 and f["events"] == []

    def test_hung_fails_clean_and_resumes(self, baseline, tmp_path):
        """A hung launch fails the search with a TimeoutError naming
        the chunk/compile group; chunks finalized before it are durable
        and a resume completes exact-equal to the fault-free run."""
        X, y = _data()
        cfg = sst.TpuConfig(fault_plan="hung@5", launch_timeout_s=30.0,
                            checkpoint_dir=str(tmp_path))
        with pytest.raises(TimeoutError) as ei:
            _fit(X, y, config=cfg)
        assert "compile group 0" in str(ei.value)
        assert ei.value.key in str(ei.value)
        # the fault was journaled durably before the failure
        ckpt_file = [p for p in os.listdir(tmp_path)
                     if p.endswith(".jsonl")][0]
        lines = [json.loads(ln) for ln in
                 open(tmp_path / ckpt_file).read().splitlines()]
        assert any("fault_chunk_id" in rec for rec in lines)
        assert sum("chunk_id" in rec for rec in lines) >= 1

        resumed = _fit(X, y, config=sst.TpuConfig(
            checkpoint_dir=str(tmp_path)))
        assert resumed.search_report["n_chunks_resumed"] >= 1
        assert resumed.search_report["faults"]["timeouts"] == 0
        _assert_exact_equal(_non_time_results(baseline),
                            _non_time_results(resumed))

    def test_keyboard_interrupt_never_falls_back(self, monkeypatch):
        """The narrowed dispatch guard: an interactive abort propagates
        instead of silently re-running the grid on the host."""
        X, y = _data()
        from spark_sklearn_tpu.search.grid import BaseSearchTPU

        def boom(self, *a, **kw):
            raise KeyboardInterrupt

        monkeypatch.setattr(BaseSearchTPU, "_fit_compiled", boom)
        with pytest.raises(KeyboardInterrupt):
            _fit(X, y, backend=None)


# ---------------------------------------------------------------------------
# Checkpoint satellites: atomic npz + fault journal
# ---------------------------------------------------------------------------


class TestCheckpointAtomicity:
    def _tree(self):
        return {"coef": np.arange(6.0).reshape(2, 3),
                "intercept": np.ones(2)}

    def test_save_is_atomic_and_leaves_no_temp(self, tmp_path):
        from spark_sklearn_tpu.utils.checkpoint import (load_pytree,
                                                        save_pytree)
        p = str(tmp_path / "m.npz")
        save_pytree(p, self._tree())
        assert os.listdir(tmp_path) == ["m.npz"]
        back = load_pytree(p, like=self._tree())
        np.testing.assert_allclose(back["coef"], self._tree()["coef"])
        # extension-less path keeps numpy's ".npz" append behavior —
        # and load mirrors the normalization, so a journal pointer
        # saved without the extension (the prefix payload path)
        # round-trips to the file save actually wrote
        save_pytree(str(tmp_path / "bare"), self._tree())
        assert (tmp_path / "bare.npz").exists()
        back = load_pytree(str(tmp_path / "bare"), like=self._tree())
        np.testing.assert_allclose(back["coef"], self._tree()["coef"])

    def test_truncated_npz_fails_loud_and_resaves_clean(self, tmp_path):
        """A crash mid-save must never poison the next resume: the
        truncated-archive failure mode raises cleanly on load, and an
        atomic re-save over it restores a loadable file."""
        from spark_sklearn_tpu.utils.checkpoint import (load_pytree,
                                                        save_pytree)
        p = str(tmp_path / "m.npz")
        save_pytree(p, self._tree())
        size = os.path.getsize(p)
        with open(p, "r+b") as f:          # simulate the torn write
            f.truncate(size // 2)
        with pytest.raises(Exception):
            load_pytree(p, like=self._tree())
        save_pytree(p, self._tree())        # os.replace over the wreck
        back = load_pytree(p, like=self._tree())
        np.testing.assert_allclose(back["intercept"], np.ones(2))

    def test_fault_journal_never_masquerades_as_chunk(self, tmp_path):
        from spark_sklearn_tpu.utils.checkpoint import SearchCheckpoint
        ck = SearchCheckpoint(str(tmp_path), "k1")
        ck.put("0:0:8", {"test": {"score": [[1.0]]}})
        ck.note_fault("0:8:16", {"class": "transient", "attempt": 1})
        assert ck.n_done == 1
        re = SearchCheckpoint(str(tmp_path), "k1")
        assert re.n_done == 1
        assert re.get("0:8:16") is None
        assert len(re.faults) == 1
        assert re.faults[0]["class"] == "transient"


# ---------------------------------------------------------------------------
# Multihost satellite: per-worker deadline, straggler reaping, blame
# ---------------------------------------------------------------------------


class TestMultihostWait:
    def _proc(self, code):
        return subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    def test_straggler_killed_and_named(self):
        from spark_sklearn_tpu.utils.multihost import _wait_procs
        procs = [self._proc("print('ok')"),
                 self._proc("import time; time.sleep(60)")]
        t0 = time.perf_counter()
        outs, failed, timed_out = _wait_procs(procs, timeout_s=3.0)
        assert time.perf_counter() - t0 < 45
        assert timed_out == [1]
        assert failed == []
        assert "ok" in outs[0]
        assert "<killed" in outs[1]
        assert procs[1].poll() is not None   # reaped, not leaked

    def test_failure_fast_kills_peers_and_blames_index(self):
        from spark_sklearn_tpu.utils.multihost import _wait_procs
        procs = [self._proc("import sys; sys.exit(3)"),
                 self._proc("import time; time.sleep(60)")]
        t0 = time.perf_counter()
        outs, failed, timed_out = _wait_procs(
            procs, timeout_s=120.0, grace_s=1.0)
        # the sleeper was killed on the 1s grace, not the 120s budget
        assert time.perf_counter() - t0 < 45
        assert failed == [0]
        assert timed_out == [1]
