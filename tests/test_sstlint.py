"""sstlint's own suite: fixture trees per rule (positive + negative +
suppression), baseline round-trip, the runtime lock-order recorder,
and the real-tree gate (the package must lint clean)."""

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools.sstlint import Project, run_lint, save_baseline  # noqa: E402
from tools.sstlint.core import load_baseline  # noqa: E402


def make_project(root: Path, **kw) -> Project:
    pkg = root / "pkg"
    pkg.mkdir(parents=True, exist_ok=True)
    defaults = dict(root=root, package=pkg)
    defaults.update(kw)
    return Project(**defaults)


def write(root: Path, rel: str, text: str) -> Path:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(text)
    return p


def lint(project, rules):
    return run_lint(project, rules=rules,
                    baseline_path=project.root / "baseline.json")


def rule_hits(result, rule):
    return [f for f in result["findings"] if f["rule"] == rule]


# ---------------------------------------------------------------------------
# exception hygiene
# ---------------------------------------------------------------------------


class TestExceptRules:
    def test_bare_except_flagged_and_suppressed(self, tmp_path):
        write(tmp_path, "pkg/a.py", (
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except:\n"
            "        return None\n"
            "def g():\n"
            "    try:\n"
            "        work()\n"
            "    # justified: legacy shim\n"
            "    # sstlint: disable=bare-except\n"
            "    except:\n"
            "        return None\n"
            "def h():\n"
            "    try:\n"
            "        work()\n"
            "    except ValueError:\n"
            "        return None\n"))
        r = lint(make_project(tmp_path), ["bare-except"])
        hits = rule_hits(r, "bare-except")
        assert len(hits) == 1 and hits[0]["line"] == 4

    def test_broad_baseexception_requires_reraise(self, tmp_path):
        write(tmp_path, "pkg/a.py", (
            "def bad():\n"
            "    try:\n"
            "        work()\n"
            "    except BaseException as exc:\n"
            "        log(exc)\n"
            "def ok():\n"
            "    try:\n"
            "        work()\n"
            "    except BaseException:\n"
            "        raise\n"))
        r = lint(make_project(tmp_path), ["broad-except-swallow"])
        hits = rule_hits(r, "broad-except-swallow")
        assert len(hits) == 1 and hits[0]["line"] == 4

    def test_swallowed_exception(self, tmp_path):
        write(tmp_path, "pkg/a.py", (
            "import warnings\n"
            "def bad():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass\n"
            "def ok_logs():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception as exc:\n"
            "        warnings.warn(f'fallback: {exc}')\n"))
        r = lint(make_project(tmp_path), ["swallowed-exception"])
        hits = rule_hits(r, "swallowed-exception")
        assert len(hits) == 1 and hits[0]["line"] == 5

    def test_raise_without_cause(self, tmp_path):
        write(tmp_path, "pkg/a.py", (
            "def bad():\n"
            "    try:\n"
            "        work()\n"
            "    except ValueError as exc:\n"
            "        raise RuntimeError('translated')\n"
            "def ok():\n"
            "    try:\n"
            "        work()\n"
            "    except ValueError as exc:\n"
            "        raise RuntimeError('translated') from exc\n"))
        r = lint(make_project(tmp_path), ["raise-without-cause"])
        hits = rule_hits(r, "raise-without-cause")
        assert len(hits) == 1 and hits[0]["line"] == 5

    def test_launch_taxonomy(self, tmp_path):
        write(tmp_path, "pkg/launchy.py", (
            "def classify_error(e):\n"
            "    return 'fatal'\n"
            "def bad_handler():\n"
            "    try:\n"
            "        launch()\n"
            "    except Exception as exc:\n"
            "        return None\n"
            "def ok_handler():\n"
            "    try:\n"
            "        launch()\n"
            "    except Exception as exc:\n"
            "        if classify_error(exc) == 'fatal':\n"
            "            raise\n"))
        proj = make_project(tmp_path, launch_paths=("launchy.py",))
        r = lint(proj, ["launch-except-taxonomy"])
        hits = rule_hits(r, "launch-except-taxonomy")
        assert len(hits) == 1 and hits[0]["line"] == 6


# ---------------------------------------------------------------------------
# lock order / shared state
# ---------------------------------------------------------------------------


class TestLockRules:
    def test_lock_order_cycle(self, tmp_path):
        write(tmp_path, "pkg/locksmod.py", (
            "A = named_lock('m.A')\n"
            "B = named_lock('m.B')\n"
            "def one():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
            "def two():\n"
            "    with B:\n"
            "        with A:\n"
            "            pass\n"))
        r = lint(make_project(tmp_path), ["lock-order-cycle"])
        assert rule_hits(r, "lock-order-cycle")

    def test_consistent_order_clean(self, tmp_path):
        write(tmp_path, "pkg/locksmod.py", (
            "A = named_lock('m.A')\n"
            "B = named_lock('m.B')\n"
            "def one():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
            "def two():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"))
        r = lint(make_project(tmp_path), ["lock-order-cycle"])
        assert not rule_hits(r, "lock-order-cycle")

    def test_deferred_callback_is_not_under_the_lock(self, tmp_path):
        # a callback DEFINED under lock A runs in whatever frame later
        # invokes it: acquiring B in its body is no A->B edge, and a
        # shared-state mutation in its body is NOT guarded by A
        from tools.sstlint.project import SharedState
        write(tmp_path, "pkg/locksmod.py", (
            "A = named_lock('m.A')\n"
            "B = named_lock('m.B')\n"
            "TOTALS = {'n': 0}\n"
            "def install():\n"
            "    with A:\n"
            "        def cb():\n"
            "            with B:\n"
            "                pass\n"
            "        register(cb)\n"
            "def other():\n"
            "    with B:\n"
            "        with A:\n"
            "            pass\n"
            "def install2():\n"
            "    with A:\n"
            "        def cb2():\n"
            "            TOTALS['n'] += 1\n"
            "        register(cb2)\n"))
        proj = make_project(tmp_path, shared_state=(
            SharedState("locksmod.py", "m.A", name="TOTALS"),))
        r = lint(proj, ["lock-order-cycle", "unlocked-shared-mutation"])
        # no false A->B edge from cb, so B->A in other() is no cycle
        assert not rule_hits(r, "lock-order-cycle")
        # and cb2's mutation is correctly seen as unguarded
        assert [f["line"] for f in
                rule_hits(r, "unlocked-shared-mutation")] == [17]

    def test_cross_module_lock_including_call_through(self, tmp_path):
        # nested with across module prefixes, via a one-hop call
        write(tmp_path, "pkg/other.py", (
            "L2 = named_lock('other.L2')\n"
            "def locked_op():\n"
            "    with L2:\n"
            "        pass\n"))
        write(tmp_path, "pkg/main.py", (
            "from pkg.other import locked_op\n"
            "L1 = named_lock('main.L1')\n"
            "def f():\n"
            "    with L1:\n"
            "        locked_op()\n"))
        proj = make_project(tmp_path)
        r = lint(proj, ["cross-module-lock"])
        hits = rule_hits(r, "cross-module-lock")
        assert len(hits) == 1
        assert "other.L2" in hits[0]["message"]
        # the allowlist silences the pair
        proj2 = make_project(tmp_path,
                             allowed_cross_module=(("main", "other"),))
        r2 = lint(proj2, ["cross-module-lock"])
        assert not rule_hits(r2, "cross-module-lock")

    def test_unlocked_shared_mutation(self, tmp_path):
        from tools.sstlint.project import SharedState
        write(tmp_path, "pkg/state.py", (
            "TOTALS = {'bytes': 0}\n"
            "LOCK = named_lock('state.LOCK')\n"
            "def bad(n):\n"
            "    TOTALS['bytes'] += n\n"
            "def good(n):\n"
            "    with LOCK:\n"
            "        TOTALS['bytes'] += n\n"
            "def bad_taint(plan, cid):\n"
            "    done = plan.setdefault('staged_ids', set())\n"
            "    done.add(cid)\n"
            "def good_taint(plan, cid):\n"
            "    done = plan.setdefault('staged_ids', set())\n"
            "    with LOCK:\n"
            "        done.add(cid)\n"))
        proj = make_project(tmp_path, shared_state=(
            SharedState("state.py", "state.LOCK", name="TOTALS"),
            SharedState("state.py", "state.LOCK",
                        taint_key="staged_ids"),
        ))
        r = lint(proj, ["unlocked-shared-mutation"])
        lines = sorted(f["line"] for f in
                       rule_hits(r, "unlocked-shared-mutation"))
        assert lines == [4, 10]

    def test_unnamed_lock(self, tmp_path):
        write(tmp_path, "pkg/a.py", (
            "import threading\n"
            "GOOD = named_lock('a.GOOD')\n"
            "BAD = threading.Lock()\n"))
        r = lint(make_project(tmp_path), ["unnamed-lock"])
        hits = rule_hits(r, "unnamed-lock")
        assert len(hits) == 1 and hits[0]["line"] == 3


# ---------------------------------------------------------------------------
# spans + schema + docs
# ---------------------------------------------------------------------------

_FIXTURE_SPANS = (
    "KNOWN = {'stage', 'dispatch'}\n"
    "ASYNC = ('launch',)\n"
    "def known_span_names():\n"
    "    return frozenset(KNOWN)\n"
    "def async_prefix(name):\n"
    "    for p in ASYNC:\n"
    "        if name == p or name.startswith(p + ' '):\n"
    "            return p\n"
    "    return None\n"
    "def is_known_span(name):\n"
    "    return name in KNOWN or async_prefix(name) is not None\n")


class TestSpanRules:
    def test_span_vocabulary(self, tmp_path):
        spans = write(tmp_path, "pkg/spans.py", _FIXTURE_SPANS)
        write(tmp_path, "pkg/a.py", (
            "def f(tracer, key):\n"
            "    with tracer.span('stage', key=key):\n"
            "        pass\n"
            "    with tracer.span('stag', key=key):\n"
            "        pass\n"
            "    tracer.record_async(f'launch {key}', 0, 1, track='t')\n"
            "    tracer.record_async(f'lunch {key}', 0, 1, track='t')\n"))
        proj = make_project(tmp_path, spans_path=spans)
        r = lint(proj, ["span-unknown-name"])
        syms = sorted(f["message"] for f in
                      rule_hits(r, "span-unknown-name"))
        assert len(syms) == 2
        assert any("'stag'" in s for s in syms)
        assert any("'lunch'" in s for s in syms)

    def test_span_context_manager(self, tmp_path):
        spans = write(tmp_path, "pkg/spans.py", _FIXTURE_SPANS)
        write(tmp_path, "pkg/a.py", (
            "def f(tracer):\n"
            "    s = tracer.span('stage')\n"
            "    s.__enter__()\n"
            "def g(tracer):\n"
            "    with tracer.span('stage'):\n"
            "        pass\n"))
        proj = make_project(tmp_path, spans_path=spans)
        r = lint(proj, ["span-not-context-managed"])
        hits = rule_hits(r, "span-not-context-managed")
        assert len(hits) == 1 and hits[0]["line"] == 2

    def test_schema_block_drift_both_directions(self, tmp_path):
        # schema misses a produced key ('extra') AND declares one
        # nothing produces ('missing') — the ISSUE's drift fixture
        metrics = write(tmp_path, "pkg/metrics.py", (
            "from collections import namedtuple\n"
            "MetricDef = namedtuple('MetricDef', 'name kind')\n"
            "DATAPLANE_BLOCK_SCHEMA = (\n"
            "    MetricDef('hits', 'counter'),\n"
            "    MetricDef('missing', 'gauge'),\n"
            ")\n"))
        write(tmp_path, "pkg/plane.py", (
            "def report_block(plane):\n"
            "    return {'hits': plane.hits, 'extra': 1}\n"))
        from tools.sstlint.project import BlockSpec, Producer
        proj = make_project(
            tmp_path, metrics_path=metrics,
            blocks=(BlockSpec("dataplane", "DATAPLANE_BLOCK_SCHEMA", (
                Producer("dict-keys", "plane.py", "report_block"),)),))
        r = lint(proj, ["schema-block-drift"])
        msgs = " | ".join(f["message"] for f in
                          rule_hits(r, "schema-block-drift"))
        assert "'extra'" in msgs and "'missing'" in msgs
        assert len(rule_hits(r, "schema-block-drift")) == 2

    def test_report_key_undeclared(self, tmp_path):
        metrics = write(tmp_path, "pkg/metrics.py", (
            "from collections import namedtuple\n"
            "MetricDef = namedtuple('MetricDef', 'name kind')\n"
            "SEARCH_REPORT_SCHEMA = (MetricDef('n_launches', "
            "'counter'),)\n"))
        write(tmp_path, "pkg/engine.py", (
            "def run(metrics):\n"
            "    metrics.counter('n_launches').inc()\n"
            "    metrics.counter('nope').inc()\n"))
        proj = make_project(tmp_path, metrics_path=metrics)
        r = lint(proj, ["report-key-undeclared"])
        hits = rule_hits(r, "report-key-undeclared")
        assert len(hits) == 1 and "'nope'" in hits[0]["message"]

    def test_docs_stale(self, tmp_path):
        from tools.sstlint import catalog_markdown
        metrics = write(tmp_path, "pkg/metrics.py", (
            "def schema_markdown():\n"
            "    return '## schema\\n| a | b |\\n'\n"))
        spans = write(tmp_path, "pkg/spans.py", (
            "def vocabulary_markdown():\n"
            "    return '## spans\\n| s |\\n'\n"))
        docs = write(tmp_path, "docs/API.md", "# API\nstale text\n")
        proj = make_project(tmp_path, metrics_path=metrics,
                            spans_path=spans, docs_api=docs)
        r = lint(proj, ["docs-stale"])
        # one finding per drifted generated section
        assert sorted(f["key"].rsplit("::", 1)[-1]
                      for f in rule_hits(r, "docs-stale")) == [
            "catalog-section", "schema-section", "spans-section"]
        docs.write_text("# API\n## schema\n| a | b |\nmore\n"
                        "## spans\n| s |\n" + catalog_markdown())
        r2 = lint(proj, ["docs-stale"])
        assert not rule_hits(r2, "docs-stale")


# ---------------------------------------------------------------------------
# config knobs
# ---------------------------------------------------------------------------

_FIXTURE_CONFIG = (
    "import dataclasses\n"
    "@dataclasses.dataclass\n"
    "class TpuConfig:\n"
    "    used_knob: int = 1\n"
    "    dead_knob: int = 2\n")


class TestKnobRules:
    def test_config_knob_unread(self, tmp_path):
        write(tmp_path, "pkg/mesh.py", _FIXTURE_CONFIG)
        write(tmp_path, "pkg/engine.py",
              "def f(config):\n    return config.used_knob\n")
        docs = write(tmp_path, "docs/API.md",
                     "used_knob dead_knob\n")
        proj = make_project(tmp_path, docs_api=docs)
        r = lint(proj, ["config-knob-unread"])
        hits = rule_hits(r, "config-knob-unread")
        assert [f["message"] for f in hits] == \
            ["TpuConfig.dead_knob is never read by the package"]

    def test_config_knob_undocumented(self, tmp_path):
        write(tmp_path, "pkg/mesh.py", _FIXTURE_CONFIG)
        write(tmp_path, "pkg/engine.py",
              "def f(c):\n    return c.used_knob + c.dead_knob\n")
        # the match wants the rendered-signature form (`name=` / `name:`)
        # — prose mentioning "dead_knob settings" must NOT count
        docs = write(tmp_path, "docs/API.md",
                     "TpuConfig(used_knob: int = 1)\n"
                     "prose about dead_knob settings\n")
        proj = make_project(tmp_path, docs_api=docs)
        r = lint(proj, ["config-knob-undocumented"])
        hits = rule_hits(r, "config-knob-undocumented")
        assert len(hits) == 1 and "dead_knob" in hits[0]["message"]

    def test_env_knob_unregistered(self, tmp_path):
        write(tmp_path, "pkg/mesh.py", _FIXTURE_CONFIG)
        write(tmp_path, "pkg/engine.py", (
            "import os\n"
            "def f():\n"
            "    a = os.environ.get('SST_USED_KNOB')\n"
            "    b = os.environ.get('SST_ROGUE')\n"
            "    c = os.environ.get('SST_JUSTIFIED')\n"
            "    return a, b, c\n"))
        # knob-table rows: exact | `VAR` | cells (prose doesn't count)
        readme = write(tmp_path, "README.md",
                       "| `SST_USED_KNOB` | x |\n"
                       "| `SST_JUSTIFIED` | y |\n")
        proj = make_project(
            tmp_path, readme=readme,
            env_field_exceptions={"SST_JUSTIFIED": "test harness"})
        r = lint(proj, ["env-knob-unregistered"])
        syms = {f["message"] for f in
                rule_hits(r, "env-knob-unregistered")}
        # SST_ROGUE: no field AND no README row; others clean
        assert len(syms) == 2
        assert all("SST_ROGUE" in m for m in syms)


# ---------------------------------------------------------------------------
# jit purity
# ---------------------------------------------------------------------------


class TestPurityRules:
    def test_impure_sites_flagged(self, tmp_path):
        write(tmp_path, "pkg/progs.py", (
            "import time, random\n"
            "import jax\n"
            "import numpy as np\n"
            "CAPTURED = np.zeros(4)\n"
            "def impure(x):\n"
            "    t = time.perf_counter()\n"
            "    r = random.random()\n"
            "    y = jax.device_put(x)\n"
            "    CAPTURED[0] = 1.0\n"
            "    return x + t + r + y\n"
            "fn = jax.jit(impure)\n"
            "def pure(x):\n"
            "    return x * 2\n"
            "gn = jax.jit(pure)\n"))
        proj = make_project(tmp_path)
        rules = ["jit-impure-time", "jit-impure-random",
                 "jit-unplaned-upload", "jit-host-mutation"]
        r = lint(proj, rules)
        got = {f["rule"] for f in r["findings"]}
        assert got == set(rules)
        # nothing points at the pure function
        assert all("impure" in f["message"] for f in r["findings"])

    def test_vmap_wrapped_and_one_hop(self, tmp_path):
        write(tmp_path, "pkg/progs.py", (
            "import time\n"
            "import jax\n"
            "def helper(x):\n"
            "    return x + time.time()\n"
            "def outer(x):\n"
            "    return helper(x)\n"
            "fn = jax.jit(jax.vmap(outer))\n"))
        r = lint(make_project(tmp_path), ["jit-impure-time"])
        assert rule_hits(r, "jit-impure-time")


# ---------------------------------------------------------------------------
# hygiene + baseline + CLI
# ---------------------------------------------------------------------------


class TestHygieneBaselineCli:
    def test_gitignore_rule(self, tmp_path):
        write(tmp_path, "pkg/a.py", "x = 1\n")
        proj = make_project(tmp_path)
        r = lint(proj, ["gitignore-bytecode"])
        assert rule_hits(r, "gitignore-bytecode")
        write(tmp_path, ".gitignore", "__pycache__/\n*.pyc\n")
        r2 = lint(proj, ["gitignore-bytecode"])
        assert not rule_hits(r2, "gitignore-bytecode")

    def test_baseline_roundtrip(self, tmp_path):
        write(tmp_path, "pkg/a.py", (
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except:\n"
            "        return None\n"))
        proj = make_project(tmp_path)
        bl = tmp_path / "baseline.json"
        r = run_lint(proj, rules=["bare-except"], baseline_path=bl)
        assert r["n_findings"] == 1 and r["n_baselined"] == 0
        save_baseline(bl, r["_finding_objs"], r["_baseline"])
        entries = load_baseline(bl)
        assert len(entries) == 1
        r2 = run_lint(proj, rules=["bare-except"], baseline_path=bl)
        assert r2["n_findings"] == 0 and r2["n_baselined"] == 1
        # baselines key on symbols, not line numbers: shifting the
        # function down must not un-baseline the finding
        src = (tmp_path / "pkg/a.py").read_text()
        (tmp_path / "pkg/a.py").write_text("# moved\n\n" + src)
        r3 = run_lint(proj, rules=["bare-except"], baseline_path=bl)
        assert r3["n_findings"] == 0 and r3["n_baselined"] == 1

    def test_cli_real_tree_exits_zero(self):
        out = subprocess.run(
            [sys.executable, "-m", "tools.sstlint", "--format", "json",
             "spark_sklearn_tpu/"],
            capture_output=True, text=True, cwd=str(REPO), timeout=180)
        assert out.returncode == 0, out.stdout + out.stderr
        payload = json.loads(out.stdout)
        assert payload["n_findings"] == 0
        assert payload["n_rules"] >= 20

    def test_cli_seeded_violation_exits_nonzero(self, tmp_path):
        write(tmp_path, "pkg/a.py", (
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except:\n"
            "        return None\n"))
        out = subprocess.run(
            [sys.executable, "-m", "tools.sstlint", "--format", "json",
             str(tmp_path / "pkg")],
            capture_output=True, text=True, cwd=str(REPO), timeout=180)
        assert out.returncode == 1
        payload = json.loads(out.stdout)
        assert any(f["rule"] == "bare-except"
                   for f in payload["findings"])

    def test_real_tree_lints_clean_in_process(self):
        r = run_lint(root=REPO)
        assert r["n_findings"] == 0, r["findings"]
        assert r["n_baselined"] == 0, \
            "the committed baseline should stay empty"


# ---------------------------------------------------------------------------
# runtime lock-order recorder (SST_LOCKCHECK)
# ---------------------------------------------------------------------------


class TestLockcheckRuntime:
    def _locks(self):
        from spark_sklearn_tpu.utils.locks import (CheckedLock,
                                                   LockOrderRecorder)
        return CheckedLock, LockOrderRecorder

    def test_inversion_detected(self):
        CheckedLock, LockOrderRecorder = self._locks()
        rec = LockOrderRecorder()
        A = CheckedLock(threading.Lock(), "m.A", rec)
        B = CheckedLock(threading.Lock(), "m.B", rec)

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass

        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
        rep = rec.report()
        assert rep["n_edges"] == 2
        assert len(rep["inversions"]) == 1
        assert set(rep["inversions"][0]["locks"]) == {"m.A", "m.B"}

    def test_consistent_order_clean(self):
        CheckedLock, LockOrderRecorder = self._locks()
        rec = LockOrderRecorder()
        A = CheckedLock(threading.Lock(), "m.A", rec)
        B = CheckedLock(threading.Lock(), "m.B", rec)
        for _ in range(3):
            with A:
                with B:
                    pass
        rep = rec.report()
        assert rep["edges"] == [("m.A", "m.B")]
        assert not rep["inversions"]

    def test_rlock_reentry_records_no_self_edge(self):
        CheckedLock, LockOrderRecorder = self._locks()
        rec = LockOrderRecorder()
        R = CheckedLock(threading.RLock(), "m.R", rec)
        with R:
            with R:
                pass
        rep = rec.report()
        assert rep["n_edges"] == 0 and not rep["inversions"]

    def test_long_hold_recorded(self, monkeypatch):
        monkeypatch.setenv("SST_LOCKCHECK_HOLD_S", "0.01")
        CheckedLock, LockOrderRecorder = self._locks()
        rec = LockOrderRecorder()
        A = CheckedLock(threading.Lock(), "m.A", rec)
        with A:
            time.sleep(0.05)
        rep = rec.report()
        assert rep["long_holds"] and \
            rep["long_holds"][0]["lock"] == "m.A"

    def test_named_lock_factories_honor_env(self, monkeypatch):
        from spark_sklearn_tpu.utils import locks
        monkeypatch.delenv("SST_LOCKCHECK", raising=False)
        assert not isinstance(locks.named_lock("t.x"),
                              locks.CheckedLock)
        monkeypatch.setenv("SST_LOCKCHECK", "1")
        lk = locks.named_lock("t.x")
        assert isinstance(lk, locks.CheckedLock)
        rk = locks.named_rlock("t.y")
        assert isinstance(rk, locks.CheckedLock)

    def test_engine_search_clean_under_lockcheck(self):
        """End-to-end: a real compiled search in a subprocess with
        SST_LOCKCHECK=1 must record zero inversions (and at least the
        plane->totals edge)."""
        code = (
            "import os\n"
            "import numpy as np\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from sklearn.linear_model import LogisticRegression\n"
            "import spark_sklearn_tpu as sst\n"
            "from spark_sklearn_tpu.utils import locks\n"
            "X = np.random.RandomState(0).randn(64, 4)"
            ".astype(np.float32)\n"
            "y = (X[:, 0] > 0).astype(np.int64)\n"
            "cfg = sst.TpuConfig(fault_plan='transient@1,oom@3',\n"
            "                    retry_backoff_s=0.01)\n"
            "gs = sst.GridSearchCV(LogisticRegression(max_iter=5),\n"
            "    {'C': [0.1, 1.0, 10.0]}, cv=2, refit=False,\n"
            "    backend='tpu', config=cfg).fit(X, y)\n"
            "rep = locks.get_recorder().report()\n"
            "assert not rep['inversions'], rep['inversions']\n"
            "print('EDGES', rep['n_edges'])\n")
        env = dict(__import__("os").environ,
                   SST_LOCKCHECK="1", JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, env=env,
                             cwd=str(REPO), timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "EDGES" in out.stdout
